#!/usr/bin/env python
"""Headline benchmark: GPT-2 125M causal-LM training MFU on one chip.

Prints ONE JSON line on stdout:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

``vs_baseline`` is value / 0.4 — the BASELINE.json north-star MFU target
(the reference publishes no numbers of its own; SURVEY.md §6).

Hardened against a flaky accelerator runtime (which zeroed out round 1's
perf evidence): the TPU backend is first probed in a *child process*
with a hard timeout — a hung PJRT client init cannot be interrupted
in-process — and retried with backoff; every phase logs progress to
stderr; any failure still emits the structured JSON line (with an
``error`` object) so the driver always records evidence.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SEQ_LEN = 1024
_BATCH_ENV = os.environ.get("DTT_BENCH_BATCH", "32")
# Headline model config: "mlp" remat drops only the (B, S, 4D) MLP
# hidden tensors — measured on the v5e as the residual class that OOMs
# batch 16/32 (six 1.12 GiB stacked buffers); recompute is wi-matmul +
# gelu, ~+4% step FLOPs, and it unlocks batch 32 (4x the batch-8 r2
# config). Sweeps override via measure(..., remat=False, ...).
HEADLINE_MODEL_KWARGS = {"remat": True, "remat_policy": "mlp"}
# Measured after the headline succeeds (same batch); best result wins.
# Contenders measured after the headline (cheap-to-risky, each in its
# own salvage window). MEASURED r4: the no-remat full-unroll
# hypothesis point ({"remat": False, "scan_unroll": 12}) cannot
# compile inside any reasonable salvage window on this 1-core host
# (>420 s, still in XLA when the timer fired), and the salvage's
# os._exit mid-compile leaves the PJRT client undestroyed — which is
# exactly what wedges the tunnel for the following ~40+ min. A point
# that can only time out and wedge the chip is negative information
# per chip-second, so it is no longer a default; opt in via
#   DTT_BENCH_CONTENDERS='[{"remat": false, "scan_unroll": 12}]'
# Also measured r4: {"scan_unroll": 4} compiled+ran fine and LOST to
# the headline outright (0.249 vs 0.427 MFU after the seq-aware flash
# tiles landed) — a contender with a measured loss is pure chip-window
# waste, so the default list is now empty; the headline config IS the
# tuned winner of the r4 matrix. Opt contenders back in via
# DTT_BENCH_CONTENDERS when there is a new hypothesis to race.
CONTENDER_MODEL_KWARGS: list = []


def _contenders() -> list:
    """Contender list, env-overridable. Parsed lazily (not at import)
    so a malformed DTT_BENCH_CONTENDERS can't crash tools that merely
    import bench for its measurement core, and falls back to the
    default with a stderr note naming the variable — a typo'd env var
    must not forfeit a scarce healthy-chip window."""
    raw = os.environ.get("DTT_BENCH_CONTENDERS")
    if not raw:
        return CONTENDER_MODEL_KWARGS
    try:
        parsed = json.loads(raw)
        if not isinstance(parsed, list):
            raise ValueError("expected a JSON list of kwargs objects")
        return parsed
    except ValueError as e:
        print(f"[bench] malformed DTT_BENCH_CONTENDERS ignored ({e}); "
              f"using default {CONTENDER_MODEL_KWARGS}", file=sys.stderr)
        return CONTENDER_MODEL_KWARGS
WARMUP_STEPS = 3
TIMED_STEPS = 20
PROBE_TIMEOUT_S = int(os.environ.get("DTT_BENCH_PROBE_TIMEOUT", "120"))
PROBE_ATTEMPTS = int(os.environ.get("DTT_BENCH_PROBE_ATTEMPTS", "10"))
PROBE_BACKOFF_S = float(os.environ.get("DTT_BENCH_PROBE_BACKOFF", "90"))
# Hard ceiling on TOTAL probe wall time. Round 3's lesson: per-attempt
# limits alone let the loop run ~35 min, which outlasted the driver's
# own kill budget — the process died from outside (rc=124) and the
# "always emit the evidence JSON" guarantee never fired. The budget
# must stay well under any plausible driver timeout, and the failure
# line is emitted BEFORE exhaustion, by a daemon timer armed up front.
PROBE_TOTAL_BUDGET_S = float(
    os.environ.get("DTT_BENCH_PROBE_TOTAL_BUDGET", "480"))
# Measurement deadline. Probe budget (480) + this must stay inside the
# driver's observed ~35 min kill window so the parent's failure line
# always beats an external kill: 480 + 1500 + slack < 2100.
RUN_TIMEOUT_S = int(os.environ.get("DTT_BENCH_RUN_TIMEOUT", "1500"))


def _child_mode() -> bool:
    """True when this process is the measurement CHILD of
    parent_main(). Same "", "0" convention as every other DTT_ knob —
    DTT_BENCH_CHILD=0 must mean parent mode, not a truthy surprise."""
    return os.environ.get("DTT_BENCH_CHILD", "0") not in ("", "0")


def _phase(name: str, **kv) -> None:
    extra = " ".join(f"{k}={v}" for k, v in kv.items())
    print(f"[bench] phase={name} {extra}".rstrip(), file=sys.stderr,
          flush=True)


# Committed ledger of in-session measurements. A wedged chip at the
# driver's end-of-round run must not erase a number that WAS measured
# on real hardware earlier (r3: an 8h wedge zeroed the round even
# though the code had been measured that session) — the failure record
# carries the newest ledger entry, clearly labeled as prior evidence.
EVIDENCE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "evidence")


def _latest_evidence() -> dict | None:
    """Newest ledger entry by its recorded measurement time (filename
    order is meaningless across committed seeds + runtime writes).

    Only entries in the bench RESULT schema (``metric`` str + numeric
    ``value``) are eligible: the ledger also holds free-form session
    notes, and in r4 a 2.4 KB prose entry won the recency race, was
    embedded verbatim in the failure record, and pushed the emitted
    JSON line past the driver's 2,000-char tail capture — zeroing the
    round's official number (BENCH_r04 ``parsed: null``)."""
    best = None
    try:
        names = os.listdir(EVIDENCE_DIR)
    except OSError:
        return None
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(EVIDENCE_DIR, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict):
            continue
        value = rec.get("value")
        if not isinstance(rec.get("metric"), str) or \
                isinstance(value, bool) or \
                not isinstance(value, (int, float)):
            continue
        if best is None or rec.get("measured_at_unix", 0) > \
                best.get("measured_at_unix", 0):
            best = rec
    return best


def _compact_evidence(rec: dict) -> dict:
    """Strip a ledger entry to the fixed set of keys a failure record
    may embed, with every string value bounded — the ledger holds
    hand-written files too, and an oversized value in a KEPT key must
    shrink rather than force the shed cascade to drop the prior."""
    def _bound(v):
        # Strings truncate; numbers/bools pass; anything else (a list,
        # a nested dict) is dropped — an unbounded non-string in a
        # kept key must not force the shed cascade to drop the prior.
        if isinstance(v, str):
            return v[:80]
        if isinstance(v, (int, float, bool)) or v is None:
            return v
        return None

    out = {k: b for k in
           ("metric", "value", "unit", "vs_baseline", "measured_at_unix")
           if k in rec and (b := _bound(rec[k])) is not None}
    detail = rec.get("detail")
    if isinstance(detail, dict):
        out["detail"] = {k: b for k in
                         ("device_kind", "batch", "seq_len",
                          "tokens_per_sec_per_chip", "step_time_ms")
                         if k in detail
                         and (b := _bound(detail[k])) is not None}
    return out


def record_evidence(result: dict) -> None:
    """Persist a successful measurement to the committed ledger
    (best-effort; measurement must never fail on a ledger write).

    Only results carrying a hardware identity are recorded: unit tests
    drive main() with stubbed measure() functions whose results have no
    ``detail.device_kind``, and a stub result in the ledger would later
    surface as fake "prior hardware evidence" in a failure record
    (caught in review — it had already happened). Atomic replace so an
    external kill mid-write can't destroy the previous good entry."""
    if not isinstance(result, dict) or not result.get(
            "detail", {}).get("device_kind"):
        return
    try:
        os.makedirs(EVIDENCE_DIR, exist_ok=True)
        path = os.path.join(EVIDENCE_DIR, "last_good.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({**result,
                       "measured_at_unix": int(time.time())}, f,
                      indent=1)
        os.replace(tmp, path)
    except OSError:
        pass


# Budget for the single emitted JSON line. The driver records only the
# last 2,000 chars of output; stderr phase lines may share that tail,
# so the line itself stays well under it.
MAX_LINE_BYTES = 1500


def _failure_record(stage: str, message: str) -> dict:
    rec = {
        "metric": "gpt2_125m_train_mfu_single_chip",
        "value": 0.0,
        "unit": "mfu",
        "vs_baseline": 0.0,
        "error": {"stage": stage, "message": message[:500]},
    }
    prior = _latest_evidence()
    if prior is not None:
        rec["last_measured"] = _compact_evidence(prior)
    # Enforce the line budget against the SERIALIZED length (non-ASCII
    # chars escape to up to 12 chars under json.dumps, so character
    # truncation alone is not enough). Shed the message FIRST — it is
    # the compressible part; the prior evidence is the part worth
    # keeping ("a wedged chip must not erase a number that WAS
    # measured"). Only if even an empty message overflows does the
    # prior get reduced and finally dropped — with the compact prior at
    # ~300 bytes and the fixed keys ~200, that path is unreachable in
    # practice but keeps the parse guarantee unconditional.
    while len(json.dumps(rec)) > MAX_LINE_BYTES and \
            rec["error"]["message"]:
        msg = rec["error"]["message"]
        rec["error"]["message"] = msg[:len(msg) // 2]
    if len(json.dumps(rec)) > MAX_LINE_BYTES:
        rec.get("last_measured", {}).pop("detail", None)
    if len(json.dumps(rec)) > MAX_LINE_BYTES:
        rec.pop("last_measured", None)
    return rec


def _fail(stage: str, message: str) -> None:
    print(json.dumps(_failure_record(stage, message)))
    sys.exit(1)


def _write_postmortem(reason: str) -> str:
    """Best-effort postmortem bundle (all-thread stacks, per-device
    memory_stats) under benchmarks/state/postmortem/ — the artifact
    BENCH_r05's "backend unresponsive" exit was missing. Called from
    the hang/budget timer threads, so it must never raise and must
    not initialize a backend (telemetry.watchdog only touches jax if
    it is already imported)."""
    try:
        from distributed_training_tpu.telemetry.watchdog import (
            write_postmortem)
        path = write_postmortem(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "benchmarks", "state", "postmortem"),
            reason)
        _phase("postmortem_written", path=path)
        return path
    except Exception as e:  # noqa: BLE001 — evidence line must survive
        _phase("postmortem_failed", error=f"{type(e).__name__}")
        return ""


def probe_backend() -> None:
    """Confirm the accelerator backend answers before committing this
    process to it. PJRT client creation can hang indefinitely when the
    runtime is sick (observed: ``make_c_api_client`` blocked >5 min), and
    once the main process is stuck in that C call no signal handler runs
    — so the probe happens in a child we can kill."""
    import threading

    # Armed BEFORE the first probe: even if a probe subprocess call
    # itself wedges past its timeout (or the loop miscounts), the
    # evidence line still goes out inside the budget. os._exit because
    # the main thread may be blocked in an uninterruptible wait.
    def _budget_fire():
        _phase("probe_budget_expired", budget_s=PROBE_TOTAL_BUDGET_S)
        _write_postmortem(
            "probe budget expired: accelerator backend unresponsive "
            f"for {PROBE_TOTAL_BUDGET_S}s")
        print(json.dumps(_failure_record(
            "probe_backend",
            "accelerator backend unresponsive; total probe budget "
            f"{PROBE_TOTAL_BUDGET_S}s expired")), flush=True)
        os._exit(1)

    budget_timer = threading.Timer(PROBE_TOTAL_BUDGET_S, _budget_fire)
    budget_timer.daemon = True
    budget_timer.start()
    t_start = time.monotonic()

    def _remaining() -> float:
        return PROBE_TOTAL_BUDGET_S - (time.monotonic() - t_start)

    code = ("import jax; d = jax.devices(); "
            "import jax.numpy as jnp; "
            "x = (jnp.ones((256, 256)) @ jnp.ones((256, 256))).sum(); "
            "x.block_until_ready(); print(d[0].device_kind)")
    probes_run = 0
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        # Leave ~10s headroom so the subprocess timeout always trips
        # before the budget timer would hard-exit mid-probe. The break
        # gates on REMAINING budget, not the configured timeout — a
        # short DTT_BENCH_PROBE_TIMEOUT must shorten probes, not skip
        # them entirely.
        if _remaining() < 15:
            break
        per_try = max(1.0, min(PROBE_TIMEOUT_S, _remaining() - 10))
        probes_run += 1
        _phase("probe_backend", attempt=attempt,
               timeout_s=round(per_try))
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=per_try)
            if out.returncode == 0:
                kind = out.stdout.strip().splitlines()[-1]
                _phase("probe_backend_ok", device_kind=repr(kind))
                budget_timer.cancel()
                return
            detail = (out.stderr or out.stdout).strip()[-300:]
            _phase("probe_backend_error", rc=out.returncode,
                   detail=repr(detail))
        except subprocess.TimeoutExpired:
            _phase("probe_backend_timeout")
        if attempt < PROBE_ATTEMPTS and _remaining() > PROBE_BACKOFF_S + 15:
            _phase("probe_backoff", sleep_s=PROBE_BACKOFF_S)
            time.sleep(PROBE_BACKOFF_S)
    budget_timer.cancel()
    _fail("probe_backend",
          f"accelerator backend unresponsive after {probes_run} probes "
          f"within {round(time.monotonic() - t_start)}s "
          f"(budget {PROBE_TOTAL_BUDGET_S}s)")


def _arm_watchdog():
    """Emit the failure JSON and hard-exit if the measurement wedges
    after a healthy probe (device lost mid-run). Returns the timer so
    the caller cancels it on success (a late fire would print a second
    JSON line and fail a successful run)."""
    import threading

    def fire():
        _phase("watchdog_fired", budget_s=RUN_TIMEOUT_S)
        # The stacks show WHERE the measurement wedged (compile vs.
        # dispatch vs. a blocked PJRT call) — the attribution every
        # previous round's timeout message lacked.
        _write_postmortem(f"bench run exceeded {RUN_TIMEOUT_S}s")
        print(json.dumps(_failure_record(
            "watchdog", f"run exceeded {RUN_TIMEOUT_S}s")), flush=True)
        os._exit(1)

    t = threading.Timer(RUN_TIMEOUT_S, fire)
    t.daemon = True
    t.start()
    return t


# Per-contender salvage window. Two contenders each get one, so the
# worst case adds 2x this to the run — 420s keeps the whole bench
# comfortably inside the driver's observed kill budget (~35 min).
CONTENDER_TIMEOUT_S = int(os.environ.get("DTT_BENCH_CONTENDER_TIMEOUT",
                                         "420"))


def _arm_salvage(holder: dict):
    """Timer that emits an already-measured result and exits CLEANLY
    if a contender run wedges the process — the opposite failure
    semantics of _arm_watchdog (which zeroes the round). ``holder``
    is a mutable {"result": ...} cell read at fire time, so a
    contender that improved the best before a later one wedged still
    gets reported (ADVICE r3: a snapshot here discarded wins)."""
    import threading

    def fire():
        _phase("salvage_fired", budget_s=CONTENDER_TIMEOUT_S)
        record_evidence(holder["result"])
        print(json.dumps(holder["result"]), flush=True)
        os._exit(0)

    t = threading.Timer(CONTENDER_TIMEOUT_S, fire)
    t.daemon = True
    t.start()
    return t


def measure(batch_size: int, seq_len: int = SEQ_LEN,
            warmup_steps: int = WARMUP_STEPS,
            timed_steps: int = TIMED_STEPS,
            phase=_phase, **model_kwargs) -> dict:
    """The measurement core (shared with benchmarks/sweep_mfu.py so the
    sweep times exactly what the bench reports): build the gpt2_125m
    trainer at ``batch_size``, warm up, time ``timed_steps`` steps, and
    return mfu/throughput detail."""
    import jax
    import numpy as np

    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models import build_model
    from distributed_training_tpu.runtime import initialize_runtime
    from distributed_training_tpu.train.trainer import Trainer
    from distributed_training_tpu.utils.metrics import peak_flops_per_chip

    cfg = Config()
    cfg.train.batch_size = batch_size
    cfg.train.optimizer = "adamw"
    cfg.train.learning_rate = 6e-4
    cfg.train.dtype = "bfloat16"
    cfg.train.log_every = 0
    cfg.train.parallel_strategy = "ddp"

    model_kwargs = {**HEADLINE_MODEL_KWARGS, **model_kwargs}
    phase("init_runtime")
    rt = initialize_runtime(cfg)
    phase("build_model", batch=batch_size, seq_len=seq_len,
          **model_kwargs)
    model = build_model("gpt2_125m", dtype="bfloat16", **model_kwargs)
    ds = SyntheticLMDataset(
        size=max(64, batch_size * rt.data_shard_count),
        seq_len=seq_len, vocab_size=50257, seed=0)
    loader = ShardedDataLoader(ds, rt, batch_size=batch_size,
                               shuffle=False)
    trainer = Trainer(cfg, rt, model, loader)
    batch = next(iter(loader.epoch(0)))

    phase("compile_and_warmup", steps=warmup_steps)
    t_compile = time.perf_counter()
    for _ in range(warmup_steps):
        metrics = trainer.train_step(batch)
    jax.block_until_ready(metrics["loss"])
    phase("warmup_done",
          seconds=round(time.perf_counter() - t_compile, 1))

    phase("measure", steps=timed_steps)
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        metrics = trainer.train_step(batch)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    steps_per_sec = timed_steps / dt
    tokens_per_sec = steps_per_sec * loader.global_batch * seq_len
    mfu = (tokens_per_sec * model.flops_per_token(seq_len)
           / rt.num_devices / peak_flops_per_chip(rt.device_kind))
    return {
        "mfu": float(mfu),
        "tokens_per_sec_per_chip": round(
            tokens_per_sec / rt.num_devices, 1),
        "step_time_ms": round(1000 * dt / timed_steps, 2),
        "batch": batch_size,
        "seq_len": seq_len,
        "device_kind": rt.device_kind,
        "num_devices": rt.num_devices,
        "loss_finite": bool(np.isfinite(float(metrics["loss"]))),
        # Effective (merged) kwargs — the model actually measured, so
        # sweep rows are never confounded by the headline defaults.
        "model_kwargs": dict(model_kwargs),
    }


def run_sweep_point(batch: int, timed_steps: int = 10,
                    warmup_steps: int = WARMUP_STEPS,
                    seq_len: int = SEQ_LEN, **model_kwargs) -> dict:
    """One sweep measurement as a JSON-ready dict — shared by
    benchmarks/sweep_mfu.py and benchmarks/tune_headline.py so every
    sweep row is produced (and labeled) identically. Errors become an
    ``error`` row instead of raising; the matrix continues."""
    t0 = time.perf_counter()
    try:
        m = measure(batch, seq_len=seq_len, timed_steps=timed_steps,
                    warmup_steps=warmup_steps,
                    phase=lambda *a, **k: None, **model_kwargs)
        m["mfu"] = round(m["mfu"], 4)
    except Exception as e:  # noqa: BLE001 — sweeps survive OOM points
        # Record the EFFECTIVE kwargs (same merge measure() applies) so
        # an OOM row for {} reads as the headline config it actually
        # ran, not the bare default (ADVICE r3).
        m = {"batch": batch, "seq_len": seq_len,
             "model_kwargs": {**HEADLINE_MODEL_KWARGS, **model_kwargs},
             "error": f"{type(e).__name__}: {e}"[:300]}
    m["point_wall_s"] = round(time.perf_counter() - t0, 1)
    return m


def _resolve_batch() -> int:
    """DTT_BENCH_BATCH: an int, or 'auto' = largest power-of-two batch
    whose estimated footprint fits the local chip's HBM
    (utils/memory.py — VERDICT r2 item 1a)."""
    if _BATCH_ENV != "auto":
        return int(_BATCH_ENV)
    import jax

    from distributed_training_tpu.models.transformer import (
        PRESETS, TransformerConfig)
    from distributed_training_tpu.utils.memory import (
        HBM_GIB, estimate_transformer_memory)
    kind = jax.devices()[0].device_kind.lower()
    if not any(k in kind for k in HBM_GIB):
        return 8
    key = next(k for k in HBM_GIB if k in kind)
    # Same merge direction as measure(): headline kwargs override the
    # preset (dict merge, so a shared key overrides instead of raising).
    cfg = TransformerConfig(dtype="bfloat16",
                            **{**PRESETS["gpt2_125m"],
                               **HEADLINE_MODEL_KWARGS})
    batch = 8  # floor — smallest batch the bench will attempt
    for cand in (8, 16, 32, 64, 128, 256, 512):
        if estimate_transformer_memory(
                cfg, batch_per_chip=cand, seq_len=SEQ_LEN).fits(key):
            batch = cand
        else:
            break
    if batch == 8 and not estimate_transformer_memory(
            cfg, batch_per_chip=8, seq_len=SEQ_LEN).fits(key):
        _phase("auto_batch_floor_may_not_fit", batch=batch)
    _phase("auto_batch", batch=batch)
    return batch


def _is_oom(e: Exception) -> bool:
    """Real device-OOM signatures only. The previous bare "allocat"
    substring matched any message mentioning "allocate" (e.g. a host
    allocation hiccup), silently rerouting deterministic failures into
    batch-halving and burning watchdog budget (ADVICE r3)."""
    msg = str(e).lower()
    return ("resource_exhausted" in msg
            or "out of memory" in msg
            or "ran out of memory" in msg
            or "failed to allocate" in msg
            or "allocation failure" in msg
            or ("hbm" in msg and "exceed" in msg))


# Patterns for background chip users this repo may leave running: the
# watcher loop, the harvest orchestrator + its python phases, and the
# watcher's in-flight probe child (matched by its distinctive matmul
# line — a WEDGED probe child ignores SIGTERM, hence SIGKILL below).
_CLAIM_PATTERNS = ("probe_loop.sh", "chip_session.sh",
                   "tune_headline.py", "bench_1b_single_chip.py",
                   "profile_step.py", "jnp.ones((512,512)")


def _claim_chip() -> None:
    """Stop any background chip users this repo may have left running:
    the bench is the round's scored evidence and a second PJRT client
    blocking on the tunnel — or a timeout-kill against one — is
    exactly how the backend wedges. Suppressed by DTT_BENCH_NO_CLAIM=1
    — set by chip_session.sh, whose OWN ancestors (probe_loop →
    chip_session → this process) would otherwise be killed, and by the
    test suite (a unit test must not pkill live host processes).
    After the kills, waits (bounded) for the targets to actually exit
    so probe_backend doesn't race a dying client for the tunnel."""
    if os.environ.get("DTT_BENCH_NO_CLAIM"):
        return
    for pattern in _CLAIM_PATTERNS:
        try:
            subprocess.run(["pkill", "-9", "-f", pattern],
                           capture_output=True, timeout=10)
        except Exception as e:  # noqa: BLE001 — never let cleanup
            # kill us; but say so (DTT002: no silent swallows).
            print(f"[bench] claim-chip pkill '{pattern}' failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        try:
            alive = any(
                subprocess.run(["pgrep", "-f", p],
                               capture_output=True,
                               timeout=5).returncode == 0
                for p in _CLAIM_PATTERNS)
        except Exception:  # noqa: BLE001
            return
        if not alive:
            return
        time.sleep(1)


def main() -> None:
    """Measure and print the evidence line (in-process).

    Invoked directly by the unit tests (with measure/_resolve_batch
    stubbed) and as the CHILD of parent_main(). In child mode
    (DTT_BENCH_CHILD=1) the probe/claim/watchdog are all skipped — the
    parent owns the deadline, and crucially the child must never
    os._exit itself mid-XLA-compile: an abrupt exit with a live PJRT
    client is exactly what wedges the axon tunnel for ~40 min
    (measured r3/r4)."""
    child_mode = _child_mode()
    cancel_pm = None
    if child_mode:
        # The abandoned-child protocol means nobody kills this
        # process — but if it outlives the parent's deadline, a
        # faulthandler stack dump (no exit, no PJRT disruption) is
        # scheduled so the orphan's state is on disk when someone
        # later asks what it was doing.
        try:
            from distributed_training_tpu.telemetry.watchdog import (
                arm_process_watchdog)
            cancel_pm = arm_process_watchdog(
                RUN_TIMEOUT_S,
                os.path.join(CHILD_LOG_DIR, "postmortem"),
                f"bench child still running at the parent's "
                f"{RUN_TIMEOUT_S}s deadline (abandoned-child path)")
        except Exception as e:  # noqa: BLE001 — the bench must run
            # even without its safety net; but say which net is gone
            # (DTT002: no silent swallows).
            print(f"[bench] child postmortem watchdog not armed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    if not child_mode:
        _claim_chip()
        probe_backend()
    watchdog = _arm_watchdog() if not child_mode else None
    try:
        batch = _resolve_batch()
    except Exception as e:  # noqa: BLE001 — evidence line must survive
        if watchdog:
            watchdog.cancel()
        _fail("resolve_batch", f"{type(e).__name__}: {e}")
    try:
        while True:
            try:
                m = measure(batch)
                break
            except Exception as e:  # noqa: BLE001
                _phase("measure_failed", batch=batch,
                       error=f"{type(e).__name__}")
                # OOM degrades to a halved batch (a smaller number
                # beats zeroing the round's perf evidence; floor 4).
                # Anything else is deterministic — retrying would just
                # burn the watchdog budget and mask the real error.
                if not _is_oom(e) or batch <= 4:
                    _fail("measure", f"{type(e).__name__}: {e}")
                batch //= 2
                _phase("retry_smaller_batch", batch=batch)
    finally:
        if watchdog:
            watchdog.cancel()

    def _result(mm: dict) -> dict:
        mm = dict(mm)
        mfu = mm.pop("mfu")
        return {
            "metric": "gpt2_125m_train_mfu_single_chip",
            "value": round(mfu, 4),
            "unit": "mfu",
            "vs_baseline": round(mfu / 0.4, 4),
            "detail": mm,
        }

    # The headline config succeeded; also measure the contender
    # configs at the same batch and report the best. Insurance for an
    # untunable round (flaky chip): the driver's single run still
    # picks the winner between the committed candidates. Contender
    # failures only forfeit the comparison, never the evidence line —
    # a salvage watchdog emits the ALREADY-VALID headline result if a
    # contender wedges (the main watchdog would have zeroed it), and a
    # contender must be loss-finite to win (a NaN run can be fast).
    best = {"result": _result(m)}
    # Ledger write the moment the headline exists: a contender that
    # hard-crashes the process (native abort, no salvage window) must
    # not take the already-measured number with it.
    record_evidence(best["result"])
    for extra in _contenders():
        # Per-contender salvage window: a slow/wedging contender must
        # not consume the shared budget and silently skip later ones.
        # In child mode the parent owns the deadline AND the headline
        # is already ledgered — an in-child os._exit could fire
        # mid-compile and wedge the tunnel, so no timer is armed.
        salvage = _arm_salvage(best) if not _child_mode() else None
        try:
            _phase("contender", batch=batch, **extra)
            cand = measure(batch, **extra)
            if cand.get("loss_finite") and cand["mfu"] > m["mfu"]:
                m = cand
                best["result"] = _result(m)
        except Exception as e:  # noqa: BLE001
            _phase("contender_failed", error=f"{type(e).__name__}")
        finally:
            if salvage:
                salvage.cancel()
    final = _result(m)
    record_evidence(final)
    if cancel_pm is not None:
        cancel_pm()
    print(json.dumps(final))


# Where the measurement child writes its stdout/stderr. Files, not
# inherited pipes: an abandoned child that inherited the parent's
# stdout would keep the driver's capture pipe open — the driver would
# block on the "finished" bench until the child exited.
CHILD_LOG_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "benchmarks", "state")

# Swappable for tests (a stub child simulates success/failure/hang
# without a real accelerator); production value re-invokes this file,
# which DTT_BENCH_CHILD routes into main().
_CHILD_ARGV = [sys.executable, os.path.abspath(__file__)]


def parent_main() -> None:
    """Wedge-proof driver entrypoint: this process NEVER creates a
    PJRT client. The measurement runs in a child; on deadline the
    child is ABANDONED, not killed — killing a process mid-XLA-compile
    leaves the axon tunnel wedged for ~40 min (the r3/r4 failure
    mode), while an abandoned child finishes its compile, destroys its
    client cleanly, and still ledgers its result for the NEXT failure
    record via record_evidence. The parent emits the (compact,
    always-parseable) evidence line either way.

    A persistent XLA compilation cache (JAX_COMPILATION_CACHE_DIR) is
    threaded to the child so any compile the child completes — even
    after abandonment — is banked: the next invocation replays it from
    cache instead of re-paying the compile that caused the deadline."""
    _claim_chip()
    probe_backend()
    os.makedirs(CHILD_LOG_DIR, exist_ok=True)
    out_path = os.path.join(CHILD_LOG_DIR, "bench_child.out")
    err_path = os.path.join(CHILD_LOG_DIR, "bench_child.log")
    env = dict(os.environ)
    env["DTT_BENCH_CHILD"] = "1"
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(CHILD_LOG_DIR, "xla_cache"))
    with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
        child = subprocess.Popen(_CHILD_ARGV, stdout=out_f,
                                 stderr=err_f, env=env)
    _phase("child_started", pid=child.pid, log=err_path)
    deadline = time.monotonic() + RUN_TIMEOUT_S
    last_echo = 0
    while time.monotonic() < deadline:
        rc = child.poll()
        # Mirror the child's phase lines so the driver's stderr shows
        # live progress (tail only what's new).
        try:
            # errors="replace": the echo races the child's writes, and
            # a multi-byte UTF-8 character torn at the read boundary
            # must degrade to a replacement char, not kill the parent
            # (whose whole job is the always-parseable evidence line).
            with open(err_path, errors="replace") as f:
                f.seek(last_echo)
                chunk = f.read()
                last_echo = f.tell()
            if chunk:
                sys.stderr.write(chunk)
                sys.stderr.flush()
        except OSError:
            pass
        if rc is not None:
            break
        time.sleep(0.5)
    rc = child.poll()
    if rc is None:
        _phase("deadline_abandon_child", pid=child.pid,
               budget_s=RUN_TIMEOUT_S)
        # rc=124, not 1: the abandoned child still OWNS the chip, and
        # callers (chip_session.sh phase_or_stop) use 124 as the
        # "stop launching TPU work" signal — a generic failure rc
        # would let the session start a second process against the
        # tunnel the orphan holds.
        print(json.dumps(_failure_record(
            "measure_deadline",
            f"measurement exceeded {RUN_TIMEOUT_S}s; child "
            f"pid={child.pid} left to finish (a mid-compile kill "
            "would wedge the accelerator tunnel) — its result, if "
            "any, lands in the evidence ledger")))
        sys.exit(124)
    # Propagate the child's own evidence line verbatim when it printed
    # one — on failure it carries the precise stage and the compact
    # last-measured prior (richer than anything the parent could
    # synthesize). Only a child that died with no line at all gets a
    # parent-synthesized failure record.
    try:
        with open(out_path, errors="replace") as f:
            lines = [ln.strip() for ln in f if ln.strip()]
    except OSError:
        lines = []
    if lines:
        try:
            json.loads(lines[-1])
        except ValueError:
            pass
        else:
            print(lines[-1])
            if rc == 0:
                return
            sys.exit(1)
    tail = ""
    try:
        with open(err_path, errors="replace") as f:
            tail = f.read()[-300:]
    except OSError:
        pass
    _fail("measure_child", f"child rc={rc}; stderr tail: {tail}")


if __name__ == "__main__":
    if _child_mode():
        main()
    else:
        parent_main()
