#!/usr/bin/env python
"""Headline benchmark: GPT-2 125M causal-LM training MFU on one chip.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

``vs_baseline`` is value / 0.4 — the BASELINE.json north-star MFU target
(the reference publishes no numbers of its own; SURVEY.md §6).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SEQ_LEN = 1024
BATCH = 8
WARMUP_STEPS = 3
TIMED_STEPS = 10


def main() -> None:
    import jax
    import numpy as np

    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models import build_model
    from distributed_training_tpu.runtime import initialize_runtime
    from distributed_training_tpu.utils.metrics import peak_flops_per_chip

    cfg = Config()
    cfg.train.batch_size = BATCH
    cfg.train.optimizer = "adamw"
    cfg.train.learning_rate = 6e-4
    cfg.train.dtype = "bfloat16"
    cfg.train.log_every = 0
    cfg.train.parallel_strategy = "ddp"

    rt = initialize_runtime(cfg)
    model = build_model("gpt2_125m", dtype="bfloat16")
    ds = SyntheticLMDataset(size=max(64, BATCH * rt.data_shard_count),
                            seq_len=SEQ_LEN, vocab_size=50257, seed=0)
    loader = ShardedDataLoader(ds, rt, batch_size=BATCH, shuffle=False)

    from distributed_training_tpu.train.trainer import Trainer
    trainer = Trainer(cfg, rt, model, loader)

    batches = list(loader.epoch(0))
    batch = batches[0]

    for _ in range(WARMUP_STEPS):
        metrics = trainer.train_step(batch)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        metrics = trainer.train_step(batch)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    steps_per_sec = TIMED_STEPS / dt
    tokens_per_step = loader.global_batch * SEQ_LEN
    tokens_per_sec = steps_per_sec * tokens_per_step
    flops_per_token = model.flops_per_token(SEQ_LEN)
    model_flops_per_sec_per_chip = (tokens_per_sec * flops_per_token
                                    / rt.num_devices)
    mfu = model_flops_per_sec_per_chip / peak_flops_per_chip(
        rt.device_kind)

    result = {
        "metric": "gpt2_125m_train_mfu_single_chip",
        "value": round(float(mfu), 4),
        "unit": "mfu",
        "vs_baseline": round(float(mfu) / 0.4, 4),
        "detail": {
            "tokens_per_sec_per_chip": round(
                tokens_per_sec / rt.num_devices, 1),
            "step_time_ms": round(1000 * dt / TIMED_STEPS, 2),
            "device_kind": rt.device_kind,
            "num_devices": rt.num_devices,
            "loss_finite": bool(np.isfinite(float(metrics["loss"]))),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
