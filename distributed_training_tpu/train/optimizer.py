"""Optimizer construction (optax).

Parity baseline: plain SGD at ``cfg.learning_rate``
(reference: src/distributed_trainer.py:200, conf/train/default.yaml:6),
extended with the knobs the BASELINE.json transformer configs need
(AdamW, warmup+cosine schedule, global-norm clipping). Unlike the
reference — which builds the optimizer against pre-FSDP-wrap params
(SURVEY.md §8 B4) — optimizer state here is born sharded: the trainer
jits ``optimizer.init`` with the strategy's output shardings.
"""

from __future__ import annotations

import optax

from distributed_training_tpu.config import TrainConfig


def build_schedule(cfg: TrainConfig, total_steps: int):
    base = cfg.learning_rate
    if cfg.lr_schedule == "constant":
        sched = optax.constant_schedule(base)
    elif cfg.lr_schedule == "cosine":
        decay_steps = max(total_steps - cfg.warmup_steps, 1)
        sched = optax.cosine_decay_schedule(
            base, decay_steps=decay_steps, alpha=0.1)
    else:
        raise ValueError(f"unknown lr_schedule '{cfg.lr_schedule}'")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, base, cfg.warmup_steps)
        sched = optax.join_schedules([warmup, sched], [cfg.warmup_steps])
    return sched


# Leaf names that never decay regardless of rank: the Transformer
# stacks per-layer params with a leading L dim, so its LN scales/biases
# are (L, D) and MLP biases (L, F) — a bare ndim>=2 test would decay
# them, silently violating the documented "matrices" convention.
_NO_DECAY_KEYS = frozenset({"b", "bi", "bo", "bias", "scale"})


def _matrices_mask(params):
    """Decay only matmul-participating params: biases and LayerNorm
    scales/offsets are excluded (the standard transformer convention;
    embeddings, being matrices, do decay under this heuristic). A leaf
    decays iff it is >=2-D AND its key is not a bias/scale name —
    name-aware because stacked per-layer 1-D params carry a leading
    layer dim (pinned vs torch in tests/test_torch_parity.py)."""
    import jax

    def decide(path, p):
        last = path[-1]
        key = getattr(last, "key", None) or getattr(last, "name", "")
        return (getattr(p, "ndim", 0) >= 2
                and str(key) not in _NO_DECAY_KEYS)

    return jax.tree_util.tree_map_with_path(decide, params)


def build_optimizer(cfg: TrainConfig,
                    total_steps: int) -> optax.GradientTransformation:
    if cfg.decay_mask not in ("all", "matrices"):
        raise ValueError(f"unknown decay_mask '{cfg.decay_mask}' "
                         "(expected 'all' or 'matrices')")
    sched = build_schedule(cfg, total_steps)
    if cfg.optimizer == "sgd":
        core = optax.sgd(sched)
    elif cfg.optimizer == "adamw":
        # decay_mask="all" reproduces torch.optim.AdamW's default
        # (decays every param — pinned by tests/test_torch_parity.py);
        # "matrices" is the transformer-training convention.
        mask = _matrices_mask if cfg.decay_mask == "matrices" else None
        core = optax.adamw(sched, b1=cfg.b1, b2=cfg.b2,
                           weight_decay=cfg.weight_decay, mask=mask)
    elif cfg.optimizer == "adafactor":
        # TPU-idiomatic memory-lean choice for the largest FSDP
        # configs: factored second moment ≈ (rows+cols) state per
        # matrix instead of Adam's 2x full-size fp32 moments.
        mask = _matrices_mask if cfg.decay_mask == "matrices" else None
        core = optax.adafactor(sched,
                               weight_decay_rate=(cfg.weight_decay
                                                  or None),
                               weight_decay_mask=mask)
    else:
        raise ValueError(f"unknown optimizer '{cfg.optimizer}'")
    parts = []
    if cfg.grad_clip_norm and cfg.grad_clip_norm > 0:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    parts.append(core)
    return optax.chain(*parts)
