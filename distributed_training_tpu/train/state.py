"""Train state: a transparent pytree, born sharded.

``{"params", "opt_state", "step"}`` — the unit the checkpoint layer
saves/restores (superset of the reference's ``{"MODEL_STATE",
"EPOCHS_RUN"}`` snapshot, src/distributed_trainer.py:88-91, which dropped
optimizer state entirely; SURVEY.md §5.4).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_tpu.parallel.strategy import ShardingStrategy


def state_specs(strategy: ShardingStrategy,
                optimizer: optax.GradientTransformation,
                param_shapes: Any, logical_axes: Any = None,
                opt_shapes: Any = None) -> dict:
    """PartitionSpecs for the full train state.

    Optimizer-state leaves that mirror params (Adam moments, momentum)
    inherit the param's spec via ``optax.tree_map_params``; scalar/other
    leaves replicate. ``opt_shapes`` may be precomputed by the caller
    (the trainer shares one abstract trace with state_shardings).
    """
    param_specs = strategy.specs_for_tree(param_shapes, logical_axes)
    # Param-shaped optimizer leaves get the strategy's OPT layout —
    # identical to the param layout except under ZeRO-1, where moments
    # shard over the data axes while params stay replicated.
    opt_base_specs = strategy.opt_specs_for_tree(param_shapes,
                                                 logical_axes)
    if opt_shapes is None:
        opt_shapes = jax.eval_shape(optimizer.init, param_shapes)

    def spec_for_opt_leaf(leaf, spec, pshape):
        # Optimizer state inherits the param's spec ONLY when it is
        # exactly param-shaped. Anything else replicates: Adafactor's
        # factored v_row/v_col drop one of the param's dims, so a
        # rank-compatible spec can still land a sharded axis on the
        # WRONG (possibly non-divisible) dimension — caught by the 7B
        # fsdp=16 topology compile, where GQA wk (L, D, Hkv, hd) has
        # param spec P(None, 'fsdp') but v_row is (L, Hkv, hd) and
        # dim 1 became Hkv=8, not divisible by 16. (The earlier
        # rank/size guard missed exactly this equal-rank-prefix case.)
        # Factored moments are tiny by construction, so replication
        # costs nothing material.
        if (isinstance(spec, P) and hasattr(leaf, "shape")
                and hasattr(pshape, "shape")
                and tuple(leaf.shape) != tuple(pshape.shape)):
            return P()
        return spec

    opt_specs = optax.tree_map_params(
        optimizer,
        spec_for_opt_leaf,
        opt_shapes,
        opt_base_specs,
        param_shapes,
        transform_non_params=lambda _leaf: P(),
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )
    return {"params": param_specs, "opt_state": opt_specs, "step": P()}


def state_shardings(mesh: Mesh, specs: dict,
                    offload_opt_state: bool = False,
                    opt_shapes: Any = None) -> dict:
    """NamedShardings for the state tree.

    ``offload_opt_state=True`` makes ``pinned_host`` memory the
    RESIDENCY of the optimizer moments — the analogue of the reference
    FSDP's CPU offload (fsdp_strategy.py:23-25, which was unreachable
    there, SURVEY.md §8 B7, and which likewise round-trips state to
    the accelerator per use). The trainer streams the moments to
    device around each step and back (see Trainer.train_step), so
    between steps HBM holds params + activations only — AdamW's
    2×params fp32, the bulk of big-model residency, lives in host RAM.
    In-jit streaming via memory-space annotations (tiles resident
    only) is the upgrade path once XLA's host-offload annotations are
    reliable on the deployed runtime — attempted on jax 0.9.0 (r4):
    any jit whose out_shardings mix memory kinds AND include a scalar
    output (Adam's count) fails XLA SPMD's
    "Side-effect HLO must have sharding" RET_CHECK
    (spmd_partitioner.cc:5743) because the scalar's placement
    custom-call carries no sharding; and in-traced ``device_put`` to
    host does not pin output residency without out_shardings. Re-try
    when the partitioner handles scalar placements. Requires
    host-memory support (``supports_memory_kind``); raises otherwise
    rather than silently keeping state on device."""
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    if offload_opt_state:
        if not supports_memory_kind(mesh, "pinned_host"):
            raise ValueError(
                "offload_opt_state=true but this runtime has no "
                "pinned_host memory space (CPU test meshes and old "
                "libtpu builds lack it)")
        if opt_shapes is None:
            raise ValueError(
                "offload_opt_state=true requires opt_shapes (scalar "
                "step counters must stay on device)")

        def offload(sh: NamedSharding, leaf) -> NamedSharding:
            # Only array-sized leaves move to host: scalar counters
            # (Adam's count) trip XLA's side-effecting placement
            # custom-call under SPMD, and offloading them buys nothing.
            if getattr(leaf, "ndim", 0) >= 1 and np.prod(leaf.shape) > 1:
                return sh.with_memory_kind("pinned_host")
            return sh

        shardings["opt_state"] = jax.tree.map(
            offload, shardings["opt_state"], opt_shapes)
    return shardings


def supports_memory_kind(mesh: Mesh, kind: str) -> bool:
    """Whether the mesh's devices expose the given memory space."""
    try:
        dev = mesh.devices.reshape(-1)[0]
        return any(m.kind == kind for m in dev.addressable_memories())
    except (AttributeError, RuntimeError, jax.errors.JaxRuntimeError):
        return False


def init_state(model, optimizer, rng: jax.Array, shardings: dict) -> dict:
    """Initialize params and optimizer state directly into their sharded
    layout — no host-side full materialization, so 7B-class models
    never need to fit on one host (contrast: the reference builds the
    full model on every rank then wraps, src/distributed_trainer.py:137)."""
    params = jax.jit(model.init,
                     out_shardings=shardings["params"])(rng)
    opt_state = jax.jit(optimizer.init,
                        out_shardings=shardings["opt_state"])(params)
    step = jnp.zeros((), jnp.int32)
    return {"params": params, "opt_state": opt_state, "step": step}


def abstract_state(model, optimizer, rng: jax.Array,
                   shardings: dict) -> dict:
    """ShapeDtypeStructs (with shardings attached) for checkpoint
    restore-in-place."""
    p_shapes = jax.eval_shape(model.init, rng)
    o_shapes = jax.eval_shape(optimizer.init, p_shapes)
    shapes = {"params": p_shapes, "opt_state": o_shapes,
              "step": jax.ShapeDtypeStruct((), jnp.int32)}
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)
