"""Train state: a transparent pytree, born sharded.

``{"params", "opt_state", "step"}`` — the unit the checkpoint layer
saves/restores (superset of the reference's ``{"MODEL_STATE",
"EPOCHS_RUN"}`` snapshot, src/distributed_trainer.py:88-91, which dropped
optimizer state entirely; SURVEY.md §5.4).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_tpu.parallel.strategy import ShardingStrategy


def state_specs(strategy: ShardingStrategy,
                optimizer: optax.GradientTransformation,
                param_shapes: Any, logical_axes: Any = None) -> dict:
    """PartitionSpecs for the full train state.

    Optimizer-state leaves that mirror params (Adam moments, momentum)
    inherit the param's spec via ``optax.tree_map_params``; scalar/other
    leaves replicate.
    """
    param_specs = strategy.specs_for_tree(param_shapes, logical_axes)
    opt_shapes = jax.eval_shape(optimizer.init, param_shapes)
    opt_specs = optax.tree_map_params(
        optimizer,
        lambda _leaf, spec: spec,
        opt_shapes,
        param_specs,
        transform_non_params=lambda _leaf: P(),
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )
    return {"params": param_specs, "opt_state": opt_specs, "step": P()}


def state_shardings(mesh: Mesh, specs: dict) -> dict:
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def init_state(model, optimizer, rng: jax.Array, shardings: dict) -> dict:
    """Initialize params and optimizer state directly into their sharded
    layout — no host-side full materialization, so 7B-class models
    never need to fit on one host (contrast: the reference builds the
    full model on every rank then wraps, src/distributed_trainer.py:137)."""
    params = jax.jit(model.init,
                     out_shardings=shardings["params"])(rng)
    opt_state = jax.jit(optimizer.init,
                        out_shardings=shardings["opt_state"])(params)
    step = jnp.zeros((), jnp.int32)
    return {"params": params, "opt_state": opt_state, "step": step}


def abstract_state(model, optimizer, rng: jax.Array,
                   shardings: dict) -> dict:
    """ShapeDtypeStructs (with shardings attached) for checkpoint
    restore-in-place."""
    p_shapes = jax.eval_shape(model.init, rng)
    o_shapes = jax.eval_shape(optimizer.init, p_shapes)
    shapes = {"params": p_shapes, "opt_state": o_shapes,
              "step": jax.ShapeDtypeStruct((), jnp.int32)}
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)
