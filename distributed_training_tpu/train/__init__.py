"""Training orchestration: jitted train step + epoch loop.

Replaces the reference's ``Trainer``/``main`` (src/distributed_trainer.py:
108-192,243-276). The structural difference is the TPU execution model:
instead of an eager per-batch loop whose collectives hide in autograd
hooks, the whole optimization step — forward, backward, gradient
collectives, optimizer update — is one jitted SPMD program whose
parallelism comes from the strategy's sharding layout.
"""

from distributed_training_tpu.train.optimizer import (  # noqa: F401
    build_optimizer,
)
from distributed_training_tpu.train.trainer import Trainer  # noqa: F401
