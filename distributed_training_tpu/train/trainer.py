"""The Trainer: one jitted SPMD train step + epoch orchestration.

Replaces the reference ``Trainer`` (src/distributed_trainer.py:108-192):
same externally-visible behavior — epoch loop resuming from the last
checkpointed epoch, per-``save_every`` checkpointing, per-epoch logging —
with the compute path redesigned for XLA: forward+backward+update is a
single compiled program with donated inputs; DDP's gradient all-reduce and
FSDP's all-gather/reduce-scatter are emitted by the compiler from the
strategy's sharding layout (no imperative collectives anywhere).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Iterable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_training_tpu import telemetry as telemetry_lib
from distributed_training_tpu.config import Config
from distributed_training_tpu.models.base import count_params
from distributed_training_tpu.parallel.strategy import ShardingStrategy
from distributed_training_tpu.runtime import Runtime
from distributed_training_tpu.train import state as state_lib
from distributed_training_tpu.train.optimizer import build_optimizer
from distributed_training_tpu.utils.metrics import (MetricsLogger,
                                                    peak_flops_per_chip)

logger = logging.getLogger(__name__)


def make_train_step(model, optimizer: optax.GradientTransformation,
                    nan_guard: bool = False, grad_accum_steps: int = 1,
                    microbatch_sharding=None, grad_shardings=None):
    """Build the pure train-step function (pre-jit).

    The entire reference ``_run_batch`` (zero_grad → forward → loss →
    backward → step, src/distributed_trainer.py:160-165) plus the
    collective layer beneath it, as one traced function.

    ``grad_accum_steps > 1`` splits the global batch into that many
    microbatches and accumulates mean gradients over a ``lax.scan`` —
    one optimizer step per call either way, so larger effective batches
    fit in HBM at the same peak activation memory. Requires the global
    batch to split evenly (checked at trace time via the reshape).
    """

    def accumulated_grads(params, batch, rng):
        def loss_fn(p, b, r):
            loss, metrics = model.loss(p, b, r, train=True)
            return loss, metrics

        if grad_accum_steps <= 1:
            return jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, rng)
        a = grad_accum_steps
        # STRIDED split (microbatch i = rows i, i+a, i+2a, ...), not
        # contiguous chunks: each device's contiguous batch shard
        # contains an equal residue of every stride class, so every
        # microbatch row stays on its original device — a contiguous
        # split would force an all-to-all of the whole batch each step.
        # Mean-of-means is identical over any equal partition.
        micro = jax.tree.map(
            lambda x: jnp.swapaxes(
                x.reshape((x.shape[0] // a, a) + x.shape[1:]), 0, 1),
            dict(batch))
        if microbatch_sharding is not None:
            # Keep the (now second) batch dim sharded over the data
            # axes — without the constraint XLA may shard the scan dim.
            micro = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, microbatch_sharding), micro)

        def body(carry, inp):
            acc_grads, acc_loss, acc_metrics = carry
            i, mb = inp
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb,
                                       jax.random.fold_in(rng, i))
            acc_grads = jax.tree.map(jnp.add, acc_grads, grads)
            acc_metrics = jax.tree.map(jnp.add, acc_metrics, metrics)
            return (acc_grads, acc_loss + loss, acc_metrics), None

        zero_g = jax.tree.map(jnp.zeros_like, params)
        mb0 = jax.tree.map(lambda x: x[0], micro)
        _, zero_m = jax.eval_shape(
            lambda: loss_fn(params, mb0, rng))
        zero_m = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), zero_m)
        (grads, loss, metrics), _ = jax.lax.scan(
            body, (zero_g, jnp.zeros((), jnp.float32), zero_m),
            (jnp.arange(a), micro))
        inv = 1.0 / a
        mean_loss = loss * inv
        metrics = jax.tree.map(lambda m: m * inv, dict(metrics))
        # Nonlinear derived metrics don't average arithmetically
        # (Jensen): recompute from the averaged loss so accum=N logs
        # the same value as accum=1 at the same effective batch.
        if "perplexity" in metrics:
            metrics["perplexity"] = jnp.exp(mean_loss)
        return (mean_loss, metrics), jax.tree.map(
            lambda g: g * inv, grads)

    def train_step(state: dict, batch: Mapping[str, jax.Array],
                   base_rng: jax.Array):
        params, opt_state, step = (state["params"], state["opt_state"],
                                   state["step"])
        rng = jax.random.fold_in(base_rng, step)

        (loss, metrics), grads = accumulated_grads(params, batch, rng)
        if grad_shardings is not None:
            # Pin gradients to the PARAM layout before any full-tree
            # consumer (global_norm here; clip inside optimizer.update)
            # can demand them replicated: with the pin, the batch-axis
            # reduction lowers to reduce-scatter (the TPU pipeline
            # fuses all-reduce + slice into an %all-reduce-scatter
            # kernel) and the grad norm becomes shard-local square-sums
            # + one scalar psum. Without it, every sharded-param grad
            # pays a full-shape all-reduce — 2x optimal traffic
            # (VERDICT r4 item 4; audited via
            # benchmarks/audit_collectives.py --tpu-topology).
            grads = jax.lax.with_sharding_constraint(
                grads, grad_shardings)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)

        gnorm = optax.global_norm(grads)
        metrics = dict(metrics)
        metrics["grad_norm"] = gnorm

        if nan_guard:
            # Skip non-finite update steps instead of poisoning params —
            # replaces "watch the logs for NaN" (SURVEY.md §5.2).
            ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
            new_params = jax.tree.map(
                lambda new, old: jnp.where(ok, new, old),
                new_params, params)
            new_opt = jax.tree.map(
                lambda new, old: jnp.where(ok, new, old),
                new_opt, opt_state)
            metrics["skipped_nonfinite"] = (~ok).astype(jnp.float32)

        new_state = {"params": new_params, "opt_state": new_opt,
                     "step": step + 1}
        return new_state, metrics

    return train_step


class Trainer:
    """Config-driven training orchestrator."""

    def __init__(self, cfg: Config, runtime: Runtime, model,
                 loader, checkpointer=None, preemption_guard=None,
                 eval_loader=None, abstract: bool = False,
                 watchdog=None, fault_injector=None,
                 profile_capture=None):
        self.cfg = cfg
        self.rt = runtime
        self.model = model
        self.loader = loader
        self.eval_loader = eval_loader
        self._eval_fn = None
        self.checkpointer = checkpointer
        # Cooperative stop flag (SIGTERM → save + clean exit); see
        # utils/preemption.py. None → never stops early.
        self.preemption_guard = preemption_guard
        # Observability: the ambient Telemetry (entrypoints install it
        # — telemetry.install(...); the default is a no-op sink whose
        # spans still mark XProf trace regions). Bound BEFORE the
        # checkpoint restore below so ckpt_restore spans are captured.
        self.telemetry = telemetry_lib.current()
        # Hang watchdog (telemetry/watchdog.py), armed around every
        # step in _run_epoch; owned by the caller (cli builds it from
        # train.watchdog_timeout_s and stops it after train()).
        self.watchdog = watchdog
        # Deterministic fault injection (resilience/faults.py): the
        # step-loop hook fires crash/sigterm faults as a pure function
        # of global_step — the same every-host-same-loop-point
        # discipline as the straggler exchange, so injection can never
        # strand hosts on different sides of a collective. None → off.
        self.faults = fault_injector
        # In-run profiler capture + step-time attribution (telemetry/
        # attribution.py ProfileCapture, built by the CLI from
        # train.profile_at / the run-dir drop-file trigger;
        # coordinator-gated there). None → no capture, zero overhead.
        self.profiles = profile_capture
        self.ledger = None
        self.hbm = None
        self._steps_dispatched = 0
        self._div_check_compiled = False
        # Model/dataset contract check BEFORE any tracing: a mismatch
        # (e.g. model=byte_lm with the default regression dataset)
        # otherwise dies as a bare KeyError inside the jitted step.
        need = set(getattr(model, "batch_keys", ()) or ())
        model_vocab = getattr(getattr(model, "cfg", None),
                              "vocab_size", None)
        for role, ldr in (("train", loader), ("eval", eval_loader)):
            ds = getattr(ldr, "dataset", None)
            if ds is None:
                continue
            if need and len(ds) > 0:
                have = set(ds.batch(np.array([0])).keys())
                if not need <= have:
                    raise ValueError(
                        f"model expects batch keys {sorted(need)} but "
                        f"the {role} dataset yields {sorted(have)} — "
                        "pick a matching train.dataset (LMs: "
                        "synthetic_lm / bytes_file / memmap_tokens; "
                        "regression: synthetic*; images: "
                        "synthetic_images)")
            # Token-id range check (independent of batch_keys — any
            # model exposing cfg.vocab_size gets it): ids >= the
            # model's vocab read out-of-range embedding rows (XLA
            # clamps the gather) and poison the loss as NaN — a
            # config mistake that must fail with its cause named
            # (e.g. the dataset's default vocab 50257 against a
            # small-vocab model).
            ds_vocab = getattr(ds, "vocab_size", None)
            if model_vocab and ds_vocab and ds_vocab > model_vocab:
                raise ValueError(
                    f"the {role} dataset draws token ids from a "
                    f"vocab of {ds_vocab} but the model embeds "
                    f"only {model_vocab} — set train."
                    "dataset_kwargs.vocab_size to the model's "
                    "vocab (or pick the matching model config)")
        # Cross-host straggler detector (telemetry/straggler.py):
        # no-op single-process or with straggler_every=0; on a pod it
        # exchanges window step/data_wait means every K steps and
        # flags persistent outliers into the event stream + watchdog
        # context.
        from distributed_training_tpu.telemetry.straggler import (
            StragglerDetector)
        from distributed_training_tpu.resilience import elastic
        self.straggler = StragglerDetector(
            runtime,
            every=cfg.train.straggler_every,
            threshold=cfg.train.straggler_threshold,
            persist=cfg.train.straggler_persist,
            evict_after=cfg.train.straggler_evict_after,
            elastic_dir=os.environ.get(elastic.ENV_ELASTIC_DIR))
        tcfg = cfg.train
        if (tcfg.grad_accum_steps > 1
                and loader.batch_size % tcfg.grad_accum_steps):
            # The strided microbatch split is zero-communication only
            # when each shard's rows divide evenly into the stride
            # classes; otherwise GSPMD would silently reshard the whole
            # batch every step. Fail loudly instead.
            raise ValueError(
                f"grad_accum_steps={tcfg.grad_accum_steps} must divide "
                f"the per-shard batch_size={loader.batch_size}")

        # Sharding source: a resolved plan (parallel/planner.py) when
        # one is pinned — the planner's sharding-map-by-name is then
        # the single spec source the step compiles against — else the
        # legacy per-strategy producers. The plan's mesh must be the
        # runtime's mesh (dp may flex under an elastic incarnation,
        # PR 7's wildcard contract); a mismatch is a config error that
        # must fail here, not compile into a silently different
        # layout.
        self.plan = None
        if tcfg.sharding_plan:
            from distributed_training_tpu.parallel import planner
            self.plan = planner.load_plan(tcfg.sharding_plan)
            planner.check_plan_runtime(self.plan, runtime.spec)
            self.strategy: ShardingStrategy = planner.PlannedStrategy(
                plan=self.plan,
                min_shard_elems=tcfg.min_shard_elems,
                gather_on_save=tcfg.gather_on_save)
        else:
            from distributed_training_tpu.parallel import get_strategy
            self.strategy = get_strategy(
                tcfg.parallel_strategy, runtime.spec,
                min_shard_elems=tcfg.min_shard_elems,
                gather_on_save=tcfg.gather_on_save)
        if hasattr(model, "bind_mesh"):
            model.bind_mesh(runtime.mesh)
        total_steps = tcfg.total_steps or (
            loader.steps_per_epoch * tcfg.total_epochs)
        self.optimizer = build_optimizer(tcfg, total_steps)

        rng = jax.random.PRNGKey(tcfg.seed)
        self.init_rng, self.step_rng = jax.random.split(rng)

        param_shapes = jax.eval_shape(model.init, self.init_rng)
        logical = (model.logical_axes()
                   if hasattr(model, "logical_axes") else None)
        opt_shapes = jax.eval_shape(self.optimizer.init, param_shapes)
        self.state_shardings = state_lib.state_shardings(
            runtime.mesh,
            state_lib.state_specs(self.strategy, self.optimizer,
                                  param_shapes, logical,
                                  opt_shapes=opt_shapes),
            offload_opt_state=tcfg.offload_opt_state,
            opt_shapes=opt_shapes if tcfg.offload_opt_state else None)
        # Offload: the compiled step is pure device compute; the
        # trainer streams opt-state host<->device around it. The
        # device-residency variant of the sharding tree drives the jit.
        self._offload = tcfg.offload_opt_state
        self._device_state_shardings = self.state_shardings
        if self._offload:
            self._device_state_shardings = dict(
                self.state_shardings,
                opt_state=jax.tree.map(
                    lambda sh: (sh.with_memory_kind("device")
                                if sh.memory_kind == "pinned_host"
                                else sh),
                    self.state_shardings["opt_state"]))
        self.batch_sharding = NamedSharding(runtime.mesh,
                                            self.strategy.batch_spec())

        if (tcfg.fsdp_gather_for_compute
                and self.strategy.wants_gather_for_compute
                and hasattr(model, "bind_gather_for_compute")):
            # See TrainConfig.fsdp_gather_for_compute: weights gather
            # for compute; activations never pay collective traffic.
            # Placed AFTER state_shardings exist: the per-leaf backward
            # specs (derived from them) make each weight's cotangent
            # born in the param layout (reduce-scatter-able) instead of
            # replicated — see Transformer.bind_gather_for_compute.
            model.bind_gather_for_compute(
                NamedSharding(runtime.mesh, P()),
                bwd_specs=self._compute_bwd_specs())

        self._step_fn = jax.jit(
            make_train_step(
                model, self.optimizer, nan_guard=tcfg.nan_guard,
                grad_accum_steps=tcfg.grad_accum_steps,
                microbatch_sharding=NamedSharding(
                    runtime.mesh,
                    P(None, *self.strategy.batch_spec())),
                grad_shardings=self._device_state_shardings["params"]),
            donate_argnums=(0,),
            out_shardings=(self._device_state_shardings,
                           NamedSharding(runtime.mesh, P())),
        )

        if abstract:
            # AOT/audit mode: every sharding and the jitted step exist,
            # but nothing is materialized — ``self.state`` is a
            # ShapeDtypeStruct tree, so ``_step_fn.lower(state, ...)``
            # compiles against meshes with no attached devices
            # (runtime.topology_runtime; the TPU reduce-scatter audit).
            self.epochs_run = 0
            self.global_step = 0
            self.state = state_lib.abstract_state(
                model, self.optimizer, self.init_rng,
                self._device_state_shardings)
            self.metrics = MetricsLogger(
                log_every=0, samples_per_step=loader.global_batch,
                flops_per_sample=0, num_devices=runtime.num_devices,
                enabled=False)
            return

        # Resume-if-exists (parity: ModelCheckpoint.load on startup,
        # src/distributed_trainer.py:157,97-105) — but restoring optimizer
        # state and step too, which the reference dropped (§5.4).
        # Init/restore target the device layout; offloaded state moves
        # to its host residency right after.
        self.epochs_run = 0
        restored = None
        if checkpointer is not None:
            abstract_tree = state_lib.abstract_state(
                model, self.optimizer, self.init_rng,
                self._device_state_shardings)
            restored = checkpointer.restore_latest(abstract_tree)
        if restored is not None:
            self.state, meta = restored
            # Exactly-once resume (docs/data.md): the checkpoint meta
            # carries the loader's serialized position; restoring it
            # makes the interrupted epoch CONTINUE at its saved batch
            # offset instead of replaying from the epoch start (the
            # old behavior double-fed the optimizer every sample the
            # interrupted epoch had already consumed). Checkpoints
            # predating the state (or a state this loader cannot
            # drive) fall back to the epoch-boundary resume.
            self.epochs_run = int(meta.get("epoch", -1)) + 1
            data_state = meta.get("data")
            restored_pos = False
            if data_state and hasattr(self.loader, "load_state_dict"):
                try:
                    self.loader.load_state_dict(data_state)
                    self.epochs_run = self.loader.resume_epoch
                    restored_pos = True
                except (ValueError, KeyError, TypeError) as e:
                    logger.warning(
                        "checkpointed loader state unusable (%s); "
                        "resuming at the epoch boundary instead", e)
            if not restored_pos and hasattr(self.loader, "seek_epoch"):
                # Epoch-boundary fallback. A MID-EPOCH save whose
                # offset is unusable must REPLAY its interrupted epoch
                # from the start — skipping the remainder would
                # silently drop up to an epoch of data; the replay is
                # the lesser evil and the recovery table reports its
                # replayed-sample count honestly (the cursor sits
                # behind step * global_batch).
                if isinstance(data_state, dict) and data_state.get(
                        "mid_epoch"):
                    self.epochs_run = max(0, self.epochs_run - 1)
                self.loader.seek_epoch(self.epochs_run)
            logger.info("resumed from checkpoint: epoch=%d step=%d",
                        self.epochs_run, int(self.state["step"]))
        else:
            self.state = state_lib.init_state(
                model, self.optimizer, self.init_rng,
                self._device_state_shardings)
            logger.info("initialized fresh state: %d params",
                        count_params(self.state["params"]))
        if self._offload:
            self.state["opt_state"] = jax.device_put(
                self.state["opt_state"], self.state_shardings["opt_state"])
        # Host-side mirror of state["step"]: reading the device scalar
        # every step would force a host-device sync per step and defeat
        # async dispatch + prefetch.
        self.global_step = int(self.state["step"])

        flops_per_sample = (model.flops_per_sample()
                            if hasattr(model, "flops_per_sample") else 0)
        self.metrics = MetricsLogger(
            log_every=tcfg.log_every,
            samples_per_step=loader.global_batch,
            flops_per_sample=flops_per_sample,
            num_devices=runtime.num_devices,
            enabled=runtime.is_coordinator,
            device_kind=runtime.device_kind,
            jsonl_path=tcfg.metrics_jsonl or None,
            jsonl_fresh=(restored is None),
            start_step=self.global_step,
            # Mirror every metrics entry into the event stream as a
            # ``train_metrics`` record: the anomaly detector's
            # loss/throughput signals ride the loss float this logger
            # already materializes at log_every cadence — zero NEW
            # device syncs. Late-bound: _bind_telemetry re-resolves
            # the ambient sink at train(), so emit through it then.
            on_entry=lambda entry: self.telemetry.event(
                "train_metrics", **entry),
        )

        # HBM cross-check input: the exact per-device state residency
        # (utils/memory.py), computed from shape trees (cheap) so a
        # telemetry sink installed after construction can still get it.
        from distributed_training_tpu.utils.memory import (
            state_bytes_per_device)
        self._state_bytes_est = (
            state_bytes_per_device(
                param_shapes, self.state_shardings["params"])
            + state_bytes_per_device(
                opt_shapes, self.state_shardings["opt_state"]))
        self._flops_per_step = flops_per_sample * loader.global_batch
        self._bind_telemetry()

    def _bind_telemetry(self) -> None:
        """(Re)resolve the ambient Telemetry and build the goodput
        ledger + HBM sampler against it. Called at construction AND at
        the top of train(): an embedder that install()s after building
        the Trainer must not silently get a null-sink run where the
        checkpoint manager's module-level spans record but the
        trainer's (and the ledger's buckets) don't."""
        tel = telemetry_lib.current()
        if tel is self.telemetry and (self.ledger is not None
                                      or not tel.enabled):
            return
        self.telemetry = tel
        if not tel.enabled:
            self.ledger = self.hbm = None
            return
        # Goodput ledger: depth-0 telemetry spans land in its buckets
        # (events.py), so wall-clock decomposes into compile/data_wait/
        # step/checkpoint/eval/idle with MFU computed from the same
        # FLOPs accounting as the metrics stream.
        self.ledger = telemetry_lib.GoodputLedger(
            flops_per_step=self._flops_per_step,
            num_devices=self.rt.num_devices,
            peak_flops=peak_flops_per_chip(self.rt.device_kind))
        tel.attach_ledger(self.ledger)
        self.hbm = telemetry_lib.HBMSampler(
            tel, every=self.cfg.train.hbm_sample_every,
            estimate_bytes=self._state_bytes_est)

    # -- cooperative stop / health ----------------------------------------

    _stop_agreed: bool = False

    @property
    def _stopping_early(self) -> bool:
        """Leaving the run before its epochs are done — preemption
        (agreed across hosts) or a coordinated eviction stop. Both
        must force a final save; the exit sentinel tells the
        supervisor which it was (train/cli.py)."""
        return self._stop_agreed or (
            self.straggler is not None
            and self.straggler.evict_request is not None)

    def _compute_bwd_specs(self) -> dict:
        """Per-leaf PARAM-layout shardings for the gather-for-compute
        asymmetric VJP, keyed by the model's weight paths. Layer
        params are stored stacked with a leading depth dim — the scan
        body sees slices, so their spec drops the first entry. The
        tied head is the embedding transposed, so its spec is the
        embedding's reversed."""
        ps = self.state_shardings.get("params")
        if not isinstance(ps, dict):
            return {}
        mesh = self.rt.mesh

        def slice_spec(sh):
            return NamedSharding(mesh, P(*sh.spec[1:]))

        out: dict = {}
        for group in ("attn", "mlp"):
            for name, sh in (ps.get(group) or {}).items():
                out[f"{group}/{name}"] = slice_spec(sh)
        for name in ("tok_embed", "pos_embed"):
            if name in ps:
                out[name] = ps[name]
        if "lm_head" in ps:
            out["head"] = ps["lm_head"]
        elif "tok_embed" in ps:
            spec = ps["tok_embed"].spec
            pads = (None,) * max(0, 2 - len(spec))
            v_ax, d_ax = (tuple(spec) + pads)[:2]
            out["head"] = NamedSharding(mesh, P(d_ax, v_ax))
        return out

    def _agreed_stop(self) -> bool:
        """Whether to break the step loop — agreed across ALL hosts.

        The local SIGTERM flag alone is not enough on a multi-host pod:
        the signal lands at different loop points on different hosts, and
        a host that breaks while others dispatch the next compiled step
        deadlocks the SPMD program (its collectives wait forever). So
        every host contributes its flag to a host-level allgather at the
        same loop point and all act on the OR."""
        if self.preemption_guard is None:
            return False
        local = self.preemption_guard.should_stop
        if self.rt.process_count == 1:
            self._stop_agreed = local
            return local
        # Multi-host: the allgather blocks the host thread, so polling
        # every step would break async dispatch. Poll on a step cadence
        # instead — the condition must be a function of global_step (in
        # lockstep on every host), NOT of the local flag or a local
        # clock, or hosts would enter the collective at different loop
        # points and deadlock. Stop latency is stop_poll_every ×
        # step_time; it must fit the preemption grace window, so for
        # slow steps set stop_poll_every=1 (see config).
        poll = max(1, self.cfg.train.stop_poll_every)
        if self.global_step % poll == 0:
            from jax.experimental import multihost_utils
            flags = multihost_utils.process_allgather(
                np.asarray([local], dtype=np.bool_))
            self._stop_agreed = bool(np.asarray(flags).any())
        return self._stop_agreed

    def _check_divergence(self):
        """Replica-drift check over axes the params are replicated on
        (DDP: (dp, fsdp); FSDP/TP: dp only — shards are fingerprinted in
        place, no all-gather). None if the layout has no replicas."""
        from jax.sharding import PartitionSpec
        from distributed_training_tpu.runtime import BATCH_AXES
        from distributed_training_tpu.utils import diagnostics
        specs = jax.tree.map(lambda s: s.spec,
                             self.state_shardings["params"])
        used = {a for s in jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
            for part in s if part is not None
            for a in ((part,) if isinstance(part, str) else part)}
        sizes = self.rt.spec.as_dict()
        axes = tuple(a for a in BATCH_AXES
                     if a not in used and sizes.get(a, 1) > 1)
        if not axes:
            return None
        return diagnostics.replica_divergence(
            self.state["params"], self.rt.mesh, axes=axes,
            param_specs=specs)

    # -- loops -------------------------------------------------------------

    def train_step(self, batch) -> Mapping[str, jax.Array]:
        # The first dispatch traces + compiles (blocking), so it is a
        # "compile" span/bucket; steady-state dispatches are "step".
        # Under async dispatch a "step" span is host time in (or
        # blocked on) the dispatch path — see telemetry/goodput.py.
        name = "compile" if self._steps_dispatched == 0 else "step"
        with self.telemetry.span(name, step=self.global_step + 1):
            if self.faults is not None:
                # slow_host fault: the injected degradation must land
                # INSIDE the measured step region — the span (so the
                # goodput ledger and the anomaly detector see the
                # degraded step time) and the straggler detector's
                # timing window both cover this call. A pure
                # host-local sleep — no collective.
                delay_s = self.faults.step_delay(self.global_step + 1)
                if delay_s:
                    time.sleep(delay_s)
            if self._offload:
                # Stream the moments host->device for the compiled
                # step and back to their pinned-host residency after —
                # the torch-FSDP-offload semantic (state lives on
                # host, visits the accelerator per step). Transfers
                # are async dispatches.
                self.state["opt_state"] = jax.device_put(
                    self.state["opt_state"],
                    self._device_state_shardings["opt_state"])
            self.state, metrics = self._step_fn(self.state, batch,
                                                self.step_rng)
            if self._offload:
                self.state["opt_state"] = jax.device_put(
                    self.state["opt_state"],
                    self.state_shardings["opt_state"])
        self._steps_dispatched += 1
        self.global_step += 1
        if name == "compile":
            # One-shot: the program that just compiled is the one the
            # whole run executes, so its collective traffic is now a
            # fixed fact worth recording.
            self._maybe_emit_collectives(batch)
        return metrics

    def collectives_report(self, batch) -> dict:
        """Static audit of the compiled step's collective traffic
        (telemetry/collectives.py): lower + compile the SAME jitted
        step against abstract inputs and walk the optimized HLO. No
        state is materialized or donated — also valid in abstract/
        topology mode, where this is how the TPU comms contract is
        inspected chip-free."""
        from distributed_training_tpu.telemetry import collectives
        abstract = state_lib.abstract_state(
            self.model, self.optimizer, self.init_rng,
            self._device_state_shardings)
        # Compile under an fd-level stderr capture: the SPMD
        # partitioner's "Involuntary full rematerialization" cliff is
        # only ever reported as a C++ log line, and the ledger must
        # carry that count mechanically (analysis/ gates on the same
        # parse) instead of via a log-tail grep.
        with collectives.capture_stderr_fd() as cap:
            text = self._step_fn.lower(
                abstract, batch, self.step_rng).compile().as_text()
        # Stashed for the one-shot attribution_static event: the
        # static overlap audit walks the SAME compiled text, so the
        # two events can never describe different programs (and the
        # compile is paid once).
        self._last_audit_hlo = text
        rep = collectives.audit_hlo_text(text, mesh=self.rt.mesh)
        rep["mesh"] = {a: s for a, s in self.rt.spec.as_dict().items()
                       if s > 1}
        rep["spmd_reshard_warnings"] = len(
            collectives.parse_reshard_warnings(cap.text))
        if self.plan is not None:
            # Plan provenance travels with the comms ledger: a
            # MULTICHIP-style entry can then say WHICH resolved plan
            # produced the traffic it records.
            rep["sharding_plan"] = {
                "name": self.plan.name,
                "fingerprint": self.plan.fingerprint(),
                "remat": self.plan.remat,
                "base_strategy": self.plan.base_strategy,
            }
        return rep

    def _maybe_emit_collectives(self, batch) -> None:
        """Emit the ``collectives`` event after the first step.
        Coordinator-only (the SPMD program is identical on every
        host) and only when an event sink is recording — the audit
        costs a cache-warm trace + compile, which a bench loop
        without telemetry must not pay."""
        if not (self.cfg.train.collectives_audit
                and self.telemetry.enabled
                and self.rt.is_coordinator):
            return
        with self.telemetry.span("collectives_audit"):
            try:
                rep = self.collectives_report(batch)
            except Exception:  # noqa: BLE001 — observability must not
                # take down the training loop it observes.
                logger.exception("collectives audit failed; continuing")
                # The compile may have stashed its HLO text before the
                # audit failed; without a consumer to clear it, the
                # multi-MB dump would stay resident for the whole run.
                self._last_audit_hlo = None
                return
        self.telemetry.event("collectives", **rep)
        self._emit_attribution_static()

    def _emit_attribution_static(self) -> None:
        """One-shot ``attribution_static`` event: the static overlap
        score of the compiled schedule (telemetry/attribution.py),
        from the HLO text the collectives audit just walked, with the
        planner roofline's expected comms/compute seconds as the
        denominator context — "the schedule hides X% of collectives,
        which the cost model prices at Y ms/step"."""
        text = getattr(self, "_last_audit_hlo", None)
        # One-shot consumer: the compiled module's text dump can run
        # tens of MB and must not stay resident for the whole run.
        self._last_audit_hlo = None
        if text is None:
            return
        from distributed_training_tpu.telemetry import attribution
        try:
            rep = attribution.overlap_summary(
                attribution.hlo_overlap_report(text))
        except Exception:  # noqa: BLE001 — same contract as the
            # collectives audit: never take down the loop.
            logger.exception("static overlap audit failed; continuing")
            return
        rep["step"] = self.global_step
        if self.plan is not None:
            score = (self.plan.provenance or {}).get("score", {})
            for src, dst in (("comms_s", "expected_comms_s"),
                             ("compute_s", "expected_compute_s")):
                if isinstance(score.get(src), (int, float)):
                    rep[dst] = score[src]
            rep["sharding_plan"] = {
                "name": self.plan.name,
                "fingerprint": self.plan.fingerprint()}
            # Scheduler provenance: which plan-derived latency-hiding
            # flags this process actually ran with (cli/launch/bench
            # apply them to XLA_FLAGS; an operator may also have set
            # or suppressed them by hand) — so the static score is
            # attributable to its scheduler config.
            from distributed_training_tpu.parallel import overlap
            rep["xla_overlap_flags"] = overlap.active_in_env(
                self.plan.xla_overlap_flags(self.rt.platform))
        self.telemetry.event("attribution_static", **rep)

    def _run_epoch(self, epoch: int) -> dict[str, float]:
        """Parity: Trainer._run_epoch (src/distributed_trainer.py:167-183)
        — sampler reshuffle per epoch, batch loop — without the
        wasted peek-batch (§8 B3)."""
        losses = []
        div_every = self.cfg.train.divergence_check_every
        log_every = self.cfg.train.log_every
        it = iter(self.loader.epoch(epoch))
        try:
            while True:
                if self.watchdog is not None:
                    # Armed BEFORE the fetch: a wedged input pipeline (dead
                    # prefetch thread, stuck host data op) is exactly the
                    # silent-hang class the watchdog exists for, so the
                    # data wait must be inside the armed window. The first
                    # step gets a 10x allowance: compile time is expected
                    # to dwarf a steady-state step, and a watchdog tuned to
                    # step time must not fire on it.
                    self.watchdog.arm(
                        step=self.global_step + 1, epoch=epoch,
                        timeout_s=(self.watchdog.timeout_s * 10
                                   if self._steps_dispatched == 0
                                   else None))
                if self.profiles is not None:
                    # In-run trace capture (train.profile_at / the
                    # drop-file trigger): started BEFORE the fetch so
                    # the captured window includes the step's data
                    # wait — the host+data fraction of the
                    # attribution needs it on the timeline.
                    self.profiles.maybe_start(self.global_step + 1)
                # Host time blocked on the (prefetching) loader — the
                # data_wait goodput bucket. Near-zero when prefetch keeps
                # up; a hot data_wait is an input-pipeline limiter.
                t_wait0 = time.perf_counter()
                with self.telemetry.span("data_wait",
                                         step=self.global_step + 1):
                    batch = next(it, None)
                data_wait_s = time.perf_counter() - t_wait0
                if batch is None:
                    if self.watchdog is not None:
                        self.watchdog.disarm()
                    break
                t_step0 = time.perf_counter()
                metrics = self.train_step(batch)
                if self.straggler.enabled:
                    self.straggler.record_step(
                        time.perf_counter() - t_step0, data_wait_s)
                    # The exchange is a collective: its cadence (inside
                    # maybe_exchange) is a pure function of global_step so
                    # every host enters at the same loop point.
                    if (self.straggler.maybe_exchange(self.global_step)
                            is not None and self.watchdog is not None):
                        self.watchdog.set_context(
                            self.straggler.watchdog_info())
                if self.straggler.evict_request is not None:
                    # Coordinated eviction stop: the request derives from
                    # the all-gathered table at this exchange step, so
                    # EVERY host sees it here, at the same loop point —
                    # all break together, save, and exit cleanly; no host
                    # is left waiting in a collective during teardown.
                    if self.watchdog is not None:
                        self.watchdog.disarm()
                    logger.warning(
                        "stopping for elastic eviction of host %s "
                        "(requested at step %s)",
                        self.straggler.evict_request.get("host"),
                        self.straggler.evict_request.get("step"))
                    self.metrics.record(self.global_step, metrics,
                                        epoch=epoch)
                    losses.append(metrics["loss"])
                    break
                if div_every and self.global_step % div_every == 0:
                    # Compiled cross-replica drift check (SURVEY.md §5.2's
                    # "diff the rank logs", formalized).
                    if (self.watchdog is not None
                            and not self._div_check_compiled):
                        # The first check jit-compiles the whole-params
                        # fingerprint program inside the armed window —
                        # give it the compile allowance too.
                        self.watchdog.arm(
                            step=self.global_step, epoch=epoch,
                            timeout_s=self.watchdog.timeout_s * 10)
                    self._div_check_compiled = True
                    report = self._check_divergence()
                    if report is not None:
                        metrics = {**metrics, "replica_divergence":
                                   report["max_divergence"]}
                self.metrics.record(self.global_step, metrics, epoch=epoch)
                if self.hbm is not None:
                    self.hbm.maybe_sample(self.global_step)
                if (self.ledger is not None and log_every > 0
                        and self.global_step % log_every == 0):
                    self.telemetry.event(
                        "goodput", scope="window", step=self.global_step,
                        **self.ledger.window_report())
                if self.watchdog is not None:
                    self.watchdog.disarm()
                if self.profiles is not None:
                    # Close the capture window once its steps are in.
                    # The sync drains the traced async dispatches so
                    # their device work lands in the trace; it fires
                    # only on a capture's FINAL step, after the step
                    # span closed — the stall books to idle, never to
                    # the goodput step bucket.
                    rep = self.profiles.maybe_stop(
                        self.global_step,
                        sync=lambda: jax.block_until_ready(metrics))  # noqa: DTT003 — capture-final-step drain by design
                    if rep is not None:
                        self.telemetry.event("attribution", **rep)
                losses.append(metrics["loss"])
                if self.faults is not None:
                    # After the step's bookkeeping, before the stop poll:
                    # a sigterm fault raised here is observed by
                    # _agreed_stop at the same loop point on every host.
                    self.faults.on_step(self.global_step)
                if self._agreed_stop():
                    break
        finally:
            # Every exit — natural end, preemption/eviction
            # break, OR an exception unwinding (a crash fault,
            # an XLA error) — must close the epoch iterator so
            # the prefetch worker is signalled, drained and
            # JOINED (never left blocked on a full queue
            # holding dataset resources; data/loader.py), and
            # the loader's consumed position stays exactly at
            # the last batch the optimizer saw (what the
            # checkpoint meta records).
            close = getattr(it, "close", None)
            if close is not None:
                close()
        # One host sync per epoch, not per step — THE deliberate sync
        # point the DTT003 rule exists to protect (everything above
        # dispatches async; this drain happens once per epoch).
        mean_loss = float(np.mean([float(x) for x in losses]))  # noqa: DTT003 — epoch-end drain by design
        return {"epoch": epoch, "mean_loss": mean_loss}

    def train(self, max_epochs: int | None = None) -> dict[str, float]:
        """Parity: Trainer.train (src/distributed_trainer.py:185-192)."""
        max_epochs = max_epochs or self.cfg.train.total_epochs
        summary: dict[str, float] = {}
        t0 = time.perf_counter()
        self._bind_telemetry()
        if self.ledger is not None:
            # Ledger wall-clock starts at the training loop, not at
            # trainer construction — init/restore time is visible in
            # the event stream but is not this run's goodput story.
            self.ledger.reset()
        for epoch in range(self.epochs_run, max_epochs):
            summary = self._run_epoch(epoch)
            if self.rt.is_coordinator:
                logger.info("epoch %d | mean_loss %.6f", epoch,
                            summary["mean_loss"])
            eval_every = self.cfg.train.eval_every
            if (self.eval_loader is not None and eval_every
                    and (epoch + 1) % eval_every == 0
                    and not self._stopping_early):
                val_loss = self.evaluate(self.eval_loader.epoch(epoch))
                summary["val_loss"] = val_loss
                # Unthrottled: epoch-end eval must never be dropped by
                # the per-step log_every window.
                self.metrics.record_scalar(self.global_step, "val_loss",
                                           val_loss, epoch=epoch)
            preempted = self._stopping_early
            save_every = self.cfg.train.save_every
            if self.checkpointer is not None and (
                    preempted or (save_every > 0
                                  and epoch % save_every == 0)):
                # Collective save: every process participates (fixes the
                # reference's rank-0-only FSDP save hang, SURVEY.md §8 B6).
                # On preemption: save whatever we have, mid-epoch
                # included. The loader's serialized position rides the
                # meta (same sha256 manifest as the weights), so a
                # resume continues the interrupted epoch at its saved
                # cursor — no sample replayed, none skipped. Loaders
                # without a position keep the legacy epoch-1 label
                # (resume replays the interrupted epoch).
                data_state = (self.loader.state_dict()
                              if hasattr(self.loader, "state_dict")
                              else None)
                meta_epoch = (epoch if data_state is not None
                              or not preempted else epoch - 1)
                meta = {"epoch": meta_epoch, **self._arch_meta()}
                if data_state is not None:
                    meta["data"] = data_state
                self.checkpointer.save(
                    self.global_step, self.state, meta=meta,
                    force=preempted)
                if self.strategy.gather_on_save:
                    # Same epoch label as the sharded checkpoint: an
                    # interrupted epoch must not read as complete in
                    # the portable artifact either.
                    self.export_consolidated(epoch=meta_epoch)
            if preempted:
                logger.warning(
                    "stopping at epoch %d due to %s", epoch,
                    "preemption" if self._stop_agreed
                    else "elastic eviction")
                break
            self.epochs_run = epoch + 1
        if self.checkpointer is not None:
            self.checkpointer.wait()
        summary["wall_time_s"] = time.perf_counter() - t0
        if self.ledger is not None:
            rep = self.ledger.report()
            self.telemetry.event("goodput", scope="run",
                                 step=self.global_step, **rep)
            summary["goodput"] = rep
            if self.rt.is_coordinator:
                logger.info(
                    "goodput %.1f%% over %.1fs wall (%d steps): %s",
                    100 * rep["goodput"], rep["wall_s"], rep["steps"],
                    rep["buckets"])
        return summary

    def _arch_meta(self) -> dict:
        """Architecture identity stamped into every checkpoint/artifact
        meta, so a consolidated export is self-describing — the
        generation CLI can rebuild the exact model without the run's
        resolved config."""
        return {"model_name": self.cfg.model.name,
                "model_kwargs": dict(self.cfg.model.kwargs),
                "model_dtype": self.cfg.model.kwargs.get(
                    "dtype", self.cfg.train.dtype),
                "loss": self.cfg.train.loss}

    # -- consolidated export -----------------------------------------------

    def export_consolidated(self, epoch: int | None = None,
                            path: str | None = None) -> str:
        """Gather the full train state and write ONE portable artifact
        (the reference's FSDP FULL_STATE_DICT gather, done collectively
        so it cannot deadlock — every process enters; process 0 writes).
        Default path: <snapshot_path>/consolidated_step<N>.msgpack."""
        from distributed_training_tpu.checkpoint import consolidate
        if path is None:
            import os
            path = os.path.join(
                self.cfg.train.snapshot_path,
                f"consolidated_step{self.global_step}.msgpack")
        meta = {"step": self.global_step, **self._arch_meta()}
        if epoch is not None:
            meta["epoch"] = epoch
        return consolidate.export_consolidated(
            path, self.state, self.rt.mesh, meta=meta)

    # -- eval --------------------------------------------------------------

    def evaluate(self, batches: Iterable[Mapping[str, Any]]) -> float:
        """Mean loss over batches without updating state (dropout off,
        deterministic). The jitted eval fn is built once and reused.

        Dispatch-friendly by construction: the fn is jitted with the
        same state/batch shardings as the train step (no silent
        reshards), and per-batch losses are accumulated on device — the
        host syncs exactly once per evaluation, not once per batch,
        so eval batches dispatch asynchronously like train steps do."""
        if self._eval_fn is None:
            self._eval_fn = jax.jit(
                lambda p, b, r: self.model.loss(p, b, r,
                                                train=False)[0],
                in_shardings=(self.state_shardings["params"],
                              self.batch_sharding, None),
                out_shardings=NamedSharding(self.rt.mesh, P()),
            )
        eval_fn = self._eval_fn
        total = None
        count = 0
        with self.telemetry.span("eval", step=self.global_step):
            for b in batches:
                loss = eval_fn(self.state["params"], b, self.step_rng)
                total = loss if total is None else total + loss
                count += 1
            if count == 0:
                return float("nan")
            # The one host sync per EVALUATION (see docstring): eval
            # batches above dispatch async; this drains them all.
            return float(total) / count  # noqa: DTT003 — by design
