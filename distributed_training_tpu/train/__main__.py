from distributed_training_tpu.train.cli import main

raise SystemExit(main())
