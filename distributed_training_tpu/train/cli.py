"""Training entrypoint.

Usage (parity with the reference's Hydra CLI,
``python src/distributed_trainer.py train.batch_size=64 ...``,
src/distributed_trainer.py:243-276):

    python -m distributed_training_tpu.train [key=value ...]
    python -m distributed_training_tpu.train --config-dir conf model=gpt2

Also exposed under the reference's historical entrypoint name via
``multigpu_multi_node.py`` at the repo root (the name the reference's
cloud bootstrap launches — which didn't exist there; SURVEY.md §8 B1).
One process per host on TPU pods; ``jax.distributed`` handles rendezvous.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

logger = logging.getLogger(__name__)


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dtt-train",
        description="TPU-native distributed training")
    p.add_argument("--config-dir", default=None,
                   help="config root (default: <repo>/conf)")
    p.add_argument("--config-name", default="config")
    p.add_argument("overrides", nargs="*",
                   help="key.path=value config overrides")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_argparser().parse_args(argv)

    from distributed_training_tpu.config import load_config, save_resolved
    from distributed_training_tpu.runtime import initialize_runtime
    from distributed_training_tpu.utils.logging import setup_logging

    cfg = load_config(args.config_dir, args.config_name, args.overrides)

    run_dir = os.path.join(cfg.run.output_dir, cfg.run.experiment_name)
    os.makedirs(run_dir, exist_ok=True)

    plan = None
    applied_overlap_flags: list[str] = []
    if cfg.train.sharding_plan:
        # Pinned auto-parallelism plan (parallel/planner.py): the mesh
        # is DERIVED from it — model-sharding axes pinned to the
        # plan's extents, dp as the -1 wildcard so elastic
        # incarnations (PR 7 shrink/grow) re-form around the same
        # planned layout at a different data-parallel width. The
        # Trainer re-validates the resolved mesh against the plan.
        from distributed_training_tpu.parallel import planner
        plan = planner.apply_plan_to_config(cfg)
        if cfg.train.xla_overlap_flags:
            # Scheduled comms/compute overlap: the plan's XLA
            # latency-hiding flags must land in XLA_FLAGS BEFORE the
            # first backend init (initialize_runtime below), or the
            # compiler schedules without them. Platform must be known
            # without touching the backend — the env/device config is
            # authoritative; "auto" with no env stays unflagged (a
            # log line says so) rather than guessing wrong and
            # tripping an unknown-flag abort on another backend.
            from distributed_training_tpu.parallel import overlap
            platform = overlap.platform_from_env(
                cfg.train.device if cfg.train.device != "auto"
                else "")
            applied_overlap_flags = overlap.apply_to_env(
                plan.xla_overlap_flags(platform))

    rt = initialize_runtime(cfg)
    setup_logging(cfg.run.log_level,
                  os.path.join(run_dir, cfg.run.log_file),
                  rt.process_index)
    if plan is not None:
        # After setup_logging, or the line never reaches the run log.
        logger.info("sharding plan %s@%s: mesh derived %s",
                    plan.name, plan.fingerprint(), plan.mesh)
        if applied_overlap_flags:
            logger.info("comms/compute overlap: applied XLA flags %s",
                        applied_overlap_flags)
        elif cfg.train.xla_overlap_flags:
            logger.info("comms/compute overlap: no flags applied "
                        "(already set, platform unknown, or nothing "
                        "to hide on this mesh)")
    from distributed_training_tpu.resilience import elastic
    if cfg.train.global_batch_size:
        # Elastic contract: the GLOBAL batch is world-size-invariant;
        # the per-shard batch is derived from however many data shards
        # this incarnation's mesh resolved to (a shrunken world gets a
        # proportionally larger per-shard batch). Fails loudly on an
        # uneven split — silently changing the effective batch would
        # change the optimization trajectory.
        cfg.train.batch_size = elastic.per_shard_batch(
            cfg.train.global_batch_size, rt.data_shard_count)
        logger.info("global batch %d over %d shard(s) -> per-shard "
                    "batch %d", cfg.train.global_batch_size,
                    rt.data_shard_count, cfg.train.batch_size)
    # Topology this incarnation inherited from the elastic supervisor
    # (empty outside --elastic runs); recorded in the resume event so
    # postmortems can read the world-size history off the run stream.
    evicted_hosts = elastic.evicted_from_env()
    if not cfg.train.metrics_jsonl:
        cfg.train.metrics_jsonl = os.path.join(run_dir, "metrics.jsonl")
    # Multi-host: every process records its OWN event stream under
    # <run_dir>/host_<i>/ (a central writer would put a network hop in
    # the instrumentation path, and a dead coordinator would take all
    # evidence with it). The summarizer auto-detects the layout and
    # merges (telemetry/aggregate.py). Single-process runs keep the
    # flat <run_dir>/events.jsonl — EXCEPT under an elastic
    # supervisor: a run shrunk all the way to world 1 must keep
    # appending to host_0/events.jsonl, or the aggregate's recovery
    # table (which reads the coordinator's per-host stream) silently
    # loses the final incarnations of the topology history.
    elastic_incarnation = os.environ.get(elastic.ENV_WORLD) is not None
    host_dir = (run_dir
                if rt.process_count == 1 and not elastic_incarnation
                else os.path.join(run_dir, f"host_{rt.process_index}"))
    if not cfg.train.events_jsonl:
        cfg.train.events_jsonl = os.path.join(host_dir, "events.jsonl")
    logger.info("config loaded; %s", rt.describe())
    if rt.is_coordinator:
        save_resolved(cfg, os.path.join(run_dir, "resolved_config.yaml"))

    from distributed_training_tpu import telemetry as telemetry_lib
    from distributed_training_tpu.checkpoint import Checkpointer
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               build_dataset)
    from distributed_training_tpu.models import build_model
    from distributed_training_tpu.train.trainer import Trainer

    # Deterministic fault injection (resilience/faults.py): hooks in
    # the step loop, the data loader, and the checkpoint manager; the
    # per-host ledger makes faults one-shot across supervisor
    # restarts. Empty plan → no injector, zero overhead.
    fault_injector = None
    if cfg.train.fault_plan:
        from distributed_training_tpu.resilience import faults
        plan = faults.parse_fault_plan(cfg.train.fault_plan)
        # Source-level kinds need the streaming loader's per-document
        # hook; scheduling them against the sharded loader would be a
        # drill that silently never fires.
        faults.check_plan_hooks(plan, bool(cfg.train.data_sources))
        fault_injector = faults.FaultInjector(
            plan,
            ledger_path=os.path.join(host_dir, "faults_fired.json"),
            ckpt_dir=cfg.train.snapshot_path,
            host=rt.process_index)

    eval_loader = None
    if cfg.train.data_sources:
        # Multi-source exactly-once streaming pipeline (data/
        # stream.py): the loader's whole position rides the
        # checkpoint, so restarts and elastic resizes resume
        # mid-epoch without replaying or skipping a sample.
        from distributed_training_tpu.data import (StreamingDataLoader,
                                                   build_stream_sources)
        if cfg.train.eval_fraction > 0:
            raise ValueError(
                "train.eval_fraction is not supported with "
                "train.data_sources (the stream has no held-out "
                "split); set eval_fraction=0")
        sources = build_stream_sources(
            cfg.train.data_sources,
            defaults={"size": cfg.train.dataset_size,
                      "seed": cfg.train.seed})
        loader = StreamingDataLoader(
            sources, rt,
            batch_size=cfg.train.batch_size,
            pack_len=cfg.train.pack_seq_len,
            shuffle=cfg.train.shuffle,
            seed=cfg.train.seed,
            steps_per_epoch=cfg.train.max_steps_per_epoch,
            data_retries=cfg.train.data_retries,
            fault_injector=fault_injector,
        )
    else:
        dataset = build_dataset(
            cfg.train.dataset,
            _defaults={"size": cfg.train.dataset_size,
                       "seed": cfg.train.seed},
            **cfg.train.dataset_kwargs,
        )
        if cfg.train.eval_fraction > 0:
            from distributed_training_tpu.data.datasets import (
                train_eval_split,
            )
            dataset, eval_ds = train_eval_split(
                dataset, cfg.train.eval_fraction, seed=cfg.train.seed,
                multiple_of=cfg.train.batch_size * rt.data_shard_count)
            eval_loader = ShardedDataLoader(
                eval_ds, rt, batch_size=cfg.train.batch_size,
                shuffle=False, seed=cfg.train.seed)
        loader = ShardedDataLoader(
            dataset, rt,
            batch_size=cfg.train.batch_size,
            shuffle=cfg.train.shuffle,
            seed=cfg.train.seed,
            drop_last=cfg.train.drop_last,
            max_steps_per_epoch=cfg.train.max_steps_per_epoch,
            data_retries=cfg.train.data_retries,
            fault_injector=fault_injector,
        )
    model_kwargs = dict(cfg.model.kwargs)
    # model-level dtype override wins over the training compute dtype
    model_dtype = model_kwargs.pop("dtype", cfg.train.dtype)
    model = build_model(cfg.model.name, loss=cfg.train.loss,
                        dtype=model_dtype, **model_kwargs)

    from distributed_training_tpu.resilience import supervisor as sup
    from distributed_training_tpu.utils.preemption import PreemptionGuard
    guard = PreemptionGuard.install()

    # Context-managed checkpointer: __exit__ runs wait() + close() on
    # EVERY exit path — preemption, watchdog stop, fault-injected
    # crash — so an in-flight async save is never dropped.
    with Checkpointer(cfg.train.snapshot_path,
                      fault_injector=fault_injector) as checkpointer:
        # Telemetry: an event stream on EVERY process (multi-host runs
        # write per-host streams the aggregator merges; docs/
        # observability.md), hang watchdog on every process too (hangs
        # are host-specific; each host writes its own postmortem
        # bundle).
        resumed = checkpointer.latest_step() is not None
        restart_count = int(os.environ.get(
            sup.ENV_RESTART_COUNT, "0") or 0)
        # Restored events for the anomaly detector's baseline replay,
        # read BEFORE the Telemetry below opens the stream (a fresh
        # run truncates it; a resumed run appends a new run_start —
        # either way the pre-restart records must be captured first).
        restored_events: list = []
        if (cfg.train.anomaly_detect and rt.is_coordinator
                and (resumed or restart_count > 0)):
            from distributed_training_tpu.telemetry.summarize import (
                load_jsonl)
            restored_events = load_jsonl(cfg.train.events_jsonl)
        # fresh only on a genuinely first incarnation: a supervised
        # restart that found NO checkpoint (crash before the first
        # save) must APPEND — truncating would destroy the crashed
        # segment's events and the recovery table's evidence.
        tel = telemetry_lib.install(telemetry_lib.Telemetry(
            events_jsonl=cfg.train.events_jsonl,
            enabled=True,
            fresh=not (resumed or restart_count > 0),
            start_step=checkpointer.latest_step() or 0,
            host_id=(rt.process_index
                     if rt.process_count > 1 or elastic_incarnation
                     else None)))
        # Clock-sync record: the runtime captured one barrier-anchored
        # timestamp per host at setup; emitting it into each stream is
        # what lets the offline aggregator put N host clocks on one
        # axis.
        tel.event("clock_sync", **rt.clock_sync_record())
        # Closed-loop diagnostics (telemetry/anomaly.py + incident.py),
        # coordinator-only: the online detector keeps rolling
        # median/MAD baselines over the event stream (pure host-side
        # observer — zero new device syncs), a sustained step-time
        # regression arms one in-run profile capture via the
        # profile_now drop file, and the incident recorder snapshots
        # the flight-recorder ring buffer into
        # <run_dir>/incidents/<ts>/ on anomaly / watchdog abort /
        # preemption. Baselines are rebuilt deterministically from the
        # restored stream on resume.
        detector = None
        incidents = None
        if cfg.train.anomaly_detect and rt.is_coordinator:
            from distributed_training_tpu.telemetry.anomaly import (
                AnomalyDetector)
            from distributed_training_tpu.telemetry.incident import (
                IncidentRecorder)
            detector = AnomalyDetector(
                telemetry=tel, run_dir=run_dir,
                window=cfg.train.anomaly_window,
                min_samples=cfg.train.anomaly_min_samples,
                threshold=cfg.train.anomaly_threshold,
                sustain=cfg.train.anomaly_sustain,
                autoprofile=cfg.train.anomaly_autoprofile,
                host=rt.process_index)
            if restored_events:
                n = detector.replay(restored_events)
                logger.info("anomaly baselines rebuilt from %d "
                            "restored event(s)", n)
            incidents = IncidentRecorder(
                run_dir, telemetry=tel, detector=detector,
                cooldown_s=cfg.train.incident_cooldown_s)
            tel.add_observer(detector.observe)
            tel.add_observer(incidents.observe)
        watchdog = None
        if cfg.train.watchdog_timeout_s > 0:
            watchdog = telemetry_lib.HangWatchdog(
                cfg.train.watchdog_timeout_s,
                os.path.join(host_dir, "postmortem"),
                telemetry=tel, abort=cfg.train.watchdog_abort)

        # In-run profiler capture + attribution (telemetry/
        # attribution.py): scheduled steps from train.profile_at plus
        # the drop-a-file trigger (<run_dir>/profile_now) for
        # already-running jobs. Coordinator-gated — the trace and the
        # attribution event are process-local, and one host's
        # timeline answers the fleet's question.
        from distributed_training_tpu.telemetry.attribution import (
            ProfileCapture)
        profile_capture = ProfileCapture(
            run_dir, at_steps=cfg.train.profile_at,
            n_steps=cfg.train.profile_steps,
            enabled=rt.is_coordinator)

        # Live metrics endpoint (telemetry/metrics_server.py),
        # coordinator-only: Prometheus exposition + /healthz off the
        # same Telemetry sink that writes events.jsonl. The bound
        # port is recorded in <run_dir>/metrics.port for tooling.
        metrics_server = None
        if cfg.train.metrics_port > 0 and rt.is_coordinator:
            from distributed_training_tpu.telemetry.metrics_server \
                import MetricsServer
            ds = getattr(loader, "dataset", None)
            tokens_per_sample = (getattr(ds, "seq_len", None)
                                 or cfg.train.pack_seq_len or 1)
            metrics_server = MetricsServer(
                cfg.train.metrics_port, telemetry=tel,
                tokens_per_step=loader.global_batch
                * tokens_per_sample,
                stall_timeout_s=cfg.train.watchdog_timeout_s,
                info={"world_size": rt.process_count,
                      "incarnation": restart_count}).start()
            if metrics_server is not None:
                with open(os.path.join(run_dir, "metrics.port"),
                          "w", encoding="utf-8") as pf:
                    pf.write(f"{metrics_server.port}\n")

        trainer = Trainer(cfg, rt, model, loader, checkpointer,
                          preemption_guard=guard,
                          eval_loader=eval_loader,
                          watchdog=watchdog,
                          fault_injector=fault_injector,
                          profile_capture=profile_capture)
        if (trainer.epochs_run > 0 or trainer.global_step > 0
                or restart_count > 0):
            # Recovery evidence: which step this incarnation picked up
            # from, and which supervisor incarnation it is (the
            # summarizer's recovery table joins these with run_start
            # markers to compute steps-lost and time-to-recover).
            # Emitted even on a fresh start when this IS a restart
            # incarnation (crash before the first checkpoint) — the
            # recovery table must not undercount those.
            # Cursor evidence (docs/data.md): the restored pipeline
            # position + realized mixture ride the resume event, so
            # the summarizer's recovery table can PROVE exactly-once
            # (samples replayed = step*global_batch - samples_consumed
            # must be 0, and 0 the other way for skips).
            cursor_info = {}
            if hasattr(loader, "state_dict"):
                data_state = loader.state_dict()
                cursor_info = {
                    "samples_consumed":
                        data_state.get("samples_consumed"),
                    "global_batch": loader.global_batch,
                    "data_skips": data_state.get("skipped", 0),
                }
                # Mixture evidence only once something was consumed:
                # a fresh-start restart incarnation (crash before the
                # first save) has realized weights of all zeros, and
                # the summarizer would render that as a large bogus
                # mixture drift on a zero-consumption incident.
                if data_state.get("samples_consumed"):
                    for k in ("realized_mixture", "target_mixture"):
                        if data_state.get(k):
                            cursor_info[k] = data_state[k]
            tel.event("resume", step=trainer.global_step,
                      epoch=trainer.epochs_run,
                      restarts=restart_count,
                      world_size=rt.process_count,
                      evicted_hosts=evicted_hosts,
                      **cursor_info)
        try:
            if cfg.train.profile_dir:
                from distributed_training_tpu.utils import profiler
                with profiler.trace(cfg.train.profile_dir,
                                    host_only_on_coordinator=True,
                                    process_index=rt.process_index):
                    summary = trainer.train()
            else:
                summary = trainer.train()
        finally:
            if incidents is not None and guard.should_stop:
                # Preemption incident: the drain path saved a final
                # checkpoint; the bundle records what the run looked
                # like when the platform pulled the machine.
                incidents.record(
                    "preemption",
                    reason="preemption/stop signal observed; "
                           "stopping at a checkpoint boundary")
            if watchdog is not None:
                watchdog.stop()
            if metrics_server is not None:
                metrics_server.stop()
            profile_capture.abort()  # run ended mid-capture window
            tel.close()
    if rt.is_coordinator:
        logger.info("training done: %s", summary)
    # Exit-status sentinel for the restart supervisor: a preempted run
    # exits 0 after its final save just like a completed one — only
    # this record tells the supervisor to relaunch vs. stand down. A
    # coordinated eviction also exits 0; its host_lost sentinel names
    # the evictee so the elastic supervisor shrinks around it.
    # No-op when unsupervised (no DTT_EXIT_SENTINEL in env).
    evict = trainer.straggler.evict_request
    if evict is not None:
        sup.write_exit_status(
            sup.HOST_LOST, step=trainer.global_step,
            epochs_run=trainer.epochs_run,
            lost_host=evict["host"], reason=evict.get("reason"))
    else:
        sup.write_exit_status(
            sup.PREEMPTED if guard.should_stop else sup.COMPLETED,
            step=trainer.global_step, epochs_run=trainer.epochs_run)
    return 0


if __name__ == "__main__":
    sys.exit(main())
