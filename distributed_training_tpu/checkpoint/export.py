"""CLI: consolidate an existing Orbax checkpoint into one file.

Offline counterpart of ``train.gather_on_save`` — point it at a
checkpoint directory the trainer wrote and get the single portable
msgpack artifact (checkpoint/consolidate.py format) without
reconstructing the model or mesh. Single-process tool: it restores
shards to host memory, so it is meant for a workstation with enough
RAM, not a pod (use gather_on_save there — its gather stays sharded
until the collective).

    python -m distributed_training_tpu.checkpoint.export \
        --ckpt outputs/default/checkpoints --out model.msgpack
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def restore_step_local(ckpt_dir: str, step: int | None = None
                       ) -> tuple[dict, int]:
    """Restore one checkpoint step's full state onto the LOCAL default
    device via the checkpoint's own tree metadata — NOT the saved
    shardings, so a pod checkpoint opens on any topology (usually a
    single host). Returns (state, step); ``step=None`` → newest.
    Shared by the export CLI and the generation CLI."""
    import jax
    import orbax.checkpoint as ocp
    from jax.sharding import SingleDeviceSharding

    ckpt_dir = os.path.abspath(ckpt_dir)
    if step is None:
        steps = sorted(int(d) for d in os.listdir(ckpt_dir)
                       if d.isdigit())
        if not steps:
            raise FileNotFoundError(
                f"no checkpoint steps found under {ckpt_dir}")
        step = steps[-1]
    state_path = os.path.join(ckpt_dir, str(step), "state")
    if not os.path.isdir(state_path):
        raise FileNotFoundError(
            f"checkpoint step {step} not found in {ckpt_dir} "
            f"({state_path} does not exist)")

    dev = jax.devices()[0]
    ckptr = ocp.PyTreeCheckpointer()
    # Orbax API drift: PyTreeCheckpointer.metadata() returns the tree
    # metadata directly on the version pinned here; newer releases
    # wrap it in StepMetadata(item_metadata=...). Accept both.
    meta = ckptr.metadata(state_path)
    item = getattr(meta, "item_metadata", None)
    tree = getattr(item, "tree", item) if item is not None else meta
    restore_args = jax.tree.map(
        lambda _m: ocp.ArrayRestoreArgs(
            sharding=SingleDeviceSharding(dev)), tree)
    state = ckptr.restore(
        state_path,
        args=ocp.args.PyTreeRestore(restore_args=restore_args))
    return state, int(step)


def _plan_provenance(ckpt_dir: str, plan: str | None) -> dict | None:
    """The ``sharding_plan`` stamp for the artifact meta: the source
    run's plan NAME + FINGERPRINT, so a serving stack
    (serving/disagg.py WeightStore) can refuse to lay these weights
    out when the committed plan has been regenerated since export.

    ``plan``: None → auto-detect from the run's resolved_config.yaml
    (the directory above ``ckpt_dir``), absent/unpinned → no stamp
    (legacy shape — loads with a warning downstream); "none" →
    explicitly no stamp; anything else → that plan name/path."""
    import yaml

    name = plan
    if name is None:
        cfg_path = os.path.join(os.path.dirname(ckpt_dir),
                                "resolved_config.yaml")
        if not os.path.exists(cfg_path):
            return None
        with open(cfg_path) as f:
            resolved = yaml.safe_load(f) or {}
        name = (resolved.get("train") or {}).get("sharding_plan") or ""
        if not name:
            return None
    if name == "none":
        return None
    from distributed_training_tpu.parallel.planner import load_plan
    p = load_plan(name)
    return {"name": p.name, "fingerprint": p.fingerprint()}


# Public name: callers publishing weights at runtime (the hot-swap
# path — Engine.swap_weights provenance gate) need the same stamp the
# export CLI writes, from the same implementation, so the two can
# never disagree. The underscore name stays for the existing pins.
plan_provenance = _plan_provenance


def export(ckpt_dir: str, out_path: str, step: int | None = None,
           plan: str | None = None,
           quantize: str | None = None) -> dict:
    import jax

    # Site customizations may pin the platform at interpreter start,
    # overriding the env var — re-apply it so JAX_PLATFORMS=cpu really
    # does keep this host-side tool off the accelerator.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    if quantize not in (None, "int8"):
        raise ValueError(
            f"unsupported --quantize '{quantize}' (supported: int8)")
    ckpt_dir = os.path.abspath(ckpt_dir)
    state, step = restore_step_local(ckpt_dir, step)

    meta: dict = {}
    meta_file = os.path.join(ckpt_dir, str(step), "meta", "metadata")
    if os.path.exists(meta_file):
        with open(meta_file) as f:
            meta = json.load(f) or {}
    meta.setdefault("step", int(step))
    prov = _plan_provenance(ckpt_dir, plan)
    if prov is not None:
        meta["sharding_plan"] = prov

    state = jax.tree.map(jax.device_get, state)
    if quantize == "int8":
        # Weight-only int8 serving artifact: the params subtree goes
        # per-channel int8 (serving/disagg.py quantize_params_int8);
        # the stamp is load-bearing — WeightStore validates it and
        # the parity tests gate the layout against fp32 logits.
        from distributed_training_tpu.serving.disagg import (
            quantize_params_int8)
        if "params" in state:
            state = dict(state)
            state["params"] = quantize_params_int8(state["params"])
        else:
            state = quantize_params_int8(state)
        meta["quantization"] = "int8"

    from distributed_training_tpu.checkpoint.consolidate import (
        write_artifact,
    )
    n = write_artifact(out_path, state, meta)
    return {"out": out_path, "step": int(step), "bytes": n,
            "quantization": quantize or "none"}


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ckpt", required=True,
                   help="Orbax checkpoint directory (snapshot_path)")
    p.add_argument("--out", required=True, help="output .msgpack path")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    p.add_argument("--plan", default=None,
                   help="sharding-plan provenance to stamp into the "
                        "artifact meta (default: auto-detect the "
                        "run's train.sharding_plan; 'none' to skip)")
    p.add_argument("--quantize", default=None, choices=("int8",),
                   help="weight-only quantization for the exported "
                        "params (per-channel int8; stamped into the "
                        "artifact meta for WeightStore validation)")
    args = p.parse_args(argv)
    print(json.dumps(export(args.ckpt, args.out, args.step,
                            plan=args.plan, quantize=args.quantize)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
