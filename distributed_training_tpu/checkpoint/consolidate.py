"""Gathered single-artifact checkpoint export (FULL_STATE_DICT analogue).

The day-to-day checkpoint path is sharded Orbax (manager.py) — scalable
and topology-tolerant. What it doesn't give you is ONE portable file to
hand to an inference stack or archive. The reference's FSDP strategy had
exactly this export (FULL_STATE_DICT gather with rank0-only write,
/root/reference/src/dist_strategy/fsdp_strategy.py:31-36) — and hung,
because only rank 0 entered the collective (SURVEY.md §8 B6).

Here the contract is explicit: ``export_consolidated`` is COLLECTIVE —
every process calls it (the gather is an all-gather over the mesh),
process 0 alone writes, and everyone leaves together. The artifact is a
single msgpack file of the pure nested-dict state (flax serialization),
loadable anywhere — no mesh, no sharding metadata, no orbax layout.
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import Any

import jax
import numpy as np
from flax import serialization
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


def gather_full_state(state: Any, mesh: Mesh) -> Any:
    """Gather every leaf to a fully-replicated host copy.

    COLLECTIVE: every process must call (device_put to the replicated
    sharding is an all-gather across the mesh). Returns a NumPy pytree.
    """
    replicated = NamedSharding(mesh, P())

    def to_host(x: Any) -> np.ndarray:
        if isinstance(x, jax.Array) and not x.is_fully_replicated:
            x = jax.device_put(x, replicated)
        return np.asarray(x)

    return jax.tree.map(to_host, state)


def write_artifact(path: str, state: Any, meta: dict | None) -> int:
    """Serialize ``{"state", "meta"}`` (the load_consolidated contract)
    and write it atomically (temp file + rename). Returns byte count.
    Shared by the collective export and the offline CLI so the payload
    format cannot drift between them."""
    payload = {
        "state": serialization.to_state_dict(state),
        "meta": dict(meta or {}),
    }
    blob = serialization.msgpack_serialize(payload)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return len(blob)


def export_consolidated(path: str, state: Any, mesh: Mesh,
                        meta: dict | None = None) -> str:
    """Write the full (gathered) state as ONE portable msgpack file.

    COLLECTIVE: call from every process; process 0 writes (atomically:
    temp file + rename), all processes synchronize before returning so
    no process races ahead of the durable artifact.
    """
    full = gather_full_state(state, mesh)
    if jax.process_index() == 0:
        n = write_artifact(path, full, meta)
        logger.info("consolidated checkpoint exported: %s (%d bytes)",
                    path, n)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("consolidated_export")
    return path


def load_consolidated(path: str) -> tuple[Any, dict]:
    """Read a consolidated artifact back as (state_dict pytree of NumPy
    arrays, meta). Host-local — no mesh needed; shard the result onto
    any topology with ``jax.device_put`` / ``from_state_dict``."""
    with open(path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    return payload["state"], dict(payload.get("meta") or {})
