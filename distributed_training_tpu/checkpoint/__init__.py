"""Checkpointing subsystem (Orbax-backed)."""

from distributed_training_tpu.checkpoint.manager import (  # noqa: F401
    Checkpointer,
)
