"""Checkpointing subsystem (Orbax-backed + consolidated export)."""

from distributed_training_tpu.checkpoint.consolidate import (  # noqa: F401
    export_consolidated,
    load_consolidated,
)
from distributed_training_tpu.checkpoint.manager import (  # noqa: F401
    Checkpointer,
)
