"""Sharded async checkpointing via Orbax.

Replaces the reference's ``ModelCheckpoint`` + per-strategy serialization
(src/distributed_trainer.py:73-105; ddp_strategy.py:23-32;
fsdp_strategy.py:28-46) with one path that is correct for every layout:

- **sharded save**: each host writes exactly its shards (the scalable
  successor of the FSDP FULL_STATE_DICT gather, which OOMs at 7B and
  deadlocked in the reference because only rank 0 entered the collective
  — SURVEY.md §8 B6). Every process calls ``save``; Orbax coordinates.
- **async**: training continues while the previous checkpoint drains to
  storage (preemption-friendly, the idiomatic TPU pattern).
- **full state**: params + optimizer state + step + epoch metadata; the
  reference saved params only, silently resetting momentum on resume
  (§5.4).
- **resume-if-exists**: ``restore_latest`` mirrors the reference's
  load-on-startup contract (src/distributed_trainer.py:97-105) but
  restores each shard directly to its device (topology-change-tolerant:
  Orbax reshards when the mesh differs from the one that saved).
"""

from __future__ import annotations

import logging
from typing import Any

import orbax.checkpoint as ocp

from distributed_training_tpu import telemetry

logger = logging.getLogger(__name__)


class Checkpointer:
    """Thin lifecycle wrapper over ``ocp.CheckpointManager``."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True) -> None:
        self.directory = directory
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            create=True,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(directory, options=options)

    # -- save --------------------------------------------------------------

    def save(self, step: int, state: Any, meta: dict | None = None,
             force: bool = False) -> bool:
        """Collective sharded save. Call from EVERY process.

        The ``ckpt_save`` span measures the *blocking* part only —
        with async checkpointing the drain to storage continues in
        the background (that tail is what ``ckpt_wait`` captures)."""
        with telemetry.span("ckpt_save", step=step):
            saved = self._mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    meta=ocp.args.JsonSave(meta or {}),
                ),
                force=force,
            )
        if saved:
            logger.info("checkpoint saved at step %d -> %s", step,
                        self.directory)
        return bool(saved)

    # -- restore -----------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_latest(self, abstract_state: Any
                       ) -> tuple[Any, dict] | None:
        """Restore the newest checkpoint into the given sharded layout,
        or None if no checkpoint exists (fresh start — parity:
        src/distributed_trainer.py:100-101)."""
        step = self._mgr.latest_step()
        if step is None:
            return None
        with telemetry.span("ckpt_restore", step=step):
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract_state),
                    meta=ocp.args.JsonRestore(),
                ),
            )
        logger.info("restored checkpoint step %d from %s", step,
                    self.directory)
        return restored["state"], dict(restored["meta"] or {})

    # -- lifecycle ---------------------------------------------------------

    def wait(self) -> None:
        """Block until async saves are durable (call before exit)."""
        with telemetry.span("ckpt_wait"):
            self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
