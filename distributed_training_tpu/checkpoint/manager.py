"""Sharded async checkpointing via Orbax.

Replaces the reference's ``ModelCheckpoint`` + per-strategy serialization
(src/distributed_trainer.py:73-105; ddp_strategy.py:23-32;
fsdp_strategy.py:28-46) with one path that is correct for every layout:

- **sharded save**: each host writes exactly its shards (the scalable
  successor of the FSDP FULL_STATE_DICT gather, which OOMs at 7B and
  deadlocked in the reference because only rank 0 entered the collective
  — SURVEY.md §8 B6). Every process calls ``save``; Orbax coordinates.
- **async**: training continues while the previous checkpoint drains to
  storage (preemption-friendly, the idiomatic TPU pattern).
- **full state**: params + optimizer state + step + epoch metadata; the
  reference saved params only, silently resetting momentum on resume
  (§5.4).
- **resume-if-exists**: ``restore_latest`` mirrors the reference's
  load-on-startup contract (src/distributed_trainer.py:97-105) but
  restores each shard directly to its device (topology-change-tolerant:
  Orbax reshards when the mesh differs from the one that saved).
- **integrity + fallback** (resilience/integrity.py): every committed
  save gets a per-file checksum manifest; ``restore_latest`` verifies
  and, on mismatch or an orbax restore failure, QUARANTINES the bad
  step (``step_<N>.corrupt`` + ``ckpt_quarantined`` event) and falls
  back to the next-older good checkpoint instead of crashing the run.
  A run with no restorable checkpoint starts fresh — the crash-
  restart-resume contract never dies on a half-written artifact.

Use as a context manager (the train CLI does): ``__exit__`` runs
``wait()`` + ``close()`` on EVERY exit path, so an in-flight async
save is never dropped — not on preemption, not on a fault-injected
crash.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any

import jax
import orbax.checkpoint as ocp

from distributed_training_tpu import telemetry
from distributed_training_tpu.resilience import integrity

logger = logging.getLogger(__name__)


class Checkpointer:
    """Thin lifecycle wrapper over ``ocp.CheckpointManager``."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True, verify_integrity: bool = True,
                 fault_injector=None) -> None:
        self.directory = directory
        self.verify_integrity = verify_integrity
        self._async = async_save
        self._injector = fault_injector
        # Steps saved but not yet manifested. With async saves a step
        # is only safe to hash once COMMITTED (orbax finalizes with an
        # atomic rename); commit points are "the next save() returns"
        # (orbax drains the previous save first) and wait().
        self._pending_manifest: set[int] = set()
        self._manifest_thread: threading.Thread | None = None
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            create=True,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(directory, options=options)

    # -- lifecycle (context manager: never drop an in-flight save) ---------

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        try:
            self.wait()
        finally:
            self.close()
        return False

    # -- save --------------------------------------------------------------

    def save(self, step: int, state: Any, meta: dict | None = None,
             force: bool = False) -> bool:
        """Collective sharded save. Call from EVERY process.

        The ``ckpt_save`` span measures the *blocking* part only —
        with async checkpointing the drain to storage continues in
        the background (that tail is what ``ckpt_wait`` captures)."""
        with telemetry.span("ckpt_save", step=step):
            saved = self._mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    meta=ocp.args.JsonSave(meta or {}),
                ),
                force=force,
            )
        if saved:
            logger.info("checkpoint saved at step %d -> %s", step,
                        self.directory)
            self._pending_manifest.add(step)
        # Everything except a still-draining async ``step`` is now
        # committed (orbax waits for the previous async save before
        # starting a new one) — manifest it.
        self._flush_manifests(in_flight=step if self._async else None)
        # Coordinator only: on shared storage N hosts XOR-flipping the
        # same bytes would undo each other (even count = no damage).
        # Filesystem-only hook, no collective — safe to gate by host.
        if (self._injector is not None and saved
                and jax.process_index() == 0):
            self._injector.on_checkpoint_saved(step, self.directory)
        return bool(saved)

    def _flush_manifests(self, in_flight: int | None = None,
                         blocking: bool = False) -> None:
        """Write checksum manifests for every pending COMMITTED step.
        Process 0 only — the manifest lives on the shared filesystem
        and N hosts hashing the same files is pure waste.

        Hashing a multi-host checkpoint is a full re-read of the
        step's bytes; doing it inline in save() would stall every
        host's step loop behind the coordinator. So the hash runs in
        a background thread, one flush at a time (the join below keeps
        manifests landing in step order), joined for real at wait()/
        ``__exit__``. With a fault injector armed it stays synchronous
        — ``on_checkpoint_saved`` must only ever corrupt bytes whose
        manifest already exists, or verification would bless the
        damage."""
        committed = sorted(s for s in self._pending_manifest
                           if s != in_flight)
        self._pending_manifest.difference_update(committed)
        if blocking:
            # A blocking flush must also drain an in-flight background
            # hash even when nothing NEW is pending.
            self._join_manifest_flusher()
        if (not committed or not self.verify_integrity
                or jax.process_index() != 0):
            return
        self._join_manifest_flusher()

        def _write(steps=tuple(committed)) -> None:
            for step in steps:
                step_dir = os.path.join(self.directory, str(step))
                if os.path.isdir(step_dir):
                    integrity.write_manifest(step_dir)

        if blocking or self._injector is not None:
            _write()
        else:
            self._manifest_thread = threading.Thread(
                target=_write, name="ckpt-manifest", daemon=True)
            self._manifest_thread.start()

    def _join_manifest_flusher(self) -> None:
        t = self._manifest_thread
        if t is not None:
            t.join()
            self._manifest_thread = None

    # -- restore -----------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_latest(self, abstract_state: Any
                       ) -> tuple[Any, dict] | None:
        """Restore the newest GOOD checkpoint into the given sharded
        layout, or None if none is restorable (fresh start — parity:
        src/distributed_trainer.py:100-101).

        Fallback chain: a step that fails manifest verification or
        raises during the orbax restore is quarantined (rename to
        ``step_<N>.corrupt`` + ``ckpt_quarantined`` event — bytes are
        preserved for forensics) and the next-older step is tried.
        Bounded by the number of checkpoints on disk."""
        while True:
            step = self._mgr.latest_step()
            if step is None:
                return None
            step_dir = os.path.join(self.directory, str(step))
            if self.verify_integrity:
                verified, problems = integrity.verify_manifest(step_dir)
                if problems:
                    self._quarantine(step, problems)
                    continue
                if not verified:
                    logger.warning(
                        "checkpoint step %d has no integrity manifest "
                        "(pre-manifest save); restoring unverified",
                        step)
            try:
                with telemetry.span("ckpt_restore", step=step):
                    restored = self._mgr.restore(
                        step,
                        args=ocp.args.Composite(
                            state=ocp.args.StandardRestore(
                                abstract_state),
                            meta=ocp.args.JsonRestore(),
                        ),
                    )
            except Exception as e:  # noqa: BLE001 — fallback chain:
                # quarantine (rename, nothing deleted) + try the next
                # older step; an abstract-tree bug would surface as
                # every step failing, loudly, with the dirs preserved.
                logger.exception(
                    "orbax restore of step %d failed; quarantining "
                    "and falling back", step)
                self._quarantine(
                    step, [f"restore raised {type(e).__name__}: {e}"])
                continue
            logger.info("restored checkpoint step %d from %s", step,
                        self.directory)
            return restored["state"], dict(restored["meta"] or {})

    def _quarantine(self, step: int, problems: list[str]) -> None:
        integrity.quarantine_step(self.directory, step,
                                  problems=problems)
        # The manager caches its step list; after the rename it must
        # rescan or latest_step() keeps returning the condemned step.
        self._mgr.reload()

    # -- lifecycle ---------------------------------------------------------

    def wait(self) -> None:
        """Block until async saves are durable — manifests included
        (call before exit)."""
        with telemetry.span("ckpt_wait"):
            self._mgr.wait_until_finished()
        self._flush_manifests(blocking=True)

    def close(self) -> None:
        self._join_manifest_flusher()
        self._mgr.close()
