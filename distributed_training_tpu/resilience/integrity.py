"""Checkpoint integrity: checksum manifests, quarantine, fallback scan.

Orbax commits a checkpoint atomically (tmp dir + rename), so a step
directory that EXISTS was fully written — but nothing guards against
later damage: bit rot, a truncating copy, an overzealous cleanup job,
or a fault-injected corruption (faults.py ``corrupt_ckpt``). TorchTitan
treats checkpoint durability as table stakes for production
pretraining; this module is that stance for this repo:

- ``write_manifest(step_dir)`` — a ``manifest.dtt.json`` of per-file
  sha256 + size for every file in a COMMITTED step directory, written
  atomically (tmp + rename) so a torn manifest cannot exist.
- ``verify_manifest(step_dir)`` — recompute and diff. Pre-manifest
  (legacy) checkpoints verify as "unverified but not condemned": the
  fallback chain must not quarantine every checkpoint written before
  this module existed.
- ``quarantine_step(dir, step, problems)`` — rename ``<dir>/<N>`` to
  ``<dir>/step_<N>.corrupt`` (orbax's step scan ignores non-numeric
  names) and emit a ``ckpt_quarantined`` telemetry event. Rename-only:
  the bytes stay on disk for forensics / manual recovery.
- ``latest_step_on_disk(dir)`` / ``checkpoint_steps_on_disk(dir)`` —
  orbax-free step scan for the supervisor's crash-loop detection
  (the supervisor must not import orbax in the launcher parent).

Multi-host: manifests are written by process 0 only (shared
filesystem; N hosts hashing the same files is waste). Verification is
read-only and deterministic on every host; quarantine renames tolerate
losing the race to another host (the rename is idempotent-by-outcome).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time

logger = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.dtt.json"
MANIFEST_SCHEMA = 1
QUARANTINE_SUFFIX = ".corrupt"


# ---------------------------------------------------------------------------
# step scanning (orbax-free: the supervisor parent uses this)
# ---------------------------------------------------------------------------


def checkpoint_steps_on_disk(directory: str) -> list[int]:
    """Committed checkpoint steps under ``directory``, ascending.

    Orbax's layout is one directory per step named ``<N>``; in-flight
    saves live in ``<N>.orbax-checkpoint-tmp-*`` (non-numeric, so
    excluded here exactly as orbax's own scan excludes them), and
    quarantined steps are ``step_<N>.corrupt`` (also non-numeric)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    steps = [int(n) for n in names
             if n.isdigit() and os.path.isdir(os.path.join(directory, n))]
    return sorted(steps)


def latest_step_on_disk(directory: str) -> int | None:
    """Newest committed step, or None. (The supervisor's progress
    check uses ``checkpoint_steps_on_disk`` directly — it needs the
    SET of steps, since a quarantine can lower the maximum while the
    run still progresses.)"""
    steps = checkpoint_steps_on_disk(directory)
    return steps[-1] if steps else None


# ---------------------------------------------------------------------------
# manifests
# ---------------------------------------------------------------------------


def _iter_files(step_dir: str):
    """Yield (relpath, abspath) for every regular file under
    ``step_dir``, skipping the manifest itself. Sorted for a
    deterministic manifest."""
    out = []
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            path = os.path.join(root, name)
            rel = os.path.relpath(path, step_dir)
            if rel == MANIFEST_NAME:
                continue
            out.append((rel, path))
    return sorted(out)


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def file_checksums(step_dir: str) -> dict[str, dict]:
    """Per-file ``{"bytes": N, "sha256": hex}`` for the step dir."""
    return {rel: {"bytes": os.path.getsize(path),
                  "sha256": _sha256(path)}
            for rel, path in _iter_files(step_dir)}


def write_manifest(step_dir: str) -> str:
    """Write the checksum manifest atomically; returns its path.

    Call ONLY on a committed (finalized) step directory — hashing an
    in-flight orbax write would freeze a half-written state."""
    manifest = {
        "schema": MANIFEST_SCHEMA,
        "t": time.time(),
        "files": file_checksums(step_dir),
    }
    path = os.path.join(step_dir, MANIFEST_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def verify_manifest(step_dir: str) -> tuple[bool, list[str]]:
    """Check the step dir against its manifest.

    Returns ``(verified, problems)``:

    - ``(True, [])`` — manifest present, every file matches.
    - ``(False, [])`` — NO manifest (legacy/pre-manifest checkpoint):
      unverifiable, but not evidence of corruption — the caller
      restores it with a warning rather than quarantining.
    - ``(_, [problems...])`` — mismatches (missing/extra/resized/
      altered files, or an unreadable manifest): quarantine material.
    """
    mpath = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return False, []
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        expected = manifest["files"]
    except (ValueError, KeyError, OSError) as e:
        return True, [f"unreadable manifest: {type(e).__name__}: {e}"]
    problems: list[str] = []
    actual = dict(_iter_files(step_dir))
    for rel in sorted(set(expected) - set(actual)):
        problems.append(f"missing file: {rel}")
    for rel in sorted(set(actual) - set(expected)):
        problems.append(f"unexpected file: {rel}")
    for rel in sorted(set(expected) & set(actual)):
        want = expected[rel]
        size = os.path.getsize(actual[rel])
        if size != want["bytes"]:
            problems.append(f"size mismatch: {rel} "
                            f"({size} != {want['bytes']})")
            continue  # a resize already condemns; skip the hash work
        if _sha256(actual[rel]) != want["sha256"]:
            problems.append(f"checksum mismatch: {rel}")
    return True, problems


# ---------------------------------------------------------------------------
# quarantine
# ---------------------------------------------------------------------------


def quarantine_step(directory: str, step: int,
                    problems: list[str] | None = None) -> str | None:
    """Move a condemned step out of orbax's sight: ``<dir>/<N>`` →
    ``<dir>/step_<N>.corrupt`` (``.2``, ``.3``... if a previous
    incarnation already quarantined an N). Emits a
    ``ckpt_quarantined`` telemetry event. Returns the new path, or
    None if the step dir was already gone (another process won the
    rename race — same outcome, not an error)."""
    src = os.path.join(directory, str(step))
    dst = os.path.join(directory, f"step_{step}{QUARANTINE_SUFFIX}")
    n = 1
    while os.path.exists(dst):
        n += 1
        dst = os.path.join(
            directory, f"step_{step}{QUARANTINE_SUFFIX}.{n}")
    try:
        os.rename(src, dst)
    except FileNotFoundError:
        logger.warning("step %d already quarantined by another process",
                       step)
        return None
    logger.error("QUARANTINED corrupt checkpoint step %d -> %s (%s)",
                 step, dst, "; ".join((problems or ["unspecified"])[:5]))
    from distributed_training_tpu import telemetry
    telemetry.event("ckpt_quarantined", step=step, path=dst,
                    problems=(problems or [])[:10])
    return dst
