"""Resilience: crash-restart-resume made real.

The reference repo's failure model is crash-restart-resume (SURVEY.md
§5.3: bounded rendezvous retries at bring-up, checkpoint recovery on
restart), and utils/preemption.py already covers the COOPERATIVE half
(SIGTERM → clean final save). This package supplies the other half:

- ``supervisor.py`` — a restart supervisor (used by ``launch/local.py
  --supervise``) that relaunches dead training processes with
  exponential backoff + jitter, classifies exits (completed /
  preempted / watchdog-abort / crash, via an exit-status sentinel the
  training process and the watchdog abort path write), and detects
  crash-loops by CHECKPOINT PROGRESS: an incarnation that commits a
  new on-disk step refunds the retry budget, one that doesn't burns
  it, so a deterministic step-N crash gives up fast.
- ``integrity.py`` — per-file checksum manifests written at every
  checkpoint save; restore verifies, quarantines a bad step
  (``step_<N>.corrupt``) and falls back to the next-older good
  checkpoint instead of crashing the run.
- ``faults.py`` — config-driven deterministic fault injection
  (``train.fault_plan="crash@40,sigterm@80,..."``), every trigger a
  pure function of the global step (the straggler.py discipline:
  multi-host injection cannot deadlock), which is what makes the two
  pillars above testable end-to-end on CPU. ``lose_host@N:host=K`` /
  ``slow_host@N:host=K:200ms`` drive the elastic paths.
- ``elastic.py`` — the shrink/grow world-size policy
  (``launch.local --supervise --elastic``): on a lost or evicted
  host, checkpoint, re-form the mesh at the surviving world size
  (resharded restore; per-host batch rescaled to preserve the global
  batch), continue, and grow back at a checkpoint boundary when
  capacity returns. Straggler verdicts (telemetry/straggler.py)
  escalate to coordinated evictions through the same path.

This ``__init__`` is deliberately import-free: the supervisor runs in
the LAUNCHER parent process and must not drag in orbax or the
telemetry stack on import (``from distributed_training_tpu.resilience
import supervisor`` adds nothing beyond what the package root already
loads). Event schema + failure model: docs/robustness.md.
"""
