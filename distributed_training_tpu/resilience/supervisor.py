"""Restart supervisor: relaunch dead training processes, bounded.

The missing half of the reference's crash-restart-resume failure model
(SURVEY.md §5.3): ``utils/preemption.py`` makes SIGTERM graceful and
the checkpoint layer makes restarts resumable, but nothing RESTARTED a
crashed process. ``launch/local.py --supervise`` drives this loop; the
same ``supervise()`` is the template a pod-level agent (one supervisor
per host VM) would run.

Three design points, per the issue spec:

- **Exit classification** — a supervised training process writes an
  exit-status sentinel (``write_exit_status``: "completed" /
  "preempted"; the hang-watchdog abort path writes
  "watchdog_abort" before its ``os._exit(42)``). The supervisor reads
  the sentinels and falls back to return-code heuristics (SIGTERM
  death = preemption) when a crash died too hard to write one.
- **Progress-refunded retry budget** — an incarnation that COMMITS A
  NEW checkpoint step refunds the budget to ``max_restarts``; one
  that doesn't burns one. (A new step, not a higher number than ever
  seen: a restore-time quarantine lowers the latest on-disk step
  while the run still advances from its usable base.) A
  deterministic step-N crash (same fault every incarnation, no new
  checkpoint) therefore exhausts the budget in ``max_restarts + 1``
  incarnations instead of looping forever, while a long healthy run
  survives any number of DISTINCT failures.
- **Exponential backoff + jitter** — per consecutive non-advancing
  failure, capped; deterministic given the seed (reproducible tests),
  jittered so a pod of supervisors doesn't reconnect in lockstep.

This module must stay importable in the launcher parent without
orbax/telemetry (progress scanning is the orbax-free
``integrity.checkpoint_steps_on_disk``); the telemetry sink is an
optional injected parameter.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable

from distributed_training_tpu.resilience import elastic as elastic_mod
from distributed_training_tpu.resilience.integrity import (
    checkpoint_steps_on_disk)

logger = logging.getLogger(__name__)

# Exit outcomes, worst-first. Sentinel files carry these in "outcome".
COMPLETED = "completed"
PREEMPTED = "preempted"
# One (or a strict subset) of the group's hosts was lost — evicted by
# a straggler verdict (clean exits + host_lost sentinels naming the
# evictee) or reclaimed/crashed under the survivors (launcher group
# report). Under an elastic policy this is the shrink trigger; without
# one it degrades to the crash/preempted budget rules.
HOST_LOST = "host_lost"
WATCHDOG_ABORT = "watchdog_abort"
CRASH = "crash"

# Keep in sync with telemetry/watchdog.py::HangWatchdog.EXIT_CODE —
# not imported, to keep this module telemetry-free in the parent.
WATCHDOG_EXIT_CODE = 42

ENV_SENTINEL = "DTT_EXIT_SENTINEL"
ENV_RESTART_COUNT = "DTT_RESTART_COUNT"


# ---------------------------------------------------------------------------
# exit-status sentinels (written by the CHILD, read by the supervisor)
# ---------------------------------------------------------------------------


def sentinel_path() -> str | None:
    """This process's own sentinel file, or None when unsupervised.

    The supervisor exports one base path per incarnation; each process
    of a (possibly multi-process) incarnation appends its pid so local
    pod simulations don't clobber each other's verdicts."""
    base = os.environ.get(ENV_SENTINEL)
    if not base:
        return None
    return f"{base}.pid{os.getpid()}.json"


def write_exit_status(outcome: str, **fields) -> str | None:
    """Record how this process is about to exit (atomic; no-op when
    unsupervised). Called by the train CLI on clean exits and by the
    watchdog abort path right before ``os._exit``."""
    path = sentinel_path()
    if path is None:
        return None
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump({"outcome": outcome, "pid": os.getpid(),
                   "t": time.time(), **fields}, f)
    os.replace(tmp, path)
    return path


def read_exit_statuses(base: str) -> list[dict]:
    """All sentinels an incarnation's processes left behind."""
    out = []
    for path in sorted(glob.glob(f"{base}.pid*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def classify_exit(returncode: int, statuses: list[dict]) -> str:
    """One outcome for the whole incarnation, worst report wins.

    Sentinels are authoritative when present (a preempted process
    exits 0 — only the sentinel distinguishes it from completion);
    return codes cover processes that died too hard to write one
    (SIGKILL, segfault, ``os._exit``)."""
    outcomes = {s.get("outcome") for s in statuses}
    if WATCHDOG_ABORT in outcomes or returncode == WATCHDOG_EXIT_CODE:
        return WATCHDOG_ABORT
    if HOST_LOST in outcomes:
        # A coordinated eviction exits CLEANLY (every host saves and
        # writes the sentinel naming the evictee) — only the sentinel
        # distinguishes it from completion/preemption.
        return HOST_LOST
    if returncode == 0:
        return PREEMPTED if PREEMPTED in outcomes else COMPLETED
    # 143/130: death by SIGTERM/SIGINT (launch.wait encodes signal
    # deaths as 128 + signum) — the external-preemption shape. Any
    # OTHER nonzero rc is a crash even when one process of the group
    # wrote a preempted sentinel: worst report wins, and a crash must
    # burn retry budget — a preemption verdict would refund it.
    if returncode in (143, 130):
        return PREEMPTED
    return CRASH


# ---------------------------------------------------------------------------
# restart policy
# ---------------------------------------------------------------------------


@dataclass
class RestartPolicy:
    """Budget + backoff knobs (CLI: ``--max-restarts``,
    ``--backoff-base-s``)."""

    max_restarts: int = 3
    backoff_base_s: float = 1.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    jitter: float = 0.2          # +/- fraction of the backoff
    seed: int = 0                # jitter stream (deterministic tests)

    def backoff_s(self, consecutive_failures: int) -> float:
        """Delay before the next restart after ``consecutive_failures``
        (>=1) non-advancing failures in a row. Exponential, capped,
        with deterministic +/-jitter."""
        n = max(1, consecutive_failures)
        base = min(self.backoff_max_s,
                   self.backoff_base_s * self.backoff_factor ** (n - 1))
        # Int seed only: tuple seeding raises TypeError on 3.11+.
        rng = random.Random(self.seed * 1_000_003 + n)
        return base * (1.0 + self.jitter * rng.uniform(-1.0, 1.0))


@dataclass
class Incident:
    """One supervised incarnation's outcome (the give-up summary).
    ``world_size``/``evicted`` record the topology the incarnation ran
    at (elastic runs; postmortems want the history), ``lost_hosts``
    which hosts it lost, ``elastic_action`` what the policy decided
    for the NEXT incarnation ("retry"/"shrink"/"grow")."""

    incarnation: int
    returncode: int
    outcome: str
    wall_s: float
    ckpt_step: int | None
    advanced: bool
    budget_after: int = 0
    backoff_s: float = 0.0
    world_size: int | None = None
    evicted: list[int] = field(default_factory=list)
    lost_hosts: list[int] = field(default_factory=list)
    elastic_action: str | None = None


@dataclass
class SuperviseResult:
    returncode: int
    incidents: list[Incident] = field(default_factory=list)

    @property
    def restarts(self) -> int:
        return max(0, len(self.incidents) - 1)

    def summary_lines(self) -> list[str]:
        lines = [f"supervisor: {len(self.incidents)} incarnation(s), "
                 f"{self.restarts} restart(s), final rc "
                 f"{self.returncode}"]
        for inc in self.incidents:
            lines.append(
                f"  #{inc.incarnation}: {inc.outcome} rc={inc.returncode}"
                f" wall={inc.wall_s:.1f}s ckpt_step={inc.ckpt_step}"
                f"{' (advanced)' if inc.advanced else ''}"
                f" budget={inc.budget_after}"
                + (f" world={inc.world_size}"
                   if inc.world_size is not None else "")
                + (f" lost={inc.lost_hosts}" if inc.lost_hosts else "")
                + (f" -> {inc.elastic_action}"
                   if inc.elastic_action
                   and inc.elastic_action != "retry" else ""))
        return lines


# ---------------------------------------------------------------------------
# the loop
# ---------------------------------------------------------------------------


def supervise(run_incarnation: Callable[[dict[str, str]], object],
              *,
              policy: RestartPolicy | None = None,
              state_dir: str,
              ckpt_dir: str | None = None,
              telemetry=None,
              sleep: Callable[[float], None] = time.sleep,
              should_stop: Callable[[], bool] | None = None,
              elastic: "elastic_mod.ElasticPolicy | None" = None,
              on_incident: Callable[[Incident], None] | None = None,
              ) -> SuperviseResult:
    """Run ``run_incarnation(extra_env)`` until completion or budget
    exhaustion; returns the final rc plus the incident log.

    ``run_incarnation`` launches ONE incarnation of the training job
    (all its processes) with the given extra environment merged in,
    blocks, and returns the group's exit code — for the local
    launcher that is ``launch_local(...)`` + ``wait(...)``. It may
    instead return an ``elastic.GroupReport`` (the launcher's
    ``wait_report``); the per-process detail is what lets an elastic
    policy tell "host 2 died" from "everything died".

    ``ckpt_dir`` enables progress-based budget refunds; without it
    every non-completed exit burns budget (strictly bounded either
    way). ``telemetry`` (an events.Telemetry or None) records one
    ``restart`` event per relaunch, an ``elastic`` event per world
    resize, and a ``supervisor_give_up`` event on budget exhaustion.
    ``should_stop`` (checked between incarnations) lets the caller end
    supervision from the outside — the launcher's own preemption path.

    ``elastic`` (an ``elastic.ElasticPolicy``) turns host losses into
    world resizes instead of fixed-size retries: the next incarnation's
    world size and evicted-host set ride the env
    (``DTT_ELASTIC_WORLD`` / ``DTT_ELASTIC_EVICTED``); a successful
    shrink or grow refunds the budget and resets the backoff (the
    reconfiguration IS the recovery). ``on_incident`` is called with
    each finalized Incident — the launcher writes per-attempt
    summaries from it."""
    policy = policy or RestartPolicy()
    os.makedirs(state_dir, exist_ok=True)
    result = SuperviseResult(returncode=0)
    budget = policy.max_restarts
    streak = 0  # consecutive failures without checkpoint progress
    incarnation = 0
    estate = (elastic_mod.ElasticState(world=elastic.base_world)
              if elastic is not None else None)
    elastic_dir = os.path.join(state_dir, "elastic")

    def _notify(incident: Incident) -> None:
        if on_incident is not None:
            try:
                on_incident(incident)
            except Exception:  # noqa: BLE001 — a summary-writing
                # callback must never take down the restart loop.
                logger.exception("on_incident callback failed")

    while True:
        base = os.path.join(state_dir, f"exit_{incarnation}")
        # A previous supervisor run in the same state_dir (log dirs
        # default to a constant path) left sentinels at these indices;
        # pids differ so the glob would mix its verdicts into THIS
        # incarnation's classification — e.g. a stale watchdog_abort
        # burning budget on a run that just completed.
        for stale in glob.glob(f"{base}.pid*.json"):
            try:
                os.remove(stale)
            except OSError:
                pass
        env = {ENV_SENTINEL: base,
               ENV_RESTART_COUNT: str(incarnation)}
        if estate is not None:
            # Stale requests from a previous incarnation (or a previous
            # supervisor run) must not evict a healthy host now.
            elastic_mod.clear_eviction_request(elastic_dir)
            env[elastic_mod.ENV_WORLD] = str(estate.world)
            env[elastic_mod.ENV_EVICTED] = ",".join(
                map(str, estate.evicted))
            env[elastic_mod.ENV_ELASTIC_DIR] = elastic_dir
            if estate.world < elastic.base_world and elastic.grow:
                # Arm the launcher's grow watcher: once the reduced
                # world has committed this many NEW checkpoints (and
                # capacity holds), it signals the incarnation down at
                # that checkpoint boundary for the grow-back relaunch.
                env[elastic_mod.ENV_GROW_AFTER_CKPTS] = str(
                    elastic.required_ckpts_before_grow(estate.flaps))
        pre_steps = (set(checkpoint_steps_on_disk(ckpt_dir))
                     if ckpt_dir else set())
        t0 = time.monotonic()
        raw = run_incarnation(env)
        wall = time.monotonic() - t0
        report = (raw if isinstance(raw, elastic_mod.GroupReport)
                  else elastic_mod.GroupReport(returncode=int(raw)))
        rc = report.returncode
        statuses = read_exit_statuses(base)
        outcome = classify_exit(rc, statuses)
        lost: list[int] = []
        lost_reason = None
        if estate is not None and outcome != COMPLETED:
            lost, lost_reason = elastic_mod.lost_hosts_of(
                report, statuses, elastic_dir)
            if lost:
                outcome = HOST_LOST
        post_steps = (set(checkpoint_steps_on_disk(ckpt_dir))
                      if ckpt_dir else set())
        step = max(post_steps) if post_steps else None
        # Progress = a NEW committed checkpoint this incarnation, not
        # a higher number than ever seen: a restore-time quarantine
        # LOWERS the latest on-disk step while the incarnation still
        # genuinely advances from its usable base — comparing against
        # an all-time high-water mark would burn budget on a
        # recovering run until it re-passed the condemned step.
        advanced = bool(post_steps - pre_steps)
        incident = Incident(incarnation=incarnation, returncode=rc,
                            outcome=outcome, wall_s=wall,
                            ckpt_step=step, advanced=advanced,
                            world_size=(estate.world if estate
                                        else report.world_size),
                            evicted=(list(estate.evicted) if estate
                                     else []),
                            lost_hosts=list(lost))
        result.incidents.append(incident)
        if outcome == COMPLETED:
            incident.budget_after = budget
            result.returncode = 0
            for line in result.summary_lines():
                logger.info("%s", line)
            _notify(incident)
            return result
        if should_stop is not None and should_stop():
            # The SUPERVISOR was told to stop (e.g. the launcher was
            # preempted and forwarded the signal): the children saved
            # and exited — releasing the machine beats restarting the
            # job the infrastructure just reclaimed.
            incident.budget_after = budget
            result.returncode = rc
            logger.warning("supervisor: stop requested; not "
                           "restarting (last outcome %s rc=%d)",
                           outcome, rc)
            _notify(incident)
            return result
        decision = None
        if estate is not None:
            old_world = estate.world
            decision = elastic.decide_after_exit(
                estate, outcome, lost, lost_reason,
                new_ckpts=len(post_steps - pre_steps),
                grow_requested=report.grow_requested)
            incident.elastic_action = decision.action
            if decision.action != "retry":
                logger.warning(
                    "supervisor: elastic %s — world %d -> %d%s",
                    decision.action, old_world, estate.world,
                    f" (evicted {sorted(estate.evicted)})"
                    if estate.evicted else "")
                if telemetry is not None:
                    telemetry.event(
                        "elastic", incarnation=incarnation,
                        action=decision.action, old_world=old_world,
                        new_world=estate.world,
                        lost_hosts=list(lost), lost_reason=lost_reason,
                        evicted=list(estate.evicted), outcome=outcome,
                        ckpt_step=step)
        # Budget: checkpoint progress (or a clean preemption, which is
        # the infrastructure's fault, not the job's) refunds; anything
        # else burns. This is what turns a deterministic step-N crash
        # into a fast, bounded give-up (see module docstring). A
        # successful elastic shrink/grow also refunds AND resets the
        # backoff streak: the failure was answered by reconfiguration,
        # so the relaunch is immediate.
        if decision is not None and decision.refund:
            budget = policy.max_restarts
            streak = 0
        elif advanced:
            budget = policy.max_restarts
            streak = 0
        elif outcome in (PREEMPTED, HOST_LOST):
            # Refund the budget (not the job's fault) but KEEP the
            # backoff escalating: a preemption storm with zero
            # checkpoint progress must wait out the capped backoff
            # between attempts, never hot-loop restarts. A host loss
            # the policy chose NOT to shrink on (replacement capacity,
            # min_world floor) is the same infrastructure-shaped
            # failure.
            budget = policy.max_restarts
            streak += 1
        else:
            budget -= 1
            streak += 1
        incident.budget_after = budget
        if budget < 0:
            result.returncode = rc if rc != 0 else 1
            logger.error(
                "supervisor: giving up after %d incarnation(s) — no "
                "checkpoint progress in the last %d attempt(s) "
                "(crash-loop); last outcome %s rc=%d",
                len(result.incidents), streak, outcome, rc)
            for line in result.summary_lines():
                logger.error("%s", line)
            if telemetry is not None:
                telemetry.event("supervisor_give_up",
                                incarnations=len(result.incidents),
                                streak=streak, outcome=outcome,
                                returncode=rc)
                if telemetry.events_jsonl:
                    # The crash-loop give-up is exactly the moment a
                    # human gets paged: leave a flight-recorder bundle
                    # next to the events stream (lazy import keeps the
                    # parent telemetry-free until this terminal path).
                    from distributed_training_tpu.telemetry.incident \
                        import write_incident_bundle
                    write_incident_bundle(
                        os.path.join(
                            os.path.dirname(telemetry.events_jsonl),
                            "incidents"),
                        reason=("crash-loop: no checkpoint progress in "
                                f"the last {streak} attempt(s)"),
                        kind="give_up",
                        events_tail=telemetry.tail(),
                        extra={"incarnations": len(result.incidents),
                               "streak": streak, "outcome": outcome,
                               "returncode": rc})
            _notify(incident)
            return result
        delay = policy.backoff_s(streak) if streak else 0.0
        incident.backoff_s = delay
        logger.warning(
            "supervisor: incarnation %d exited %s (rc=%d) after %.1fs; "
            "ckpt_step=%s%s; restarting in %.2fs "
            "(budget %d/%d)",
            incarnation, outcome, rc, wall, step,
            " (advanced)" if advanced else "", delay, budget,
            policy.max_restarts)
        if telemetry is not None:
            extra = {}
            if incident.world_size is not None:
                # Topology history for postmortems: the size this
                # incarnation ran at and who was excluded from it.
                extra = {"world_size": incident.world_size,
                         "evicted_hosts": list(incident.evicted)}
            telemetry.event("restart", incarnation=incarnation,
                            outcome=outcome, returncode=rc,
                            ckpt_step=step, advanced=advanced,
                            backoff_s=round(delay, 3), budget=budget,
                            **extra)
        _notify(incident)
        if delay > 0:
            sleep(delay)
        incarnation += 1


# ---------------------------------------------------------------------------
# serving supervision (in-process engine restarts)
# ---------------------------------------------------------------------------


def supervise_serving(make_engine: Callable[[], object],
                      run: Callable[[object, int], object],
                      *,
                      policy: RestartPolicy | None = None,
                      incident_dir: str | None = None,
                      sleep: Callable[[float], None] = time.sleep,
                      snapshot: Callable[[], dict] | None = None
                      ) -> dict:
    """The serving analogue of ``supervise()``: restart a CRASHED
    engine in-process, carrying the work across incarnations.

    ``make_engine`` returns a fresh, warmed engine (attach a shared
    ``FaultInjector`` instance — or one on a shared ledger path —
    there, so a one-shot ``engine_crash@N`` cannot re-fire when the
    successor's launch count passes N again); ``run(engine,
    incarnation)`` drives it (submit on incarnation 0, then step/
    drain) and returns the result that ends supervision.

    On a crash out of ``run`` the dead engine's HOST-side state is
    salvaged — an ``InjectedCrash``/engine-thread exception kills the
    step loop, not the process, so queue, slots, listeners and the
    emitted-token high-water marks are intact: in-flight sequences
    with decoded tokens export their exact KV (``export_in_flight``)
    and are RE-ADOPTED into the successor (nothing recomputed);
    never-decoded ones and the queue resubmit fresh. The emission
    state transfers wholesale, so a resubmitted stream regenerates
    its greedy-identical prefix without re-delivering a single token
    — exactly-once across the crash.

    Budget rules are ``supervise()``'s with the serving progress
    signal: an incarnation that FINISHED at least one request refunds
    the budget; one that didn't burns one. Give-up (and every crash,
    when ``incident_dir`` is set) leaves an incident bundle carrying
    the ``/debug/requests`` snapshot and the last weight-swap
    provenance, which the doctor classifies as
    ``serving_engine_crash``."""
    from distributed_training_tpu import telemetry as tel
    from distributed_training_tpu.telemetry.incident import (
        write_incident_bundle)

    policy = policy or RestartPolicy()
    engine = make_engine()
    budget = policy.max_restarts
    streak = 0
    incarnation = 0
    crashes: list[dict] = []
    while True:
        base_finished = engine.finished_total
        try:
            result = run(engine, incarnation)
            return {"engine": engine, "result": result,
                    "incarnations": incarnation + 1,
                    "restarts": incarnation, "gave_up": False,
                    "crashes": crashes}
        except KeyboardInterrupt:
            raise
        except Exception as exc:  # noqa: BLE001 — the whole point:
            # classify, salvage, restart (or give up on budget).
            err = f"{type(exc).__name__}: {exc}"
            logger.warning("serving engine crashed (incarnation %d, "
                           "launch %d): %s", incarnation,
                           getattr(engine, "launch_count", -1), err)
            snap = None
            try:
                if snapshot is not None:
                    snap = snapshot()
                else:
                    from distributed_training_tpu.serving.server \
                        import debug_requests_snapshot
                    snap = debug_requests_snapshot(engine)
            except Exception as e:  # noqa: BLE001 — evidence layers
                # are optional; a broken one must not stop recovery.
                logger.debug("serving snapshot unavailable: %s", e)
            emission = engine.export_emission_state()
            queued = list(engine.queue)
            engine.queue.clear()
            try:
                export = engine.export_in_flight()
            except Exception as e:  # noqa: BLE001 — device state may
                # be gone with the crash; restart those from the
                # prompt (host-side request state is always intact).
                logger.warning("in-flight KV salvage failed (%s); "
                               "resubmitting from prompts", e)
                export = {"adoptable": [],
                          "requests": [engine._replay_request(s)
                                       for s in engine.slots
                                       if s is not None]}
            advanced = engine.finished_total > base_finished
            # Event BEFORE the bundle: the bundle's events_tail must
            # contain the crash record the doctor keys on.
            tel.event("serving_engine_crash", incarnation=incarnation,
                      error=err,
                      launches=getattr(engine, "launch_count", None),
                      weights_version=engine.weights_version,
                      kv_salvaged=len(export["adoptable"]),
                      resubmitted=(len(export["requests"])
                                   + len(queued)),
                      finished_this_incarnation=(
                          engine.finished_total - base_finished))
            if incident_dir:
                write_incident_bundle(
                    incident_dir, reason=err, kind="engine_crash",
                    events_tail=tel.current().tail(),
                    extra={"incarnation": incarnation,
                           "launch_count": getattr(
                               engine, "launch_count", None),
                           "weights_version": engine.weights_version,
                           "weights_provenance":
                               engine.weights_provenance,
                           "swap_stats": dict(engine.swap_stats)},
                    serving=snap)
            crashes.append({"incarnation": incarnation, "error": err,
                            "advanced": advanced})
            if advanced:
                budget = policy.max_restarts
                streak = 0
            else:
                budget -= 1
                streak += 1
            if budget < 0:
                logger.error(
                    "serving supervisor: giving up after %d "
                    "incarnation(s) — no finished request in the "
                    "last %d attempt(s); last error %s",
                    incarnation + 1, streak, err)
                tel.event("supervisor_give_up",
                          incarnations=incarnation + 1,
                          streak=streak, outcome=CRASH,
                          scope="serving", error=err)
                if incident_dir:
                    write_incident_bundle(
                        incident_dir,
                        reason=("serving crash-loop: no finished "
                                f"request in the last {streak} "
                                f"attempt(s); last error {err}"),
                        kind="give_up",
                        events_tail=tel.current().tail(),
                        extra={"incarnations": incarnation + 1,
                               "streak": streak, "scope": "serving"},
                        serving=snap)
                return {"engine": engine, "result": None,
                        "incarnations": incarnation + 1,
                        "restarts": incarnation, "gave_up": True,
                        "crashes": crashes}
            delay = policy.backoff_s(streak) if streak else 0.0
            tel.event("restart", incarnation=incarnation,
                      outcome=CRASH, scope="serving",
                      advanced=advanced, backoff_s=round(delay, 3),
                      budget=budget)
            if delay > 0:
                sleep(delay)
            engine = make_engine()
            engine.import_emission_state(emission)
            if export["adoptable"]:
                try:
                    engine.adopt_batch(export["adoptable"])
                except (RuntimeError, ValueError) as e:
                    # The successor couldn't place the salvaged KV
                    # (pool shape changed, capacity): restart those
                    # from the prompt — correctness is untouched, the
                    # high-water marks still dedup the streams.
                    logger.warning("KV re-adoption refused (%s); "
                                   "resubmitting from prompts", e)
                    for req, _toks, _k, _v in export["adoptable"]:
                        engine.submit(req)
            for req in export["requests"]:
                engine.submit(req)
            for req in queued:
                engine.submit(req)
            incarnation += 1
