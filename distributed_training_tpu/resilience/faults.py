"""Deterministic fault injection: the test harness for recovery.

``train.fault_plan`` is a comma-separated plan of scheduled faults,
each a pure function of the global optimizer step — the straggler.py
discipline: on a multi-host pod every host evaluates the same trigger
at the same loop point, so an injected fault can never leave hosts on
different sides of a collective (veScale's deterministic
single-controller property, preserved under fault injection).

Grammar (docs/robustness.md)::

    plan    := entry ("," entry)*
    entry   := kind "@" step (":" modifier)*
    kind    := crash | sigterm | corrupt_ckpt | data_stall | data_error
             | data_corrupt | source_stall | lose_host | slow_host
             | engine_crash | swap_corrupt | slow_decode
             | client_disconnect                  # serving kinds
    modifier:= "always" | duration | "host=" K    # duration: "500ms"
             | "source=" NAME | "skip" | "fatal"  # source-level kinds

- ``crash@40``        raise ``InjectedCrash`` after step 40 completes
  (hard failure: no final save; recovery = supervisor restart +
  checkpoint resume).
- ``sigterm@80``      deliver SIGTERM to this process at step 80
  (exercises the PreemptionGuard clean-save path).
- ``corrupt_ckpt@120`` flip bytes in the newest committed checkpoint
  once a save at step >= 120 lands (exercises manifest verification,
  quarantine, and the restore fallback chain).
- ``data_stall@60:500ms`` sleep 500ms in batch assembly at step 60
  (exercises data_wait accounting and the hang watchdog).
- ``data_error@60``   raise a transient ``InjectedDataError`` in batch
  assembly at step 60 (exercises the loader's bounded retry).
- ``data_corrupt@60:source=wiki:skip`` the first sample read from
  source ``wiki`` at or after step 60 raises ``InjectedCorruptData``
  — a VALIDATION failure, not an IO blip, so it is never retried
  (at-or-after, the ``corrupt_ckpt`` precedent: the mixture may
  assemble the exact batch without touching the named source).
  Policy ``skip`` (the default) exercises the streaming pipeline's
  skip-and-record path (``data_skip`` event with the (source,
  sample_id), ``StreamState.skipped`` counter); ``fatal`` propagates
  and kills the run (recovery = supervisor restart; the ledger keeps
  it one-shot). ``source=`` optional — the first read of any source
  takes the hit when omitted.
- ``source_stall@60:500ms:source=wiki`` sleep 500ms in the first
  read of source ``wiki`` at or after step 60 (a single slow source
  must show up in data_wait attribution without stalling the other
  sources' cursor arithmetic).
- ``lose_host@40:host=2`` host 2 dies WITHOUT CLEANUP
  (``os._exit``) after step 40 — the machine-reclaimed shape; no
  sentinel, no final save. Exercises the launcher's lost-host
  detection and the elastic shrink path (resilience/elastic.py).
- ``slow_host@40:host=2:200ms`` host 2 sleeps 200ms inside EVERY
  measured step from step 40 on — a persistently degraded host, not a
  blip. Exercises the straggler detector's verdict → coordinated
  eviction path. Unlike the one-shot faults it keeps applying for the
  rest of its incarnation; the ledger only suppresses it after a
  restart (the degraded host was evicted — its replacement at the
  same index must not inherit the slowdown).

Serving kinds trigger on the engine LAUNCH COUNT (one per non-idle
``Engine.step`` — the serving analogue of the global step) through the
engine's ``on_launch``/``on_swap`` hooks:

- ``engine_crash@12``  raise ``InjectedCrash`` out of ``Engine.step``
  after launch 12 (recovery = the serving supervisor's in-process
  restart + KV re-adoption, resilience/supervisor.py
  ``supervise_serving``).
- ``swap_corrupt@12``  the first ``Engine.swap_weights`` publish at or
  after launch 12 fails verification and is REFUSED whole — the
  incumbent weights keep serving (at-or-after: swaps are sparse).
- ``slow_decode@12:50ms`` sleep 50ms between launches 12 and 13 — a
  one-shot degraded step (drain-deadline and SLO-attribution drills),
  not the persistent ``slow_host`` shape.
- ``client_disconnect@12`` drop one live stream listener after launch
  12 (the severed-client shape; the engine finishes the request and
  the exactly-once high-water mark keeps the stream consistent).

Host-targeted faults keep the every-host-same-loop-point discipline:
every host evaluates the trigger; only the host whose process index
matches ``host=K`` acts, and the action never involves a collective.

**One-shot vs. always:** a restarted run re-executes the steps since
the last checkpoint, so a naive step trigger re-fires every
incarnation and nothing ever recovers. Faults are therefore one-shot
by default: firing is recorded in a small ledger file BEFORE the
action, and already-fired faults are skipped after restart (every
host loads the same ledger state at startup, so the skip is as
deterministic as the trigger). ``:always`` disables the ledger for
that fault — the deliberate crash-loop used to test the supervisor's
budget exhaustion.

Every firing emits a ``fault_injected`` telemetry event.
"""

from __future__ import annotations

import json
import logging
import os
import re
import signal
import time
from dataclasses import dataclass

from distributed_training_tpu.resilience.elastic import (
    LOST_HOST_EXIT_CODE)

logger = logging.getLogger(__name__)

# Serving kinds key on the ENGINE LAUNCH COUNT (the serving analogue
# of the global step — one per non-idle ``Engine.step``): the engine's
# ``on_launch``/``on_swap`` hooks evaluate them (serving/engine.py),
# same write-before-action ledger as the trainer kinds.
SERVING_KINDS = ("engine_crash", "swap_corrupt", "slow_decode",
                 "client_disconnect")
KINDS = ("crash", "sigterm", "corrupt_ckpt", "data_stall", "data_error",
         "data_corrupt", "source_stall", "lose_host",
         "slow_host") + SERVING_KINDS
# Kinds that target one host (require a host= modifier).
HOST_KINDS = ("lose_host", "slow_host")
# Kinds that act inside a single mixture source's read path (accept a
# source= modifier; data/stream.py's per-doc hook evaluates them).
SOURCE_KINDS = ("data_corrupt", "source_stall")
# data_corrupt recovery policies (see InjectedCorruptData).
CORRUPT_POLICIES = ("skip", "fatal")

_ENTRY_RE = re.compile(r"^(?P<kind>[a-z_]+)@(?P<step>\d+)"
                       r"(?P<mods>(?::[A-Za-z0-9._=-]+)*)$")
_DURATION_RE = re.compile(r"^(?P<num>\d+(?:\.\d+)?)(?P<unit>ms|s)$")
_HOST_RE = re.compile(r"^host=(?P<host>\d+)$")
_SOURCE_RE = re.compile(r"^source=(?P<source>[A-Za-z0-9._-]+)$")


class FaultPlanError(ValueError):
    """Malformed ``train.fault_plan`` string."""


class InjectedCrash(RuntimeError):
    """A scheduled hard failure (``crash@N``). Propagates out of the
    step loop uncaught — the process dies without a final save, which
    is the point."""


class InjectedDataError(OSError):
    """A scheduled TRANSIENT input-pipeline failure (``data_error@N``).
    Subclasses OSError so the loader's retry path treats it exactly
    like a real IO blip."""


class InjectedCorruptData(ValueError):
    """A scheduled VALIDATION failure in one source's sample read
    (``data_corrupt@N``). Subclasses ValueError — corrupt bytes do not
    improve on a retry, so the loader's transient-retry path must not
    touch it. ``corrupt_policy`` is the duck-typed attribute the
    streaming pipeline keys its skip-and-record vs. fatal handling on
    (shared with data/stream.py's ``CorruptSampleError`` so injected
    and real corruption recover through the same code path)."""

    def __init__(self, msg: str, policy: str = "skip"):
        super().__init__(msg)
        self.corrupt_policy = policy


def parse_duration_s(text: str) -> float:
    m = _DURATION_RE.match(text)
    if not m:
        raise FaultPlanError(
            f"bad duration {text!r} (want e.g. '500ms' or '2s')")
    v = float(m.group("num"))
    return v / 1000.0 if m.group("unit") == "ms" else v


@dataclass(frozen=True)
class Fault:
    kind: str
    step: int
    always: bool = False
    stall_s: float = 0.0
    host: int | None = None
    source: str | None = None
    policy: str = ""

    @property
    def key(self) -> str:
        """Ledger identity. Deliberately excludes tuning modifiers
        (durations, policies): the plan is config, the (kind, step
        [, host][, source]) tuple is the scheduled incident."""
        base = f"{self.kind}@{self.step}"
        if self.host is not None:
            base += f":host={self.host}"
        if self.source is not None:
            base += f":source={self.source}"
        return base


def parse_fault_plan(spec: str) -> tuple[Fault, ...]:
    """Parse ``"crash@40,sigterm@80,data_stall@60:500ms"`` → faults."""
    faults: list[Fault] = []
    seen: set[str] = set()
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        m = _ENTRY_RE.match(entry)
        if not m:
            raise FaultPlanError(
                f"bad fault entry {entry!r} (want kind@step[:modifier],"
                f" kinds: {', '.join(KINDS)})")
        kind = m.group("kind")
        if kind not in KINDS:
            raise FaultPlanError(
                f"unknown fault kind {kind!r} in {entry!r} "
                f"(kinds: {', '.join(KINDS)})")
        step = int(m.group("step"))
        if step <= 0:
            raise FaultPlanError(
                f"fault step must be >= 1 in {entry!r}")
        always = False
        stall_s = 0.0
        host: int | None = None
        source: str | None = None
        policy = ""
        mods = [t for t in m.group("mods").split(":") if t]
        for tok in mods:
            hm = _HOST_RE.match(tok)
            sm = _SOURCE_RE.match(tok)
            if tok == "always":
                always = True
            elif tok in CORRUPT_POLICIES:
                policy = tok
            elif hm:
                host = int(hm.group("host"))
            elif sm:
                source = sm.group("source")
            else:
                stall_s = parse_duration_s(tok)
        if stall_s and kind not in ("data_stall", "slow_host",
                                    "source_stall", "slow_decode"):
            raise FaultPlanError(
                f"duration modifier only applies to data_stall/"
                f"slow_host/source_stall/slow_decode, got {entry!r}")
        if kind in ("data_stall", "slow_host", "source_stall",
                    "slow_decode") and not stall_s:
            raise FaultPlanError(
                f"{kind} needs a duration, e.g. "
                f"'{kind}@{step}:500ms' (got {entry!r})")
        if host is not None and kind not in HOST_KINDS:
            raise FaultPlanError(
                f"host= modifier only applies to "
                f"{'/'.join(HOST_KINDS)}, got {entry!r}")
        if kind in HOST_KINDS and host is None:
            raise FaultPlanError(
                f"{kind} needs a target, e.g. "
                f"'{kind}@{step}:host=2' (got {entry!r})")
        if source is not None and kind not in SOURCE_KINDS:
            raise FaultPlanError(
                f"source= modifier only applies to "
                f"{'/'.join(SOURCE_KINDS)}, got {entry!r}")
        if policy and kind != "data_corrupt":
            raise FaultPlanError(
                f"skip/fatal policy only applies to data_corrupt, "
                f"got {entry!r}")
        f = Fault(kind=kind, step=step, always=always, stall_s=stall_s,
                  host=host, source=source, policy=policy)
        if f.key in seen:
            raise FaultPlanError(f"duplicate fault {f.key!r}")
        seen.add(f.key)
        faults.append(f)
    return tuple(faults)


def check_plan_hooks(plan: tuple[Fault, ...],
                     has_stream_sources: bool) -> None:
    """Fail at wiring time when a plan schedules faults whose hook
    point the configured pipeline never calls: source-level kinds
    fire from the streaming loader's per-document read
    (``on_source``), which ``ShardedDataLoader`` does not have — a
    drill that silently never fires would exit 0 and validate
    nothing."""
    if has_stream_sources:
        return
    dead = [f.key for f in plan if f.kind in SOURCE_KINDS]
    if dead:
        raise FaultPlanError(
            f"fault(s) {dead} are source-level "
            f"({'/'.join(SOURCE_KINDS)}) but the run has no "
            "train.data_sources — the sharded loader never reads "
            "per-source, so they would silently never fire")


def corrupt_step_dir(step_dir: str, nbytes: int = 64) -> str | None:
    """Deterministically damage the largest file in a committed step
    dir (invert ``nbytes`` in the middle), leaving the manifest alone
    so verification CATCHES the damage. Returns the damaged path."""
    from distributed_training_tpu.resilience import integrity
    files = [(os.path.getsize(p), rel, p)
             for rel, p in integrity._iter_files(step_dir)]
    files = [f for f in files if f[0] > 0]
    if not files:
        return None
    size, _rel, path = max(files)
    with open(path, "r+b") as f:
        off = max(0, size // 2 - nbytes // 2)
        f.seek(off)
        chunk = f.read(min(nbytes, size - off))
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))
    return path


class FaultInjector:
    """Evaluates the plan at the three hook points (trainer step loop,
    data loader, checkpoint manager) and performs due faults.

    ``ledger_path`` holds the fired-set across restarts (one file per
    host — each host fires deterministically and records its own).
    ``ckpt_dir`` is where ``corrupt_ckpt`` finds its victim. ``host``
    is this process's index — host-targeted faults (``host=K``) act
    only when it matches, though every host evaluates the trigger."""

    def __init__(self, plan: tuple[Fault, ...] | str,
                 ledger_path: str | None = None,
                 ckpt_dir: str | None = None,
                 host: int = 0):
        self.plan = (parse_fault_plan(plan) if isinstance(plan, str)
                     else tuple(plan))
        self.ledger_path = ledger_path
        self.ckpt_dir = ckpt_dir
        self.host = int(host)
        self.fired: set[str] = set()
        if ledger_path and os.path.exists(ledger_path):
            try:
                with open(ledger_path) as f:
                    self.fired = set(json.load(f).get("fired", []))
            except (OSError, ValueError) as e:
                logger.warning("unreadable fault ledger %s (%s); "
                               "treating all faults as unfired",
                               ledger_path, e)
        # Snapshot of what had fired BEFORE this incarnation started:
        # ``slow_host`` keeps applying within the incarnation that
        # first fired it (a degraded host stays degraded) but must not
        # resume after a restart — the evicted host's replacement at
        # the same index is a healthy machine.
        self._fired_at_load: set[str] = set(self.fired)
        if self.plan:
            logger.info(
                "fault plan armed: %s (already fired: %s)",
                ", ".join(f.key + (":always" if f.always else "")
                          for f in self.plan),
                sorted(self.fired) or "none")

    # -- internals ---------------------------------------------------------

    def _due(self, step: int, kinds: tuple[str, ...]) -> list[Fault]:
        return [f for f in self.plan
                if f.kind in kinds and f.step == step
                and (f.always or f.key not in self.fired)]

    def _record(self, fault: Fault, **info) -> None:
        """Mark fired — ledger write BEFORE the action, so a fault
        that kills the process cannot re-fire after restart."""
        self.fired.add(fault.key)
        if self.ledger_path:
            os.makedirs(os.path.dirname(self.ledger_path) or ".",
                        exist_ok=True)
            tmp = f"{self.ledger_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"fired": sorted(self.fired)}, f)
            os.replace(tmp, self.ledger_path)
        from distributed_training_tpu import telemetry
        # "fault_kind", not "kind": the sink uses "kind" as the record
        # type, and a kwarg would silently overwrite it.
        telemetry.event("fault_injected", fault=fault.key,
                        fault_kind=fault.kind, step=fault.step,
                        always=fault.always, **info)
        logger.warning("FAULT INJECTED: %s %s", fault.key, info or "")

    # -- hook points -------------------------------------------------------

    def on_step(self, global_step: int) -> None:
        """Trainer step loop, after step ``global_step``'s bookkeeping.
        Graceful faults fire before lethal ones so a plan scheduling
        both at one step still exercises the graceful path; the
        host-targeted ``lose_host`` fires between them (it is lethal,
        but only for its target — the survivors' next collective hangs
        until the launcher's fail-fast sweep reaps the group, exactly
        the real lost-host shape)."""
        for f in self._due(global_step, ("sigterm",)):
            self._record(f)
            signal.raise_signal(signal.SIGTERM)
        for f in self._due(global_step, ("lose_host",)):
            if f.host != self.host:
                continue  # every host evaluates; only the target acts
            self._record(f, host=self.host)
            logger.warning("lose_host: host %d dying without cleanup "
                           "(os._exit(%d))", self.host,
                           LOST_HOST_EXIT_CODE)
            os._exit(LOST_HOST_EXIT_CODE)
        for f in self._due(global_step, ("crash",)):
            self._record(f)
            raise InjectedCrash(
                f"injected crash at global step {global_step}")

    def on_launch(self, launch: int) -> list[str]:
        """Serving engine hook, after launch ``launch``'s step record
        is emitted (serving/engine.py ``_run_faults``). Performs the
        self-contained action (``slow_decode`` sleeps here — a
        degraded-step blip, not a degraded host) and returns the
        fired kinds whose action needs engine state
        (``client_disconnect``, ``engine_crash`` — graceful recorded
        before lethal, so a plan scheduling both at one launch
        ledgers both even though the crash ends the incarnation)."""
        fired: list[str] = []
        for f in self._due(launch, ("slow_decode",)):
            self._record(f, stall_s=f.stall_s, launch=launch)
            fired.append(f.kind)
            time.sleep(f.stall_s)
        for f in self._due(launch, ("client_disconnect",)):
            self._record(f, launch=launch)
            fired.append(f.kind)
        for f in self._due(launch, ("engine_crash",)):
            self._record(f, launch=launch)
            fired.append(f.kind)
        return fired

    def on_swap(self, launch: int) -> bool:
        """Weight-swap hook (``Engine.swap_weights``): True when an
        armed ``swap_corrupt`` makes THIS publish fail verification.
        At-or-after semantics (the ``corrupt_ckpt`` precedent): swaps
        are sparse, an exact launch-count match would usually never
        fire. The ledger write precedes the refusal it causes."""
        for f in self.plan:
            if (f.kind != "swap_corrupt" or launch < f.step
                    or (not f.always and f.key in self.fired)):
                continue
            self._record(f, fired_at=launch)
            return True
        return False

    def step_delay(self, global_step: int) -> float:
        """Seconds this host must stall inside the measured region of
        step ``global_step`` (``slow_host`` faults). Applies to EVERY
        step >= the trigger step for the rest of the incarnation —
        a degraded host, not a blip — and is recorded (ledger +
        telemetry) once, at first application. Skipped entirely when
        a previous incarnation already fired it (the slow host was
        evicted; its replacement is healthy)."""
        total = 0.0
        for f in self.plan:
            if (f.kind != "slow_host" or global_step < f.step
                    or f.host != self.host):
                continue
            if not f.always and f.key in self._fired_at_load:
                continue
            if f.key not in self.fired:
                self._record(f, host=self.host, stall_s=f.stall_s)
            total += f.stall_s
        return total

    def on_data(self, step: int) -> None:
        """Data path, once per batch assembly ATTEMPT (inside the
        loader's retry loop, so a transient injected error is retried
        exactly like a real one). ``step`` is the loader's
        deterministic batch counter."""
        for f in self._due(step, ("data_stall",)):
            self._record(f, stall_s=f.stall_s)
            time.sleep(f.stall_s)
        for f in self._due(step, ("data_error",)):
            self._record(f)
            raise InjectedDataError(
                f"injected transient data error at step {step}")

    def _due_source(self, step: int, source: str,
                    kinds: tuple[str, ...]) -> list[Fault]:
        """Source-level due check: fires at the FIRST matching read at
        or after the scheduled step (the ``corrupt_ckpt`` precedent —
        an exact-step match would silently never fire when the
        mixture happens to assemble that batch without touching the
        named source). Deterministic: the stream's read sequence is a
        pure function of its state on every host."""
        return [f for f in self.plan
                if f.kind in kinds and step >= f.step
                and (f.source is None or f.source == source)
                and (f.always or f.key not in self.fired)]

    def on_source(self, step: int, source: str) -> None:
        """Source-level read path (data/stream.py), once per document
        read ATTEMPT. ``step`` is the loader's deterministic batch
        counter; a fault carrying ``source=`` acts on the named
        source's first read at or after its step — an unqualified one
        hits the first read of any source. The ledger write precedes
        the raise, so a ``fatal`` corruption that kills the run is
        one-shot across restarts."""
        for f in self._due_source(step, source, ("source_stall",)):
            self._record(f, source=source, stall_s=f.stall_s,
                         fired_at=step)
            time.sleep(f.stall_s)
        for f in self._due_source(step, source, ("data_corrupt",)):
            policy = f.policy or "skip"
            self._record(f, source=source, policy=policy,
                         fired_at=step)
            raise InjectedCorruptData(
                f"injected corrupt sample in source {source!r} at "
                f"step {step}", policy=policy)

    def on_checkpoint_saved(self, step: int,
                            directory: str | None = None) -> None:
        """Checkpoint manager, after a save at ``step`` is committed.
        A ``corrupt_ckpt@N`` fires at the first save with step >= N
        (saves land on a cadence; an exact-match step would usually
        never fire). Called on the COORDINATOR only (the manager
        gates it): on shared storage N hosts XOR-flipping the same
        bytes would undo each other.

        Only steps that already have a checksum manifest are eligible
        victims: corrupting a not-yet-manifested step would let the
        later manifest flush checksum the damaged bytes and BLESS the
        corruption — the injected fault must be the one verification
        catches, never one it hides. With async saves the newest step
        is still unmanifested when this hook runs, so the previous
        step takes the damage; the fault stays pending until a
        manifested step exists."""
        directory = directory or self.ckpt_dir
        if directory is None:
            return
        from distributed_training_tpu.resilience import integrity
        for f in self.plan:
            if (f.kind != "corrupt_ckpt" or step < f.step
                    or (not f.always and f.key in self.fired)):
                continue
            target = next(
                (s for s in reversed(
                    integrity.checkpoint_steps_on_disk(directory))
                 if os.path.exists(os.path.join(
                     directory, str(s), integrity.MANIFEST_NAME))),
                None)
            if target is None:
                continue
            step_dir = os.path.join(directory, str(target))
            damaged = corrupt_step_dir(step_dir)
            self._record(f, target_step=target, damaged=damaged)
