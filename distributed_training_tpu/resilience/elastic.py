"""Elastic world-size policy: shrink/grow the run without losing it.

ROADMAP item 3. The restart supervisor (supervisor.py) can relaunch a
dead job, but only at a FIXED world size — on a preemption that takes
one host, the only options were "wait for the host" or "give up". The
straggler detector (telemetry/straggler.py) can attribute a slow host
but never act on it. This module is the policy layer that composes
them: when a host is lost (preempted, crashed, or evicted for being a
persistent straggler), the supervised run checkpoints (or falls back
to the last manifested step), re-forms the mesh at the surviving world
size, reshards the restore (orbax reshards across mesh changes —
checkpoint/manager.py; ``MeshSpec.resolve``'s ``-1`` wildcard axis
gives the re-formed shape), rescales the per-host batch so the GLOBAL
batch is preserved (``train.global_batch_size``), and continues — then
grows back to full size at a checkpoint boundary when capacity
returns. TorchTitan's production framing (PAPERS.md) is the bar:
preemption is routine, not exceptional.

Decision table (``ElasticPolicy.decide_after_exit``):

| outcome                     | capacity to replace | action            |
|-----------------------------|---------------------|-------------------|
| whole-group crash           | —                   | retry, same world |
| whole-job preemption        | —                   | retry, same world |
| host lost (involuntary)     | yes                 | retry, same world |
| host lost (involuntary)     | no                  | **shrink**        |
| host evicted (straggler)    | either              | **shrink**        |

(An evicted host is sick — shrink regardless of capacity; at
grow-back a replacement takes its slot.)
| any, at ``min_world``       | —                   | retry (cannot shrink further) |

Budget semantics (supervisor.py's refund/burn discipline): a
SUCCESSFUL shrink or grow refunds the retry budget and resets the
backoff streak — the failure was addressed by reconfiguration, so the
relaunch is immediate. A retry at the same size follows the normal
rules (checkpoint progress refunds, a crash burns, a preemption
refunds but escalates backoff).

Grow-back ("at a checkpoint boundary when capacity returns"): a
shrunken incarnation runs until it has committed
``grow_after_ckpts * 2**flaps`` new checkpoints (hysteresis doubles
per shrink-after-grow flap, so a flapping host cannot thrash the
mesh), then the launcher's grow watcher delivers SIGTERM — the
PreemptionGuard clean-save path — and the supervisor relaunches at the
full world size. The restart IS the checkpoint boundary.

Eviction is NEVER an in-band kill: the straggler detector's verdict
(a pure function of the all-gathered table, identical on every host at
the same step) makes every host break its step loop at the same loop
point, save, and exit cleanly with a ``host_lost`` sentinel; the
coordinator also writes an eviction-request sentinel FILE the
supervisor consumes. No host is ever left waiting in a collective.

IMPORT CONTRACT: stdlib only — this module runs in the launcher parent
(next to supervisor.py) and is also imported by the train CLI for the
batch arithmetic; it must never drag in jax/orbax/telemetry.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field, replace
from typing import Callable

logger = logging.getLogger(__name__)

# Environment contract between the supervisor and each incarnation.
ENV_WORLD = "DTT_ELASTIC_WORLD"          # resolved world size
ENV_EVICTED = "DTT_ELASTIC_EVICTED"      # comma-separated host ids
ENV_ELASTIC_DIR = "DTT_ELASTIC_DIR"      # eviction-request sentinel dir
ENV_GROW_AFTER_CKPTS = "DTT_ELASTIC_GROW_AFTER"  # launcher grow watcher

# Exit code resilience/faults.py's ``lose_host`` uses for its
# no-cleanup death (os._exit) — distinct from the watchdog's 42 and
# from 128+signum signal deaths, so a lost host reads as a crash whose
# identity the launcher's group report pins down.
LOST_HOST_EXIT_CODE = 97

EVICTION_REQUEST = "eviction_request.json"

# How a host was lost (``lost_hosts_of`` reasons).
LOST_EVICTION = "eviction"
LOST_INVOLUNTARY = "lost"


def evicted_from_env(env: dict | None = None) -> list[int]:
    """Evicted-host set this incarnation inherited (ENV_EVICTED)."""
    raw = (env if env is not None else os.environ).get(ENV_EVICTED, "")
    return [int(x) for x in raw.split(",") if x.strip().isdigit()]


def per_shard_batch(global_batch: int, shard_count: int) -> int:
    """Per-data-shard batch size preserving the global batch across
    world sizes. Elastic runs must pick a ``train.global_batch_size``
    divisible by every world size they can shrink to (e.g. 12 for a
    4-host run that may run at 3) — an uneven split would silently
    change the optimization trajectory, so it fails loudly instead."""
    if global_batch <= 0:
        raise ValueError(
            f"global_batch_size must be > 0, got {global_batch}")
    if global_batch % shard_count:
        raise ValueError(
            f"train.global_batch_size={global_batch} does not divide "
            f"evenly over {shard_count} data shard(s) — elastic runs "
            "need a global batch divisible by every world size they "
            "can shrink to (e.g. 12 for 4-or-3 hosts)")
    return global_batch // shard_count


@dataclass(frozen=True)
class GroupReport:
    """What the launcher observed about one incarnation's process
    group — the per-process detail ``classify_exit`` alone cannot see.
    ``self_failed`` are processes that exited nonzero on their own;
    ``killed`` are the ones the launcher killed in its fail-fast
    teardown (their deaths are consequences, not causes)."""

    returncode: int
    world_size: int | None = None
    self_failed: tuple[int, ...] = ()
    killed: tuple[int, ...] = ()
    completed: tuple[int, ...] = ()
    grow_requested: bool = False


# ---------------------------------------------------------------------------
# eviction-request sentinel (written by the straggler detector's
# coordinator, consumed — and cleared — by the supervisor)
# ---------------------------------------------------------------------------


def write_eviction_request(elastic_dir: str, host: int, step: int,
                           **info) -> str:
    """Atomic sentinel: "evict host K" — the supervisor consumes it at
    the incarnation boundary; it is never an in-band kill."""
    os.makedirs(elastic_dir, exist_ok=True)
    path = os.path.join(elastic_dir, EVICTION_REQUEST)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"host": int(host), "step": int(step),
                   "t": time.time(), **info}, f)
    os.replace(tmp, path)
    return path


def read_eviction_request(elastic_dir: str | None) -> dict | None:
    if not elastic_dir:
        return None
    path = os.path.join(elastic_dir, EVICTION_REQUEST)
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    return rec if (isinstance(rec, dict)
                   and isinstance(rec.get("host"), int)) else None


def clear_eviction_request(elastic_dir: str | None) -> None:
    if not elastic_dir:
        return
    try:
        os.remove(os.path.join(elastic_dir, EVICTION_REQUEST))
    except OSError:
        pass


def lost_hosts_of(report: GroupReport, statuses: list[dict],
                  elastic_dir: str | None = None
                  ) -> tuple[list[int], str | None]:
    """Which hosts this incarnation lost, and why.

    Precedence: (1) clean eviction exits — every host writes a
    ``host_lost`` sentinel naming the evictee; (2) the coordinator's
    eviction-request FILE (covers a group that died during teardown
    before its sentinels landed); (3) the launcher's group report — a
    strict subset of processes that failed on their own while the rest
    completed or were killed in the fail-fast sweep is a lost host. A
    whole group failing together is a crash, not a host loss."""
    evicted = sorted({s["lost_host"] for s in statuses
                      if s.get("outcome") == "host_lost"
                      and isinstance(s.get("lost_host"), int)})
    if evicted:
        return evicted, LOST_EVICTION
    req = read_eviction_request(elastic_dir)
    if req is not None:
        return [req["host"]], LOST_EVICTION
    if report.self_failed and (report.killed or report.completed):
        return sorted(report.self_failed), LOST_INVOLUNTARY
    return [], None


# ---------------------------------------------------------------------------
# the policy
# ---------------------------------------------------------------------------


@dataclass
class ElasticState:
    """Mutable world-topology state the supervisor threads through
    incarnations (also what postmortems want: the topology history)."""

    world: int
    evicted: list[int] = field(default_factory=list)
    flaps: int = 0               # shrinks that followed a grow-back
    grows: int = 0
    ckpts_since_shrink: int = 0


@dataclass(frozen=True)
class Decision:
    """One incarnation-boundary decision."""

    action: str                  # "retry" | "shrink" | "grow"
    world: int
    evicted: tuple[int, ...] = ()
    reason: str | None = None
    # True → the reconfiguration itself is recovery: refund the retry
    # budget and reset the backoff streak (relaunch immediately).
    refund: bool = False


@dataclass
class ElasticPolicy:
    """Shrink/grow knobs (CLI: ``--elastic*`` on launch.local).

    ``replace_lost`` models "capacity available to hot-replace a lost
    host at relaunch" — False (the production default: a preempted
    host is gone for a while) makes involuntary losses shrink;
    ``capacity`` is the grow-back probe (None → always available,
    which is what a local simulation wants)."""

    base_world: int
    min_world: int = 1
    replace_lost: bool = False
    grow: bool = True
    grow_after_ckpts: int = 1
    capacity: Callable[[], bool] | None = None

    def capacity_available(self) -> bool:
        return True if self.capacity is None else bool(self.capacity())

    def required_ckpts_before_grow(self, flaps: int) -> int:
        """Grow-back hysteresis: each shrink that followed a grow
        doubles the dwell (in committed checkpoints) before the next
        grow — a flapping host cannot thrash the mesh."""
        return self.grow_after_ckpts * (2 ** min(max(0, flaps), 6))

    # -- decisions ---------------------------------------------------------

    def decide_after_exit(self, state: ElasticState, outcome: str,
                          lost_hosts: list[int],
                          lost_reason: str | None,
                          new_ckpts: int = 0,
                          grow_requested: bool = False) -> Decision:
        """Mutates ``state`` and returns the decision for the next
        incarnation. ``outcome`` is a supervisor exit class;
        ``new_ckpts`` is how many new steps this incarnation committed
        (feeds the grow-back dwell)."""
        if state.world < self.base_world:
            state.ckpts_since_shrink += max(0, new_ckpts)
        decision = self._decide(state, outcome, lost_hosts,
                                lost_reason, grow_requested)
        if decision.action == "shrink":
            if state.grows:
                state.flaps += 1
            state.world = decision.world
            state.evicted = sorted(set(state.evicted)
                                   | set(decision.evicted))
            state.ckpts_since_shrink = 0
        elif decision.action == "grow":
            state.world = decision.world
            # Host indices are fungible across incarnations: growing
            # back re-adds SLOTS, not the condemned machine (a real
            # fleet hands the slot to a replacement host).
            state.evicted = []
            state.grows += 1
            state.ckpts_since_shrink = 0
        return decision

    def _decide(self, state: ElasticState, outcome: str,
                lost_hosts: list[int], lost_reason: str | None,
                grow_requested: bool) -> Decision:
        survivors = state.world - len(lost_hosts)
        if lost_hosts and lost_reason == LOST_EVICTION:
            # A persistent straggler is SICK — retrying with it in the
            # mesh reproduces the slowdown, capacity or not.
            if survivors >= self.min_world:
                return Decision("shrink", survivors,
                                tuple(lost_hosts), LOST_EVICTION,
                                refund=True)
            logger.warning(
                "eviction of host(s) %s ignored: %d survivor(s) would "
                "fall below min_world=%d", lost_hosts, survivors,
                self.min_world)
            return Decision("retry", state.world,
                            reason="below_min_world")
        if lost_hosts:
            if self.replace_lost and self.capacity_available():
                return Decision("retry", state.world,
                                reason="replacement_capacity")
            if survivors >= self.min_world:
                return Decision("shrink", survivors,
                                tuple(lost_hosts), LOST_INVOLUNTARY,
                                refund=True)
            return Decision("retry", state.world,
                            reason="below_min_world")
        # No specific host lost: whole-group crash / preemption /
        # watchdog — a same-size retry, but take the grow-back
        # opportunity when one is due (every restart is a checkpoint
        # boundary).
        if self._grow_due(state, grow_requested):
            return Decision("grow", self.base_world, reason="grow_back",
                            refund=True)
        return Decision("retry", state.world, reason=outcome)

    def _grow_due(self, state: ElasticState,
                  grow_requested: bool) -> bool:
        if not self.grow or state.world >= self.base_world:
            return False
        if not self.capacity_available():
            return False
        if grow_requested:
            # The launcher's grow watcher already verified the dwell
            # before it signaled the incarnation down.
            return True
        return (state.ckpts_since_shrink
                >= self.required_ckpts_before_grow(state.flaps))
