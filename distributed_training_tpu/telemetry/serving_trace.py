"""Serving request-lifecycle traces: schema + the offline analyzer.

The serving engine's per-step ``serving`` records say what the ENGINE
did; nothing said what a REQUEST experienced. This module defines the
``serving_trace`` event — one record per request lifetime, emitted
through the ambient telemetry sink when the request finishes (and on
``Engine.preempt()``, so lost work is visible instead of silently
re-run) — and the offline analyzer that turns a stream of them into
the per-tenant SLO ledger ROADMAP item 3 schedules against.

The trace is accumulated HOST-SIDE on the engine's ``_Seq`` bookkeeping
at points the host already occupies (admission, the post-``_fetch_host``
timestamps every launch path already takes): tracing adds zero device
syncs (DTT010 stays clean), zero new jit entries (zero recompiles), and
writes only through ``telemetry/events.py`` (DTT001 stays clean).

Record schema (additive; ``kind``/``t``/``host`` are the telemetry
envelope's)::

    {"kind": "serving_trace",
     "id": str, "tenant": str,
     "outcome": "finished" | "preempted",
     "prompt_tokens": int, "new_tokens": int,
     "queue_wait_s": float | None,   # arrival -> admission
     "ttft_s": float | None,         # arrival -> first token
     "e2e_s": float,                 # arrival -> finish/preempt
     "prefix_hit_tokens": int,       # prompt tokens served from cache
     "tokens_discarded": int,        # preempt only (0 on finish)
     "spans": [{"ev": ..., "t": <seconds since arrival>, ...}, ...],
     "weights_versions": [[version, count], ...]}  # run-length list of
                                     # the weight version each emitted
                                     # token was produced under (the
                                     # hot-swap audit trail)

Span events (``SPAN_EVENTS``): ``queued`` (t=0 by construction, the
request's arrival), ``admitted`` (group/slot/prefix_hit_tokens),
``resumed`` (session re-attach: group/slot/session/hit_tokens),
``adopted`` (disaggregation handoff: group), ``prefill`` (one launch's
chunk: tokens), ``decode`` (one burst: emitted, plus budget on the
multi-token paths), ``session_retain`` (pages parked under the session
key), and the terminal ``finished``/``preempted`` (the latter with
``tokens_discarded``). Span timestamps are RELATIVE to arrival so the
offline math never depends on clock alignment across hosts.

The analyzer (``analyze_traces``) reconstructs per-tenant p50/p95/p99
TTFT and e2e latency, queue wait, tokens/request, launch occupancy
(tokens per prefill launch, emitted per decode burst), preemption
retry cost, and prefix-hit rates. ``slo_attainment`` scores each
finished request against a TTFT deadline + a per-token decode deadline
— the SLO fraction ``bench_serving.py`` ledgers and
``python -m distributed_training_tpu.telemetry <run_dir>
--serving-report`` prints. One implementation, three consumers
(summarizer, bench, tests), so the ledger and the report can never
disagree.
"""

from __future__ import annotations

# The per-request record's keys, pinned by tests/test_telemetry.py —
# additive only: the aggregate event schema stays at version 1, and
# consumers select by key, never by position.
TRACE_KEYS = (
    "id", "tenant", "outcome", "prompt_tokens", "new_tokens",
    "queue_wait_s", "ttft_s", "e2e_s", "prefix_hit_tokens",
    "tokens_discarded", "spans", "weights_versions",
)

SPAN_EVENTS = (
    "queued", "admitted", "resumed", "adopted", "prefill", "decode",
    "session_retain", "finished", "preempted",
)

OUTCOMES = ("finished", "preempted")

# Default SLO deadlines (seconds) — mirrored by conf/serving/
# default.yaml's ``slo:`` block; bench_serving.py and the
# --serving-report CLI read that block so the committed config is the
# single place deadlines live.
DEFAULT_TTFT_DEADLINE_S = 0.25
DEFAULT_PER_TOKEN_DEADLINE_S = 0.05


def percentile(xs, p: float) -> float | None:
    """Nearest-rank percentile (the bench ledger's convention —
    benchmarks/bench_serving.py ``percentiles``): deterministic, no
    interpolation, exact on the small-N synthetic fixtures tests pin.
    """
    xs = sorted(x for x in xs if isinstance(x, (int, float)))
    if not xs:
        return None
    rank = max(1, -(-len(xs) * p // 100))  # ceil(n * p / 100)
    return float(xs[int(rank) - 1])


def _quantiles(xs) -> dict | None:
    if not xs:
        return None
    return {"p50": percentile(xs, 50), "p95": percentile(xs, 95),
            "p99": percentile(xs, 99), "mean": sum(xs) / len(xs),
            "n": len(xs)}


def iter_traces(events) -> list[dict]:
    """The ``serving_trace`` records of an event stream. Accepts raw
    trace dicts too (no ``kind`` — the bench passes records it
    collected itself) so one analyzer serves both transports."""
    out = []
    for e in events:
        if not isinstance(e, dict):
            continue
        kind = e.get("kind")
        if kind == "serving_trace" or (kind is None
                                       and e.get("outcome")
                                       in OUTCOMES):
            out.append(e)
    return out


def meets_slo(trace: dict, ttft_deadline_s: float,
              per_token_deadline_s: float) -> bool:
    """One finished request against the two-part deadline: TTFT
    within ``ttft_deadline_s`` AND the decode tail (e2e minus TTFT)
    within ``per_token_deadline_s`` per post-first token. A request
    with no token at all (preempted before TTFT) never attains."""
    ttft = trace.get("ttft_s")
    if not isinstance(ttft, (int, float)) or ttft > ttft_deadline_s:
        return False
    e2e = trace.get("e2e_s")
    n = trace.get("new_tokens") or 0
    if not isinstance(e2e, (int, float)):
        return False
    tail_budget = per_token_deadline_s * max(0, n - 1)
    return (e2e - ttft) <= tail_budget + 1e-9


def slo_attainment(traces, ttft_deadline_s: float,
                   per_token_deadline_s: float) -> dict | None:
    """SLO-attainment fraction over the FINISHED traces (a preempted
    record is not a served request — its resubmitted incarnation is
    scored when it finishes)."""
    done = [t for t in traces if t.get("outcome") == "finished"]
    if not done:
        return None
    ok = sum(1 for t in done
             if meets_slo(t, ttft_deadline_s, per_token_deadline_s))
    return {"attained": round(ok / len(done), 6), "met": ok,
            "requests": len(done),
            "ttft_deadline_s": ttft_deadline_s,
            "per_token_deadline_s": per_token_deadline_s}


def _span_stats(traces) -> dict:
    """Launch-occupancy view from the span timelines: prompt tokens
    per prefill launch and emitted tokens per decode burst — the
    launch-amortization numbers the batched/resident paths exist
    for, now derivable per tenant from the trace stream alone."""
    prefill_tokens: list[float] = []
    decode_emitted: list[float] = []
    for t in traces:
        for s in t.get("spans") or []:
            if s.get("ev") == "prefill" and \
                    isinstance(s.get("tokens"), (int, float)):
                prefill_tokens.append(s["tokens"])
            elif s.get("ev") == "decode" and \
                    isinstance(s.get("emitted"), (int, float)):
                decode_emitted.append(s["emitted"])
    out: dict = {}
    if prefill_tokens:
        out["prefill_launches"] = len(prefill_tokens)
        out["prefill_tokens_per_launch"] = round(
            sum(prefill_tokens) / len(prefill_tokens), 4)
    if decode_emitted:
        out["decode_bursts"] = len(decode_emitted)
        out["decode_emitted_per_burst"] = round(
            sum(decode_emitted) / len(decode_emitted), 4)
    return out


def _tenant_report(traces, ttft_deadline_s, per_token_deadline_s
                   ) -> dict:
    done = [t for t in traces if t.get("outcome") == "finished"]
    pre = [t for t in traces if t.get("outcome") == "preempted"]
    rep: dict = {
        "requests": len(done),
        "preemptions": len(pre),
        "ttft_s": _quantiles([t.get("ttft_s") for t in done
                              if isinstance(t.get("ttft_s"),
                                            (int, float))]),
        "e2e_s": _quantiles([t.get("e2e_s") for t in done
                             if isinstance(t.get("e2e_s"),
                                           (int, float))]),
        "queue_wait_s": _quantiles(
            [t.get("queue_wait_s") for t in done
             if isinstance(t.get("queue_wait_s"), (int, float))]),
        "tokens_per_request": _quantiles(
            [t.get("new_tokens") for t in done
             if isinstance(t.get("new_tokens"), (int, float))]),
        "slo": slo_attainment(traces, ttft_deadline_s,
                              per_token_deadline_s),
    }
    new_tokens = sum(t.get("new_tokens") or 0 for t in done)
    discarded = sum(t.get("tokens_discarded") or 0 for t in pre)
    rep["tokens_discarded"] = discarded
    if new_tokens:
        # Retry cost: tokens generated then thrown away by
        # preemption, as a fraction of the tokens that reached users
        # — derived from the preempt traces, not inferred.
        rep["preempt_retry_cost"] = round(discarded / new_tokens, 6)
    prompt = sum(t.get("prompt_tokens") or 0 for t in done)
    hit = sum(t.get("prefix_hit_tokens") or 0 for t in done)
    if prompt:
        rep["prefix_hit_rate"] = round(hit / prompt, 6)
    rep.update(_span_stats(traces))
    return rep


def analyze_traces(events, ttft_deadline_s: float
                   = DEFAULT_TTFT_DEADLINE_S,
                   per_token_deadline_s: float
                   = DEFAULT_PER_TOKEN_DEADLINE_S) -> dict | None:
    """Event stream -> the serving SLO ledger: overall + per-tenant
    p50/p95/p99 TTFT/e2e/queue-wait, tokens/request, SLO attainment,
    preemption retry cost, prefix-hit rate, launch occupancy. None
    when the stream carries no ``serving_trace`` records (the section
    stays out of the summarizer report)."""
    traces = iter_traces(events)
    if not traces:
        return None
    tenants = sorted({t.get("tenant") or "default" for t in traces})
    report = {
        "traces": len(traces),
        "overall": _tenant_report(traces, ttft_deadline_s,
                                  per_token_deadline_s),
        "tenants": {
            name: _tenant_report(
                [t for t in traces
                 if (t.get("tenant") or "default") == name],
                ttft_deadline_s, per_token_deadline_s)
            for name in tenants},
    }
    return report


def _fmt_q(q: dict | None, scale: float = 1e3,
           unit: str = "ms") -> str:
    if not q:
        return "-"
    return (f"p50 {q['p50'] * scale:.1f}{unit}  "
            f"p95 {q['p95'] * scale:.1f}{unit}  "
            f"p99 {q['p99'] * scale:.1f}{unit}")


def render_serving_lines(rep: dict | None) -> list[str]:
    """Report lines — shared by the summarizer section and the
    ``--serving-report`` CLI so the two renderings cannot drift."""
    if not rep:
        return []
    o = rep["overall"]
    slo = o.get("slo") or {}
    lines = [
        f"serving: {o['requests']} request(s) finished, "
        f"{o['preemptions']} preemption trace(s), "
        f"{len(rep['tenants'])} tenant(s)"]
    if slo:
        lines.append(
            f"  SLO (ttft<={slo['ttft_deadline_s'] * 1e3:.0f}ms, "
            f"{slo['per_token_deadline_s'] * 1e3:.0f}ms/token): "
            f"{slo['attained']:.1%} attained "
            f"({slo['met']}/{slo['requests']})")
    for name, t in sorted(rep["tenants"].items()):
        t_slo = t.get("slo") or {}
        line = (f"  tenant {name}: {t['requests']} req  "
                f"ttft {_fmt_q(t.get('ttft_s'))}  "
                f"e2e {_fmt_q(t.get('e2e_s'))}")
        if t_slo:
            line += f"  slo {t_slo['attained']:.1%}"
        lines.append(line)
        extra = []
        if t.get("queue_wait_s"):
            extra.append(
                f"queue wait {_fmt_q(t['queue_wait_s'])}")
        if t.get("prefix_hit_rate") is not None:
            extra.append(f"prefix hit {t['prefix_hit_rate']:.1%}")
        if t.get("preempt_retry_cost") is not None:
            extra.append(
                f"retry cost {t['preempt_retry_cost']:.1%} "
                f"({t['tokens_discarded']} tok discarded)")
        if extra:
            lines.append("    " + "  ".join(extra))
    occ = []
    if o.get("prefill_tokens_per_launch") is not None:
        occ.append(f"prefill {o['prefill_tokens_per_launch']:.1f} "
                   f"tok/launch x{o['prefill_launches']}")
    if o.get("decode_emitted_per_burst") is not None:
        occ.append(f"decode {o['decode_emitted_per_burst']:.1f} "
                   f"tok/burst x{o['decode_bursts']}")
    if occ:
        lines.append("  launch occupancy: " + ", ".join(occ))
    return lines


def slo_deadlines_from_conf(path: str | None = None
                            ) -> tuple[float, float]:
    """(ttft_deadline_s, per_token_deadline_s) from conf/serving/
    default.yaml's ``slo:`` block — the one committed place deadlines
    live; module defaults when the file/block is absent (a bare
    checkout of only the telemetry package still works)."""
    import os
    ttft, per_tok = (DEFAULT_TTFT_DEADLINE_S,
                     DEFAULT_PER_TOKEN_DEADLINE_S)
    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "conf", "serving", "default.yaml")
    try:
        import yaml
        with open(path) as f:
            conf = yaml.safe_load(f) or {}
    except (OSError, ImportError, ValueError):
        return ttft, per_tok
    slo = conf.get("slo") or {}
    if isinstance(slo.get("ttft_s"), (int, float)):
        ttft = float(slo["ttft_s"])
    if isinstance(slo.get("per_token_s"), (int, float)):
        per_tok = float(slo["per_token_s"])
    return ttft, per_tok
