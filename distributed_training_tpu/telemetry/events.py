"""Span/event core: the structured ``events.jsonl`` stream.

The metrics stream (utils/metrics.py) answers "how is the loss/MFU
curve doing"; this stream answers "where did the wall-clock go and
what was the process doing when it stopped". One JSON object per line:

- ``{"kind": "span", "name": "step", "t": <end unix>, "dur_s": ...,
   "depth": 0, "parent": null, ...attrs}`` — emitted when a span
  closes (start time = ``t - dur_s``). Spans nest per thread.
- ``{"kind": "<event name>", "t": ..., ...fields}`` — point events
  (hbm samples, goodput windows, watchdog firings, run_start).

Every ``span()`` also opens a ``jax.profiler.TraceAnnotation`` so the
same region names show up in XProf timelines — one instrumentation
surface for both the always-on jsonl stream and on-demand traces
(the TorchTitan stance: metrics/tracing as one first-class subsystem,
arxiv 2410.06511).

Ambient use (the ``logging`` model): entrypoints ``install()`` one
``Telemetry``; library code calls the module-level ``span()`` /
``event()``, which no-op (except the trace annotation) until something
is installed. BENCH_r05's "backend unresponsive, zero artifacts"
failure is the motivating counterexample — with this installed, the
watchdog (telemetry/watchdog.py) can dump the last N events of exactly
this stream into a postmortem.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import threading
import time

import jax

from distributed_training_tpu.utils.metrics import sanitize_for_json

logger = logging.getLogger(__name__)


class Telemetry:
    """Thread-safe event sink: jsonl file + bounded in-memory tail.

    ``events_jsonl=None`` or ``enabled=False`` keeps the full span API
    (including trace annotations) but writes nothing — the default for
    library code running outside an instrumented entrypoint.
    ``fresh=False`` appends (resumed runs), separated by a
    ``run_start`` marker, mirroring MetricsLogger's semantics.

    ``host_id`` (the jax process index on multi-host runs) stamps a
    ``host`` field onto EVERY record, so per-host streams stay
    attributable after the multi-host aggregator merges them into one
    timeline (telemetry/aggregate.py). None (single-process default)
    keeps the stream byte-identical to the single-host schema.
    """

    def __init__(self, events_jsonl: str | None = None,
                 enabled: bool = True, fresh: bool = True,
                 tail_events: int = 256, start_step: int = 0,
                 host_id: int | None = None):
        self.enabled = enabled and events_jsonl is not None
        self.events_jsonl = events_jsonl if self.enabled else None
        self.host_id = host_id
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._observers: list = []
        self._tail: collections.deque = collections.deque(
            maxlen=tail_events)
        self.ledger = None  # GoodputLedger, attached by the trainer
        self._fh = None
        if self.events_jsonl:
            os.makedirs(os.path.dirname(self.events_jsonl) or ".",
                        exist_ok=True)
            # One persistent line-buffered handle for the run: _emit
            # fires at least twice per training step (data_wait +
            # step spans), and an open/close pair per record under
            # the lock would stall the prefetch thread's spans behind
            # the main loop's I/O. Line buffering keeps every record
            # durable-on-write for tail readers and postmortems.
            self._fh = open(self.events_jsonl,
                            "w" if fresh else "a", buffering=1)
            start: dict = {"kind": "run_start", "t": time.time(),
                           "step": start_step}
            if self.host_id is not None:
                start["host"] = self.host_id
            self._fh.write(json.dumps(start) + "\n")

    # -- sinks ------------------------------------------------------------

    def attach_ledger(self, ledger) -> None:
        """Feed top-level span durations into a GoodputLedger."""
        self.ledger = ledger

    def add_observer(self, fn) -> None:
        """Register a live consumer of every emitted record (the
        metrics endpoint, telemetry/metrics_server.py). Called with
        the sanitized record AFTER it is written, outside the sink
        lock; an observer that raises is logged and does not disturb
        emission — the jsonl stream stays the source of truth."""
        with self._lock:
            self._observers.append(fn)

    def _emit(self, rec: dict) -> None:
        if not self.enabled:  # cheap fast path; authoritative below
            return
        if self.host_id is not None:
            rec = {**rec, "host": self.host_id}
        safe = sanitize_for_json(rec)
        line = json.dumps(safe, allow_nan=False)
        with self._lock:
            # Re-check under the lock: close() (cli shutdown) may race
            # an emitting prefetch/watchdog thread past the unlocked
            # enabled check above.
            if self._fh is None:
                return
            self._tail.append(safe)
            self._fh.write(line + "\n")
            observers = list(self._observers)
        for fn in observers:
            try:
                fn(safe)
            except Exception as e:  # noqa: BLE001 — a broken live
                # consumer must not take down the emission path.
                logger.debug("telemetry observer failed: %s: %s",
                             type(e).__name__, e)

    def close(self) -> None:
        """Stop recording and release the stream handle (idempotent).
        The in-memory tail stays readable for postmortems."""
        with self._lock:
            self.enabled = False
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def tail(self) -> list[dict]:
        """Most recent events, oldest first (postmortem payload)."""
        with self._lock:
            return list(self._tail)

    # -- API --------------------------------------------------------------

    def event(self, name: str, **fields) -> None:
        self._emit({"kind": name, "t": time.time(), **fields})

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Timed region: jsonl span record + XProf trace annotation.

        Nesting is tracked per thread; only DEPTH-0 spans feed the
        goodput ledger, so an instrumented sub-operation (e.g. an
        orbax wait inside a save) never double-counts its parent's
        bucket."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        parent = stack[-1] if stack else None
        stack.append(name)
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(name):
                yield
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            depth = len(stack)
            if self.ledger is not None and depth == 0:
                self.ledger.add(name, dur,
                                steps=1 if name in ("step", "compile")
                                else 0)
            self._emit({"kind": "span", "name": name,
                        "t": time.time(), "dur_s": round(dur, 6),
                        "depth": depth, "parent": parent, **attrs})


# A permanently-disabled instance: the ambient default, so library
# call sites never need a None check.
_NULL = Telemetry(enabled=False)
_current: Telemetry = _NULL


def install(telemetry: Telemetry) -> Telemetry:
    """Make ``telemetry`` the process-ambient sink (one per process,
    like the root logger). Returns it for chaining."""
    global _current
    _current = telemetry
    return telemetry


def uninstall() -> None:
    global _current
    _current = _NULL


def current() -> Telemetry:
    return _current


def span(name: str, **attrs):
    """Module-level span against the ambient Telemetry (always a valid
    trace annotation; a jsonl record only once ``install()``-ed)."""
    return _current.span(name, **attrs)


def event(name: str, **fields) -> None:
    _current.event(name, **fields)
