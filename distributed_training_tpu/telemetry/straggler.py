"""Cross-host straggler detection: who is slowing the pod down.

On a multi-host pod every host's compiled step waits for the slowest
participant's collectives, so local telemetry alone cannot distinguish
"this host is slow" from "this host is WAITING on a slow host" — the
blindness the DDP/FSDP characterization study names as the reason
per-worker skew must be measured, not inferred (arXiv:2505.12832).

The ``StragglerDetector`` runs an on-cadence, off-critical-path
exchange: every ``every`` optimizer steps each host contributes its
window-summed host-side ``step`` and ``data_wait`` seconds to a tiny
jitted all-gather (``multihost_utils.process_allgather`` — one small
f32 vector, dwarfed by the step's own collectives), then every host
independently computes the cross-host medians and flags hosts whose
window mean exceeds ``threshold`` x median. A flag must persist for
``persist`` consecutive windows before it becomes a verdict — one
stochastically slow window (host GC, a checkpoint drain) is noise, a
persistent 2x is a failing host. Verdicts land in the event stream
(kind ``straggler``) and feed the hang watchdog's context, so a
postmortem for a collective hang says "host 3 is 2.1x median on
data_wait" instead of nothing.

The exchange cadence is a function of ``global_step`` only — in
lockstep on every host, like the trainer's agreed-stop poll — because
every host must enter the collective at the same loop point or the
detector itself deadlocks the pod. Disabled when ``process_count == 1``
(nothing to compare) or ``every == 0``.

``flag_stragglers`` is the shared core: the offline aggregator
(telemetry/aggregate.py) applies the same rule to merged per-host
event streams, so a post-hoc skew report and the runtime detector
cannot disagree about what counts as a straggler.
"""

from __future__ import annotations

import logging

import numpy as np

from distributed_training_tpu.telemetry import events as _events

logger = logging.getLogger(__name__)

# Metrics exchanged/compared, in payload order.
METRICS = ("step", "data_wait")


def flag_stragglers(per_host: dict, threshold: float = 1.5,
                    min_gap_s: float = 0.005) -> list[dict]:
    """Flag hosts persistently above the cross-host median.

    ``per_host``: host id → {"step": mean_s, "data_wait": mean_s}
    (missing/None metrics are skipped). A host is flagged on a metric
    when its value is >= ``threshold`` x the median over hosts AND at
    least ``min_gap_s`` above it — the absolute floor keeps a 3us-vs-
    1us data_wait (prefetch keeping up everywhere) from reading as a
    3x straggler. Returns verdict dicts sorted worst-first.
    """
    verdicts: list[dict] = []
    for metric in METRICS:
        vals = {h: float(d[metric]) for h, d in per_host.items()
                if isinstance(d.get(metric), (int, float))}
        if len(vals) < 2:
            continue
        med = float(np.median(list(vals.values())))
        for h, v in vals.items():
            if med > 0 and v >= threshold * med and v - med >= min_gap_s:
                ratio = v / med
                verdicts.append({
                    "host": h, "metric": metric,
                    "ratio": round(ratio, 2),
                    "value_s": round(v, 6),
                    "median_s": round(med, 6),
                    "text": (f"host {h} is {ratio:.1f}x median on "
                             f"{metric} ({v:.3f}s vs {med:.3f}s)"),
                })
    return sorted(verdicts, key=lambda v: -v["ratio"])


def _default_gather(payload: np.ndarray) -> np.ndarray:
    """All-gather one small host-level vector: (k,) → (n_hosts, k)."""
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(payload))


class StragglerDetector:
    """Windowed cross-host step/data_wait exchange + verdicts.

    Trainer contract: ``record_step(step_s, data_wait_s)`` after every
    optimizer step, then ``maybe_exchange(global_step)`` at the same
    loop point on every host. ``watchdog_info()`` returns the latest
    persistent verdicts for postmortem context.
    """

    def __init__(self, runtime, telemetry=None, every: int = 0,
                 threshold: float = 1.5, persist: int = 2,
                 min_gap_s: float = 0.005, gather=None,
                 evict_after: int = 0, elastic_dir: str | None = None):
        self.every = int(every)
        self.threshold = threshold
        self.persist = max(1, int(persist))
        self.min_gap_s = min_gap_s
        # Consecutive flagged windows before a verdict escalates to a
        # COORDINATED eviction request (0 = verdicts stay advisory).
        # The decision is computed from the all-gathered table, so it
        # lands on every host at the same exchange step — each host
        # breaks its loop at the same point and no one is stranded in
        # a collective (the cadence discipline, extended to teardown).
        self.evict_after = max(0, int(evict_after))
        # Where the coordinator writes the eviction-request sentinel
        # the elastic supervisor consumes (resilience/elastic.py);
        # exits carry the verdict too, via host_lost exit sentinels.
        self.elastic_dir = elastic_dir
        self.evict_request: dict | None = None
        self.process_index = runtime.process_index
        self.process_count = runtime.process_count
        self.enabled = self.every > 0 and self.process_count > 1
        self._telemetry = telemetry
        self._gather = gather or _default_gather
        # Window accumulators (host-local, reset at each exchange).
        self._sums = dict.fromkeys(METRICS, 0.0)
        self._n = 0
        # (host, metric) → consecutive flagged windows.
        self._streaks: dict = {}
        self.last: dict | None = None  # latest exchange summary

    @property
    def telemetry(self):
        # Resolve the ambient sink per use (install() may come late).
        return (self._telemetry if self._telemetry is not None
                else _events.current())

    def record_step(self, step_s: float, data_wait_s: float) -> None:
        if not self.enabled:
            return
        self._sums["step"] += step_s
        self._sums["data_wait"] += data_wait_s
        self._n += 1

    def maybe_exchange(self, global_step: int) -> dict | None:
        """Exchange + verdict pass, on the step cadence. Returns the
        summary (also emitted as a ``straggler`` event), or None off
        cadence / when disabled. The cadence predicate must stay a
        pure function of ``global_step``: every host has to reach the
        collective at the same loop point (see module docstring)."""
        if (not self.enabled or self._n == 0
                or global_step % self.every != 0):
            return None
        payload = np.asarray(
            [self._sums[m] for m in METRICS] + [float(self._n)],
            dtype=np.float32)
        try:
            table = self._gather(payload)
        except Exception as e:  # noqa: BLE001 — observability must
            # not take down the training loop it observes. A backend
            # without cross-process gathers (multi-process CPU) fails
            # on EVERY host at the same loop point, so disabling here
            # is symmetric — no host is left waiting in a collective.
            logger.warning("straggler exchange failed (%s); detector "
                           "disabled for the rest of the run", e)
            self.enabled = False
            self.telemetry.event("straggler_disabled",
                                 step=global_step, error=str(e)[:300])
            return None
        self._sums = dict.fromkeys(METRICS, 0.0)
        self._n = 0
        per_host: dict[int, dict] = {}
        for h, row in enumerate(np.asarray(table, dtype=np.float64)):
            n = max(1.0, float(row[len(METRICS)]))
            per_host[h] = {m: float(row[i]) / n
                           for i, m in enumerate(METRICS)}
        verdicts = flag_stragglers(per_host, self.threshold,
                                   self.min_gap_s)
        flagged = {(v["host"], v["metric"]) for v in verdicts}
        self._streaks = {k: self._streaks.get(k, 0) + 1
                         for k in flagged}
        persistent = [v for v in verdicts
                      if self._streaks[(v["host"], v["metric"])]
                      >= self.persist]
        summary = {
            "step": global_step,
            "per_host": {str(h): {m: round(x, 6)
                                  for m, x in d.items()}
                         for h, d in per_host.items()},
            "verdicts": verdicts,
            "persistent": [v["text"] for v in persistent],
        }
        self._maybe_request_eviction(global_step, verdicts)
        if self.evict_request is not None:
            summary["eviction"] = self.evict_request
        self.last = summary
        self.telemetry.event("straggler", **summary)
        return summary

    def _maybe_request_eviction(self, global_step: int,
                                verdicts: list[dict]) -> None:
        """Escalate a long-persistent verdict into an eviction request.
        Streaks are derived from the shared gathered table, so every
        host reaches the same conclusion at the same step; the
        request itself is a flag the trainer polls (coordinated clean
        stop) plus a coordinator-written sentinel FILE for the
        supervisor — never a kill."""
        if not self.evict_after or self.evict_request is not None:
            return
        worst = next(
            (v for v in verdicts  # verdicts arrive worst-first
             if self._streaks.get((v["host"], v["metric"]), 0)
             >= self.evict_after), None)
        if worst is None:
            return
        self.evict_request = {
            "host": int(worst["host"]), "step": global_step,
            "metric": worst["metric"], "ratio": worst["ratio"],
            "reason": "straggler",
        }
        logger.warning(
            "eviction requested: host %d is %.1fx median on %s for "
            ">= %d windows — coordinated stop for elastic "
            "reconfiguration", worst["host"], worst["ratio"],
            worst["metric"], self.evict_after)
        self.telemetry.event("eviction_request", **self.evict_request)
        if self.process_index == 0 and self.elastic_dir:
            # Filesystem-only and idempotent — safe to gate by host
            # (no collective behind this guard).
            from distributed_training_tpu.resilience import elastic
            elastic.write_eviction_request(self.elastic_dir,
                                           **self.evict_request)

    def watchdog_info(self) -> dict:
        """Context for HangWatchdog.set_context: the latest persistent
        verdicts (empty dict when there is nothing to say)."""
        if self.last and self.last["persistent"]:
            return {"straggler": list(self.last["persistent"])}
        return {}
