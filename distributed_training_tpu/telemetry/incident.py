"""Incident flight recorder: atomic evidence bundles + auto-actions.

``Telemetry`` already keeps a bounded in-memory tail of every emitted
record — a flight-recorder ring buffer in all but name. This module
gives it a crash cart: ``write_incident_bundle`` snapshots that ring
buffer (plus the anomaly verdict, the latest step-time attribution and
a serving ``/debug/requests`` snapshot when one is live) into ONE
timestamped, atomically-published directory, and ``IncidentRecorder``
— another ``Telemetry.add_observer`` consumer, so pure host-side —
writes such a bundle whenever the stream says something went wrong:
an ``anomaly`` (telemetry/anomaly.py), a ``watchdog_fired`` abort
(the watchdog's abort path emits BEFORE ``os._exit``, so the bundle
is on disk when the process dies), a ``supervisor_give_up``, or an
explicit call (the CLI records a ``preemption`` incident on SIGTERM
drain). Bundles land under ``<run_dir>/incidents/<ts>/`` on the
coordinator only.

The HangWatchdog postmortem (telemetry/watchdog.py) now delegates to
the same writer, so a postmortem directory and an incident bundle are
one format: ``meta.json`` (schema/kind/reason), ``stacks.txt``,
``events_tail.jsonl``, ``memory_stats.json``, and the optional
``anomaly.json`` / ``attribution.json`` / ``serving_requests.json``.
The offline doctor (telemetry/doctor.py) classifies either a run dir
or one of these bundles with the same rules.

Atomicity: everything is written into ``<path>.tmp`` and published
with one ``os.rename`` — a crash mid-write leaves a ``.tmp`` turd,
never a half-bundle that the doctor would misread as complete.

``arm_autoprofile`` is the closed-loop profiling action: record the
decision in a write-before-action ledger (the resilience/faults.py
discipline — so a crash between ledger and action cannot re-fire it
every restarted incarnation), THEN drop the existing ``profile_now``
trigger file that ``ProfileCapture`` already consumes. One-shot per
key across supervisor restarts.
"""

from __future__ import annotations

import faulthandler
import itertools
import json
import logging
import os
import threading
import time

from distributed_training_tpu.telemetry.attribution import TRIGGER_FILE

logger = logging.getLogger(__name__)

SCHEMA = 1

# Bundle layout, pinned by test: core files always present, optional
# files present when the corresponding evidence existed at capture.
BUNDLE_CORE_FILES = ("meta.json", "stacks.txt", "events_tail.jsonl",
                     "memory_stats.json")
BUNDLE_OPTIONAL_FILES = ("anomaly.json", "attribution.json",
                         "serving_requests.json")

# Incident kinds the recorder emits / the doctor understands.
KINDS = ("anomaly", "watchdog", "preemption", "give_up", "manual",
         "engine_crash")

AUTOPROFILE_LEDGER = "autoprofile_fired.json"

# Monotonic per-process suffix: two bundles in the same second must
# land in distinct directories, not overwrite each other.
_SEQ = itertools.count()


def _device_memory_stats() -> list[dict]:
    """Best-effort per-device memory stats via the watchdog helper
    (lazy import: watchdog imports this module for the bundle writer,
    so the dependency must only run at call time)."""
    from distributed_training_tpu.telemetry.watchdog import (
        _device_memory_stats as stats)
    return stats()


def write_incident_bundle(base_dir: str, reason: str,
                          kind: str = "manual",
                          events_tail: list | None = None,
                          extra: dict | None = None,
                          anomaly: dict | None = None,
                          attribution: dict | None = None,
                          serving: dict | None = None) -> str:
    """Write one timestamped incident bundle; returns its path.

    Never raises — an incident writer that can crash its host process
    is worse than no incident bundle. Dump ordering is deliberate
    (the watchdog discipline): meta + stacks + events first (pure
    host-side, cannot hang), device memory stats last and in a
    bounded daemon thread (they touch the backend, which is exactly
    what may be wedged) — a hang mid-dump still publishes the stacks,
    and an absent/empty ``memory_stats.json`` is itself a finding.
    """
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = os.path.join(
        base_dir, f"{stamp}_pid{os.getpid()}_{next(_SEQ)}")
    tmp = path + ".tmp"
    try:
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"schema": SCHEMA, "kind": kind,
                       "reason": reason, "time_unix": time.time(),
                       "pid": os.getpid(), **(extra or {})}, f,
                      indent=1)
        with open(os.path.join(tmp, "stacks.txt"), "w") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
        # noqa'd DTT001: a flight-recorder COPY of already-emitted
        # records, not an emission path — host tags are already on
        # the records.
        with open(os.path.join(tmp, "events_tail.jsonl"), "w") as f:  # noqa: DTT001
            for rec in events_tail or []:
                f.write(json.dumps(rec) + "\n")
        for name, payload in (("anomaly.json", anomaly),
                              ("attribution.json", attribution),
                              ("serving_requests.json", serving)):
            if payload is not None:
                with open(os.path.join(tmp, name), "w") as f:
                    json.dump(payload, f, indent=1)

        def _dump_memory():
            try:
                stats = _device_memory_stats()
                with open(os.path.join(tmp, "memory_stats.json"),
                          "w") as f:
                    json.dump(stats, f, indent=1)
            except Exception as e:  # noqa: BLE001 — the bundle may
                # already be renamed out from under a straggler query
                # (join timeout below); best-effort by design.
                logger.debug("incident memory_stats skipped: %s: %s",
                             type(e).__name__, e)
        t = threading.Thread(target=_dump_memory, daemon=True,
                             name="incident-memory-stats")
        t.start()
        t.join(timeout=10)
        os.rename(tmp, path)
    except Exception as e:  # noqa: BLE001 — never raises (docstring);
        # best-effort breadcrumb only (DTT002: no silent swallows).
        logger.debug("incident bundle incomplete at %s: %s: %s",
                     path, type(e).__name__, e)
    return path


def is_incident_bundle(path: str) -> bool:
    """A directory is a bundle when it carries the core evidence pair
    (the doctor's run-dir-vs-bundle dispatch)."""
    return (os.path.isfile(os.path.join(path, "meta.json"))
            and os.path.isfile(os.path.join(path,
                                            "events_tail.jsonl")))


def arm_autoprofile(run_dir: str, key: str,
                    evidence: dict | None = None) -> bool:
    """One-shot closed-loop profile trigger (module docstring).

    Returns True when THIS call armed the capture; False when the
    ledger says ``key`` already fired (this run or a previous
    incarnation of it). Ledger write happens BEFORE the drop file.
    """
    inc_dir = os.path.join(run_dir, "incidents")
    ledger = os.path.join(inc_dir, AUTOPROFILE_LEDGER)
    fired: dict = {}
    if os.path.exists(ledger):
        try:
            with open(ledger, encoding="utf-8") as f:
                fired = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("autoprofile ledger unreadable (%s); "
                           "refusing to re-arm", e)
            return False
    if key in fired:
        return False
    fired[key] = {"time_unix": time.time(),
                  "evidence": evidence or {}}
    try:
        os.makedirs(inc_dir, exist_ok=True)
        tmp = ledger + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(fired, f, indent=1)
        os.replace(tmp, ledger)
        # Ledger durable: now act. ProfileCapture consumes the drop
        # file by os.remove at the next maybe_start().
        with open(os.path.join(run_dir, TRIGGER_FILE), "w") as f:
            f.write(json.dumps({"armed_by": "anomaly", "key": key}))
    except OSError as e:
        logger.warning("autoprofile arm failed: %s", e)
        return False
    logger.info("anomaly detector armed in-run profile capture "
                "(%s)", key)
    return True


class IncidentRecorder:
    """Observer that turns bad news on the event stream into bundles.

    ``detector`` (an AnomalyDetector) contributes ``anomaly.json``;
    ``serving_snapshot`` is a zero-device-touch callable returning the
    ``/debug/requests`` payload (serving/server.py exposes one). The
    recorder caches the latest ``attribution`` record it sees flow by,
    so a bundle carries the most recent trace decomposition even when
    it has scrolled out of the ring buffer. Per-kind cooldown keeps an
    anomaly storm from writing hundreds of near-identical bundles;
    ``max_bundles`` is the hard cap.
    """

    TRIGGER_KINDS = {"anomaly": "anomaly",
                     "watchdog_fired": "watchdog",
                     "supervisor_give_up": "give_up"}

    def __init__(self, run_dir: str, telemetry=None, detector=None,
                 serving_snapshot=None, enabled: bool = True,
                 cooldown_s: float = 60.0, max_bundles: int = 32):
        self.run_dir = run_dir
        self.incidents_dir = os.path.join(run_dir, "incidents")
        self._tel = telemetry
        self._detector = detector
        self._serving_snapshot = serving_snapshot
        self.enabled = enabled
        self.cooldown_s = float(cooldown_s)
        self.max_bundles = int(max_bundles)
        self.incidents_total = 0
        self._lock = threading.Lock()
        self._last_fire: dict[str, float] = {}
        self._last_attribution: dict | None = None

    def observe(self, rec: dict) -> None:
        """Telemetry observer (sanitized record, post-write)."""
        kind = rec.get("kind")
        if kind in ("attribution",):
            self._last_attribution = rec
            return
        trigger = self.TRIGGER_KINDS.get(kind)
        if trigger is None:
            return
        reason = (rec.get("detail")
                  or f"{trigger} event: "
                     f"{rec.get('signal') or rec.get('reason') or kind}")
        self.record(trigger, reason=reason, trigger=rec)

    def record(self, kind: str, reason: str,
               trigger: dict | None = None) -> str | None:
        """Write one bundle now (cooldown/cap permitting); returns its
        path or None. Safe to call from observer context and from the
        CLI teardown path."""
        if not self.enabled:
            return None
        now = time.monotonic()
        with self._lock:
            if self.incidents_total >= self.max_bundles:
                return None
            last = self._last_fire.get(kind)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last_fire[kind] = now
            self.incidents_total += 1
            seq = self.incidents_total
        tail = self._tel.tail() if self._tel is not None else []
        anomaly = None
        if self._detector is not None:
            try:
                anomaly = self._detector.verdict()
            except Exception as e:  # noqa: BLE001 — evidence layers
                # are each optional; a broken one must not stop the
                # bundle.
                logger.debug("anomaly verdict unavailable: %s", e)
        serving = None
        if self._serving_snapshot is not None:
            try:
                serving = self._serving_snapshot()
            except Exception as e:  # noqa: BLE001 — see above.
                logger.debug("serving snapshot unavailable: %s", e)
        extra = {"incident_seq": seq}
        if trigger is not None:
            extra["trigger"] = {k: trigger.get(k) for k in
                                ("kind", "signal", "value", "median",
                                 "deviation", "step", "reason",
                                 "postmortem", "outcome")
                                if trigger.get(k) is not None}
        path = write_incident_bundle(
            self.incidents_dir, reason=reason, kind=kind,
            events_tail=tail, extra=extra, anomaly=anomaly,
            attribution=self._last_attribution, serving=serving)
        if self._tel is not None:
            # "incident_kind", not "kind": the sink uses "kind" as the
            # record type and a kwarg would silently overwrite it (the
            # faults.py "fault_kind" discipline).
            self._tel.event("incident", schema=SCHEMA,
                            incident_kind=kind, reason=reason, seq=seq,
                            path=os.path.relpath(path, self.run_dir))
        return path
