"""CLI entry: ``python -m distributed_training_tpu.telemetry <run_dir>``."""

import os
import sys

from distributed_training_tpu.telemetry.summarize import main

if __name__ == "__main__":
    try:
        rc = main()
    except BrokenPipeError:
        # Piped into head/less that quit early — not an error. Point
        # stdout at devnull so the interpreter's exit flush doesn't
        # raise a second time.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        rc = 0
    sys.exit(rc)
