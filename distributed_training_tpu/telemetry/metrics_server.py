"""Live metrics endpoint: Prometheus text exposition off the event sink.

The serving fleet and the multi-job scheduler (ROADMAP items 1/5) need
a machine-readable live view of every running job; scraping
``events.jsonl`` off N hosts is not that. This module is a stdlib-only
HTTP server the train CLI runs on the COORDINATOR
(``train.metrics_port``), registered as an observer on the ambient
``Telemetry`` sink — every gauge below is derived from records the
sink already emits (goodput windows, spans, straggler verdicts,
attribution events), so the endpoint and the jsonl stream can never
disagree: one metrics source of truth, two transports.

Endpoints:

- ``GET /metrics`` — Prometheus text exposition (version 0.0.4):
  ``dtt_step_time_seconds``, ``dtt_tokens_per_s``, ``dtt_mfu``,
  ``dtt_goodput``, ``dtt_data_wait_seconds_total``,
  ``dtt_overlap_fraction`` (measured; ``dtt_overlap_static_fraction``
  for the compiled-schedule score), ``dtt_straggler_verdicts_total``,
  ``dtt_world_size`` / ``dtt_incarnation`` (elastic machinery),
  ``dtt_steps_total``, ``dtt_up``.
- ``GET /healthz`` — 200 while the step loop makes progress; 503 once
  no step has completed for longer than the stall threshold (the CLI
  feeds ``train.watchdog_timeout_s``; the first step gets the same
  10x compile allowance the watchdog gives it). Load balancers and
  the fleet scheduler key off this.

The observer callback runs on whatever thread emits the record and
must stay cheap (dict updates); the HTTP side reads a snapshot under
the same lock. Server failures (port taken, socket errors) log and
disable — a metrics endpoint must never take down the run it reports
on.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
import time

logger = logging.getLogger(__name__)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Fixed histogram bucket bounds (seconds / tokens). FIXED and
# documented on purpose: Prometheus histograms aggregate across
# hosts/scrapes only when every emitter uses identical ``le`` bounds —
# a per-host adaptive choice would make fleet-level quantiles
# meaningless. Bounds follow the Prometheus latency idiom
# (1-2.5-5 per decade); tokens/request uses powers of two up to the
# engine's typical max_seq_len scale. The gauge
# ``dtt_serving_ttft_seconds`` (last finished request) stays for
# dashboards; these histograms are the SLO source of truth.
#
# Naming note: the TTFT histogram is ``time_to_first_token`` in full
# because the short name already belongs to the LAST-VALUE gauge
# ``dtt_serving_ttft_seconds`` (pinned schema since r01) and the
# exposition format forbids two metric families under one name — a
# same-name gauge + histogram pair is a scrape error, not a style
# choice.
HIST_BUCKETS: dict[str, tuple[float, ...]] = {
    "serving_time_to_first_token_seconds": (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 10.0),
    "serving_e2e_seconds": (
        0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
        30.0, 60.0),
    "serving_queue_wait_seconds": (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 10.0),
    "serving_tokens_per_request": (
        1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
        1024.0),
}


class MetricsServer:
    """Prometheus endpoint fed by Telemetry records.

    ``tokens_per_step`` converts step durations into a throughput
    gauge (tokens == samples for non-token models); ``stall_timeout_s``
    drives ``/healthz`` (0 = never unhealthy); ``info`` is static
    run identity (world_size, incarnation, host) exported as gauges.
    ``port=0`` binds an ephemeral port (tests); read ``.port`` after
    ``start()``.
    """

    def __init__(self, port: int, telemetry=None,
                 tokens_per_step: float = 0.0,
                 stall_timeout_s: float = 0.0,
                 info: dict | None = None,
                 host: str = "0.0.0.0"):
        self._requested_port = port
        self._host = host
        self.tokens_per_step = tokens_per_step
        self.stall_timeout_s = stall_timeout_s
        self._lock = threading.Lock()
        self._gauges: dict[str, float] = {}
        # Labeled gauge families: name -> {label-pairs -> value}
        # (the per-dp-group serving gauges; rendered as
        # dtt_<name>{group="N"} rows, additive next to the flat set).
        self._labeled: dict[str, dict[str, float]] = {}
        # Labeled COUNTER families (anomalies by signal): same label
        # layout, rendered with TYPE counter — a separate dict because
        # the exposition format pins one TYPE per family.
        self._labeled_counters: dict[str, dict[str, float]] = {}
        # Histogram families: name -> {tenant -> state}. Bounds are
        # the module-level HIST_BUCKETS; state is cumulative-ready
        # (per-bound counts + sum + count, +Inf implied by count).
        self._hists: dict[str, dict[str, dict]] = {}
        self._counters: dict[str, float] = {"steps_total": 0.0,
                                            "straggler_verdicts_total":
                                                0.0,
                                            "data_wait_seconds_total":
                                                0.0}
        for k, v in (info or {}).items():
            if isinstance(v, (int, float)):
                self._gauges[k] = float(v)
        self._started_at = time.monotonic()
        self._last_step_at: float | None = None
        self._last_progress_at: float | None = None
        self._httpd = None
        self._thread = None
        self.port: int | None = None
        # Observer registration happens in start(), AFTER a
        # successful bind — a server whose port was taken must not
        # keep folding every telemetry record for the rest of the
        # run while serving nothing.
        self._telemetry = telemetry

    # -- feed ----------------------------------------------------------

    def observe(self, rec: dict) -> None:
        """Telemetry observer: fold one emitted record into the
        gauges. Must not raise (the sink swallows, but cheap safety
        beats a stack trace per step)."""
        kind = rec.get("kind")
        with self._lock:
            if kind == "span":
                # ANY main-loop span closing is liveness evidence —
                # a run inside a long deliberate non-step phase
                # (checkpoint drain, eval, a mid-run re-compile)
                # is slow, not dead, and /healthz must not route
                # traffic away from it. data_assemble is excluded:
                # it closes on the prefetch thread, which can stay
                # briefly alive after the main loop wedges.
                if rec.get("name") != "data_assemble":
                    self._last_progress_at = time.monotonic()
            if kind == "span" and rec.get("name") in ("step",
                                                      "compile"):
                # The FIRST optimizer step dispatches under a
                # "compile" span (trainer.py): it is still a
                # completed step — counting only "step" spans would
                # export steps_total = N-1 and hold the healthz
                # first-step latch one step too long. Its duration is
                # compile-dominated though, so the step-time/tokens
                # gauges wait for a real "step" span.
                self._counters["steps_total"] += 1
                self._last_step_at = time.monotonic()
            if kind == "span" and rec.get("name") == "step":
                dur = rec.get("dur_s")
                if isinstance(dur, (int, float)) and dur > 0:
                    self._gauges["step_time_seconds"] = dur
                    if self.tokens_per_step:
                        self._gauges["tokens_per_s"] = (
                            self.tokens_per_step / dur)
            elif kind == "span" and rec.get("name") == "data_wait":
                dur = rec.get("dur_s")
                if isinstance(dur, (int, float)):
                    self._counters["data_wait_seconds_total"] += dur
            elif kind == "goodput":
                for src, dst in (("mfu_wall", "mfu"),
                                 ("goodput", "goodput")):
                    if isinstance(rec.get(src), (int, float)):
                        self._gauges[dst] = rec[src]
            elif kind == "attribution":
                for src, dst in (
                        ("overlap_frac", "overlap_fraction"),
                        ("compute_frac", "compute_fraction"),
                        ("collective_frac", "collective_fraction"),
                        ("host_frac", "host_fraction")):
                    if isinstance(rec.get(src), (int, float)):
                        self._gauges[dst] = rec[src]
            elif kind == "attribution_static":
                if isinstance(rec.get("overlap_score"), (int, float)):
                    self._gauges["overlap_static_fraction"] = \
                        rec["overlap_score"]
            elif kind == "straggler":
                persistent = rec.get("persistent") or []
                self._gauges["straggler_flagged"] = float(
                    len(persistent))
                if persistent:
                    self._counters["straggler_verdicts_total"] += len(
                        persistent)
            elif kind == "resume":
                if isinstance(rec.get("world_size"), int):
                    self._gauges["world_size"] = rec["world_size"]
                if isinstance(rec.get("restarts"), int):
                    self._gauges["incarnation"] = rec["restarts"]
            elif kind == "clock_sync":
                if isinstance(rec.get("process_count"), int):
                    self._gauges.setdefault(
                        "world_size", float(rec["process_count"]))
            elif kind == "collectives":
                if isinstance(rec.get("bytes_per_step"), (int, float)):
                    self._gauges["collective_bytes_per_step"] = \
                        rec["bytes_per_step"]
            elif kind == "serving":
                # Engine step records (serving/engine.py) — additive
                # serving gauges next to the training ones; schema
                # pinned by tests/test_serving.py.
                for src, dst in (
                        ("in_flight", "serving_requests_in_flight"),
                        ("queue_depth", "serving_queue_depth"),
                        ("pages_used", "serving_kv_pages_used"),
                        ("pages_total", "serving_kv_pages_total")):
                    if isinstance(rec.get(src), (int, float)):
                        self._gauges[dst] = float(rec[src])
                dur = rec.get("dur_s")
                toks = rec.get("tokens")
                if isinstance(dur, (int, float)) and dur > 0 \
                        and isinstance(toks, (int, float)) and toks:
                    # "tokens" means NEW tokens on decode steps and
                    # PROMPT tokens on (batched) prefill steps
                    # (serving/engine.py step records) — two gauges,
                    # split by op.
                    if rec.get("op") == "prefill":
                        self._gauges["serving_prefill_tokens_per_s"] \
                            = toks / dur
                    else:
                        self._gauges["serving_tokens_per_s"] = \
                            toks / dur
                if isinstance(rec.get("spec_accepted_mean"),
                              (int, float)):
                    # Speculative decode acceptance length (tokens
                    # emitted per slot-launch, serving/engine.py).
                    self._gauges["serving_spec_accepted_mean"] = \
                        float(rec["spec_accepted_mean"])
                # Device-resident decode + weight-store gauges
                # (SERVING_r04, serving/engine.py step records).
                for src, dst in (
                        ("host_syncs_per_token",
                         "serving_host_syncs_per_token"),
                        ("resident_steps_per_launch",
                         "serving_resident_steps_per_launch"),
                        ("weight_bytes", "serving_weight_bytes")):
                    if isinstance(rec.get(src), (int, float)):
                        self._gauges[dst] = float(rec[src])
                # Per-dp-group shard gauges (the dp-sharded engine's
                # step records carry per-group lists — serving/
                # engine.py + kv_cache.occupancy; schema pinned by
                # tests/test_serving.py).
                for src, dst in (
                        ("group_slots_active",
                         "serving_group_slots_active"),
                        ("group_prefill_slots_active",
                         "serving_group_prefill_slots_active"),
                        ("group_pages_used",
                         "serving_group_kv_pages_used"),
                        ("group_seqs", "serving_group_seqs"),
                        ("kv_pages_shared",
                         "serving_kv_pages_shared")):
                    vals = rec.get(src)
                    if isinstance(vals, (list, tuple)):
                        fam = self._labeled.setdefault(dst, {})
                        for g, v in enumerate(vals):
                            if isinstance(v, (int, float)):
                                fam[f'group="{g}"'] = float(v)
                # Prefix-sharing counters + session gauge (SERVING_r05
                # step records are additive: sharing-disabled engines
                # simply omit these keys).
                for src, dst in (
                        ("prefix_hit_tokens",
                         "serving_prefix_hit_tokens_total"),
                        ("prefill_tokens_saved",
                         "serving_prefill_tokens_saved_total")):
                    if isinstance(rec.get(src), (int, float)):
                        self._counters[dst] = \
                            self._counters.get(dst, 0.0) + rec[src]
                if isinstance(rec.get("sessions_resident"),
                              (int, float)):
                    self._gauges["serving_sessions_resident"] = \
                        float(rec["sessions_resident"])
            elif kind == "anomaly":
                # Online-detector verdicts (telemetry/anomaly.py) —
                # one counter per signal so an alert rule can key on
                # dtt_anomalies_total{kind="step_time"}.
                sig = rec.get("signal")
                if isinstance(sig, str) and sig:
                    fam = self._labeled_counters.setdefault(
                        "anomalies_total", {})
                    key = f'kind="{sig}"'
                    fam[key] = fam.get(key, 0.0) + 1
            elif kind == "anomaly_baseline":
                # Low-cadence rolling-baseline snapshots: what the
                # detector currently considers normal.
                for src, dst in (
                        ("step_time_s", "anomaly_baseline_step_time_s"),
                        ("data_wait_s",
                         "anomaly_baseline_data_wait_s")):
                    if isinstance(rec.get(src), (int, float)):
                        self._gauges[dst] = float(rec[src])
            elif kind == "incident":
                self._counters["incidents_total"] = \
                    self._counters.get("incidents_total", 0.0) + 1
            elif kind == "serving_kv":
                # Allocator records: keep occupancy live even between
                # engine steps (join/evict happen inside steps, but
                # warmup/adopt/preempt touch the pool outside them).
                for src, dst in (
                        ("pages_used", "serving_kv_pages_used"),
                        ("pages_total", "serving_kv_pages_total")):
                    if isinstance(rec.get(src), (int, float)):
                        self._gauges[dst] = float(rec[src])
            elif kind == "serving_request":
                if isinstance(rec.get("ttft_s"), (int, float)):
                    self._gauges["serving_ttft_seconds"] = \
                        rec["ttft_s"]
                self._counters["serving_requests_total"] = \
                    self._counters.get("serving_requests_total",
                                       0.0) + 1
                # Per-tenant latency histograms — the SLO source of
                # truth (the gauge above is last-value only). One
                # observation per finished request, labeled by the
                # tenant the HTTP body carried (engine default:
                # "default").
                tenant = rec.get("tenant")
                if not isinstance(tenant, str) or not tenant:
                    tenant = "default"
                for src, name in (
                        ("ttft_s",
                         "serving_time_to_first_token_seconds"),
                        ("latency_s", "serving_e2e_seconds"),
                        ("queue_wait_s",
                         "serving_queue_wait_seconds"),
                        ("new_tokens",
                         "serving_tokens_per_request")):
                    v = rec.get(src)
                    if isinstance(v, (int, float)):
                        self._hist_observe(name, tenant, float(v))

    def _hist_observe(self, name: str, tenant: str,
                      value: float) -> None:
        """Fold one observation into a tenant-labeled histogram.
        Caller holds ``self._lock``."""
        bounds = HIST_BUCKETS[name]
        fam = self._hists.setdefault(name, {})
        st = fam.setdefault(tenant, {
            "counts": [0] * len(bounds), "sum": 0.0, "count": 0})
        for i, b in enumerate(bounds):
            if value <= b:
                st["counts"][i] += 1
        st["sum"] += value
        st["count"] += 1

    # -- health --------------------------------------------------------

    def health(self) -> tuple[bool, dict]:
        """(healthy, detail). Unhealthy only when a stall threshold is
        configured and the step loop has been silent past it — with
        the watchdog's 10x first-step (compile) allowance before the
        first step lands."""
        with self._lock:
            first_step_done = self._last_step_at is not None
            last = self._last_progress_at
            steps = self._counters["steps_total"]
        now = time.monotonic()
        detail: dict = {"steps": int(steps)}
        if not self.stall_timeout_s:
            return True, {**detail, "status": "ok",
                          "stall_watch": "disabled"}
        if not first_step_done:
            budget = self.stall_timeout_s * 10
            silent = now - (last if last is not None
                            else self._started_at)
            detail["status"] = "starting"
        else:
            budget = self.stall_timeout_s
            silent = now - (last if last is not None else
                            self._started_at)
            detail["status"] = "ok"
        detail["silent_s"] = round(silent, 3)
        if silent > budget:
            detail["status"] = "stalled"
            detail["stall_threshold_s"] = budget
            return False, detail
        return True, detail

    # -- render --------------------------------------------------------

    _HELP = {
        "step_time_seconds": "Last completed optimizer step duration",
        "tokens_per_s": "Throughput from the last step "
                        "(tokens == samples for non-token models)",
        "mfu": "Wall-clock MFU of the last goodput window",
        "goodput": "Step seconds / wall seconds, last goodput window",
        "overlap_fraction": "Measured share of collective time hidden "
                            "under compute (last attribution capture)",
        "overlap_static_fraction": "Compiled-schedule overlap score "
                                   "(attribution_static)",
        "compute_fraction": "Measured compute share of step time",
        "collective_fraction": "Measured exposed-collective share",
        "host_fraction": "Measured host/data share of step time",
        "world_size": "Process count of the current incarnation",
        "incarnation": "Supervisor restart count of this incarnation",
        "straggler_flagged": "Hosts flagged in the last straggler "
                             "exchange",
        "collective_bytes_per_step": "Static per-step collective "
                                     "traffic (bytes/participant)",
        "steps_total": "Optimizer steps completed this incarnation",
        "data_wait_seconds_total": "Cumulative host time blocked on "
                                   "the input pipeline",
        "straggler_verdicts_total": "Cumulative persistent straggler "
                                    "verdicts observed",
        "up": "1 while the run is serving metrics",
        "serving_requests_in_flight": "Sequences in the engine's "
                                      "slot table (serving/)",
        "serving_queue_depth": "Requests waiting for admission",
        "serving_kv_pages_used": "KV-cache pages allocated",
        "serving_kv_pages_total": "KV-cache pages in the pool "
                                  "(scratch excluded)",
        "serving_ttft_seconds": "Time-to-first-token of the LAST "
                                "FINISHED request only (a gauge — "
                                "quantiles and SLOs come from the "
                                "dtt_serving_time_to_first_token_"
                                "seconds histogram)",
        "serving_time_to_first_token_seconds":
            "Time-to-first-token per finished request, by tenant "
            "(histogram; the SLO source of truth)",
        "serving_e2e_seconds": "Arrival-to-finish latency per "
                               "finished request, by tenant "
                               "(histogram)",
        "serving_queue_wait_seconds": "Arrival-to-admission wait per "
                                      "finished request, by tenant "
                                      "(histogram)",
        "serving_tokens_per_request": "New tokens generated per "
                                      "finished request, by tenant "
                                      "(histogram)",
        "serving_tokens_per_s": "Decode throughput of the last "
                                "engine step",
        "serving_prefill_tokens_per_s": "Aggregate prompt tokens/s "
                                        "of the last batched "
                                        "prefill step",
        "serving_spec_accepted_mean": "Speculative decode mean "
                                      "accepted chain length, last "
                                      "decode step",
        "serving_host_syncs_per_token": "Device-to-host syncs per "
                                        "emitted token, last engine "
                                        "step (resident decode "
                                        "drives this toward 1/K)",
        "serving_resident_steps_per_launch": "Mean while_loop "
                                             "iterations per "
                                             "device-resident burst, "
                                             "last decode step",
        "serving_weight_bytes": "Bytes of the engine's resident "
                                "weight tree (int8 stores shrink "
                                "this ~4x vs fp32)",
        "serving_requests_total": "Requests completed by the engine",
        "serving_group_slots_active": "Active decode slots per dp "
                                      "group (dp-sharded engine)",
        "serving_group_prefill_slots_active": "Batched-prefill lanes "
                                              "live per dp group in "
                                              "the last prefill "
                                              "launch",
        "serving_group_kv_pages_used": "KV pages allocated in each "
                                       "dp group's pool shard",
        "serving_group_seqs": "Sequences resident per dp group",
        "serving_kv_pages_shared": "KV pages with refcount > 1 per "
                                   "dp group (prefix sharing)",
        "serving_prefix_hit_tokens_total": "Prompt tokens served from "
                                           "the prefix index instead "
                                           "of being prefilled",
        "serving_prefill_tokens_saved_total": "Prefill compute "
                                              "avoided by prefix "
                                              "sharing and session "
                                              "resume (tokens)",
        "serving_sessions_resident": "Retained chat sessions holding "
                                     "KV pages for zero-prefill "
                                     "resume",
        "anomalies_total": "Online anomaly-detector verdicts by "
                           "signal (telemetry/anomaly.py)",
        "incidents_total": "Incident bundles written by the flight "
                           "recorder (telemetry/incident.py)",
        "anomaly_baseline_step_time_s": "Detector rolling-median "
                                        "step-time baseline",
        "anomaly_baseline_data_wait_s": "Detector rolling-median "
                                        "data-wait baseline",
    }

    def render(self) -> str:
        """The /metrics payload (Prometheus text format 0.0.4)."""
        with self._lock:
            gauges = dict(self._gauges)
            counters = dict(self._counters)
            labeled = {k: dict(v) for k, v in self._labeled.items()}
            labeled_counters = {k: dict(v) for k, v in
                                self._labeled_counters.items()}
            hists = {name: {t: {"counts": list(st["counts"]),
                                "sum": st["sum"],
                                "count": st["count"]}
                            for t, st in fam.items()}
                     for name, fam in self._hists.items()}
        gauges["up"] = 1.0
        lines: list[str] = []
        for name, value in sorted(gauges.items()):
            full = f"dtt_{name}"
            lines.append(f"# HELP {full} {self._HELP.get(name, name)}")
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {_fmt(value)}")
        for name, fam in sorted(labeled.items()):
            full = f"dtt_{name}"
            lines.append(f"# HELP {full} {self._HELP.get(name, name)}")
            lines.append(f"# TYPE {full} gauge")
            for labels, value in sorted(fam.items()):
                lines.append(f"{full}{{{labels}}} {_fmt(value)}")
        for name, value in sorted(counters.items()):
            full = f"dtt_{name}"
            lines.append(f"# HELP {full} {self._HELP.get(name, name)}")
            lines.append(f"# TYPE {full} counter")
            lines.append(f"{full} {_fmt(value)}")
        for name, fam in sorted(labeled_counters.items()):
            full = f"dtt_{name}"
            lines.append(f"# HELP {full} {self._HELP.get(name, name)}")
            lines.append(f"# TYPE {full} counter")
            for labels, value in sorted(fam.items()):
                lines.append(f"{full}{{{labels}}} {_fmt(value)}")
        for name, fam in sorted(hists.items()):
            full = f"dtt_{name}"
            bounds = HIST_BUCKETS[name]
            lines.append(f"# HELP {full} {self._HELP.get(name, name)}")
            lines.append(f"# TYPE {full} histogram")
            for tenant, st in sorted(fam.items()):
                lbl = f'tenant="{tenant}"'
                for b, c in zip(bounds, st["counts"]):
                    lines.append(
                        f'{full}_bucket{{{lbl},le="{_fmt(b)}"}} {c}')
                lines.append(
                    f'{full}_bucket{{{lbl},le="+Inf"}} {st["count"]}')
                lines.append(f'{full}_sum{{{lbl}}} '
                             f'{_fmt(st["sum"])}')
                lines.append(f'{full}_count{{{lbl}}} {st["count"]}')
        return "\n".join(lines) + "\n"

    # -- HTTP ----------------------------------------------------------

    def start(self):
        """Bind + serve on a daemon thread. Returns self, or None when
        the bind fails (logged; the run continues unmetered)."""
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?")[0] == "/metrics":
                    body = server.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     PROM_CONTENT_TYPE)
                elif self.path.split("?")[0] == "/healthz":
                    ok, detail = server.health()
                    body = (json.dumps(detail) + "\n").encode()
                    self.send_response(200 if ok else 503)
                    self.send_header("Content-Type",
                                     "application/json")
                else:
                    body = b"not found; try /metrics or /healthz\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                logger.debug("metrics http: " + fmt, *args)

        try:
            self._httpd = http.server.ThreadingHTTPServer(
                (self._host, self._requested_port), Handler)
        except OSError as e:
            logger.warning(
                "metrics endpoint NOT started (port %s): %s — the "
                "run continues without /metrics",
                self._requested_port, e)
            return None
        self.port = self._httpd.server_address[1]
        if self._telemetry is not None:
            self._telemetry.add_observer(self.observe)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="metrics-server", daemon=True)
        self._thread.start()
        logger.info("metrics endpoint on :%d (/metrics, /healthz)",
                    self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))
