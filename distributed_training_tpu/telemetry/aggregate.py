"""Cross-host telemetry aggregation: N per-host streams → one report.

On a multi-host pod every process writes its own event stream
(``<run_dir>/host_<i>/events.jsonl``, train/cli.py), because a central
writer would put a network hop inside the instrumentation path and a
crashed coordinator would take every host's evidence with it. This
module is the offline other half: merge the per-host streams into one
clock-aligned timeline and answer the questions a single stream cannot
— which host a slow step belongs to, how the goodput buckets differ
per host, and who everyone else was waiting for (the per-worker skew
measurement arXiv:2505.12832 argues scaling work is blind without).

Clock alignment: every host's stream carries a ``clock_sync`` record
whose ``t_sync`` was read immediately after a cross-host barrier at
runtime setup (runtime.py), i.e. N readings of the same instant. The
offset of host h is ``t_sync_h - median(t_sync)``; subtracting it puts
all streams on the median host's clock to within collective latency —
enough to order step-level events, not XProf-grade. Streams without a
sync record merge with zero correction.

Straggler attribution reuses ``straggler.flag_stragglers`` — the SAME
rule the runtime detector applies on-pod — so a post-hoc skew report
and a live ``straggler`` event can never disagree about what counts as
a straggler. Per-host goodput reuses ``goodput.goodput_of_stream`` for
the same reason.

Entry point: ``python -m distributed_training_tpu.telemetry <run_dir>``
auto-detects per-host subdirs and renders the merged report
(summarize.py dispatches here).
"""

from __future__ import annotations

import json
import os
import re

import numpy as np

from distributed_training_tpu.telemetry import collectives as collectives_lib
from distributed_training_tpu.telemetry.goodput import goodput_of_stream
from distributed_training_tpu.telemetry.straggler import flag_stragglers
from distributed_training_tpu.telemetry.summarize import (
    _attribution, _attribution_static, _loss_stats, _recovery,
    load_jsonl, render_attribution_lines, render_recovery_lines)

# Bump when the aggregate summary's keys change meaning.
SCHEMA = 1

_HOST_DIR = re.compile(r"host_(\d+)$")


def host_dirs(run_dir: str) -> dict[int, str]:
    """``host_<i>`` subdirs that actually hold an event stream."""
    out: dict[int, str] = {}
    for name in os.listdir(run_dir):
        m = _HOST_DIR.fullmatch(name)
        path = os.path.join(run_dir, name)
        if m and os.path.isfile(os.path.join(path, "events.jsonl")):
            out[int(m.group(1))] = path
    return dict(sorted(out.items()))


def is_multihost_run_dir(run_dir: str) -> bool:
    return bool(host_dirs(run_dir))


def load_host_streams(run_dir: str) -> dict[int, list[dict]]:
    return {h: load_jsonl(os.path.join(d, "events.jsonl"))
            for h, d in host_dirs(run_dir).items()}


def clock_offsets(streams: dict[int, list[dict]]) -> dict[int, float]:
    """Per-host clock offset (seconds AHEAD of the reference clock),
    from each stream's first ``clock_sync`` record. Median host is the
    reference so one host with a wild clock cannot skew everyone."""
    syncs = {
        h: next((e["t_sync"] for e in evs
                 if e.get("kind") == "clock_sync"
                 and isinstance(e.get("t_sync"), (int, float))), None)
        for h, evs in streams.items()}
    known = [v for v in syncs.values() if v is not None]
    if not known:
        return {h: 0.0 for h in streams}
    ref = float(np.median(known))
    return {h: (float(v) - ref if v is not None else 0.0)
            for h, v in syncs.items()}


def merge_streams(streams: dict[int, list[dict]],
                  offsets: dict[int, float] | None = None) -> list[dict]:
    """One clock-aligned timeline, sorted by corrected ``t``. Every
    record carries ``host`` (kept if the sink already stamped it,
    else the stream's directory index)."""
    offsets = offsets if offsets is not None else clock_offsets(streams)
    merged: list[dict] = []
    for h, evs in streams.items():
        off = offsets.get(h, 0.0)
        last_t = 0.0
        for e in evs:
            rec = dict(e)
            rec.setdefault("host", h)
            if isinstance(rec.get("t"), (int, float)):
                rec["t"] = rec["t"] - off
                last_t = rec["t"]
            else:
                # Torn record without a timestamp: anchor it where the
                # stream was, so the sort cannot fling it to t=0.
                rec["t"] = last_t
            merged.append(rec)
    merged.sort(key=lambda r: r["t"])
    return merged


def write_merged(run_dir: str, path: str) -> int:
    """Write the merged, clock-aligned timeline as jsonl; returns the
    record count. (This is a derived artifact of already-emitted
    records, not an emission path — the sink rule does not apply.)"""
    streams = load_host_streams(run_dir)
    merged = merge_streams(streams)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for rec in merged:
            f.write(json.dumps(rec) + "\n")
    return len(merged)


def _span_durs(events: list[dict], name: str) -> list[float]:
    return [e["dur_s"] for e in events
            if e.get("kind") == "span" and e.get("name") == name
            and isinstance(e.get("dur_s"), (int, float))]


def _mean(vals: list[float]) -> float | None:
    return round(float(np.mean(vals)), 6) if vals else None


def skew_report(streams: dict[int, list[dict]]) -> dict:
    """Per-host timing skew from the raw streams (duration-based, so
    clock offsets cannot contaminate it).

    - ``per_host``: mean step / mean+total data_wait / total
      checkpoint seconds per host;
    - ``step_spread``: for every step number timed on >= 2 hosts, the
      max-min duration spread — plus which host was slowest most
      often (``worst_host``), the straggler fingerprint;
    - ``ckpt_barrier_spread_s``: max-min of per-host checkpoint
      seconds. Collective saves make every host wait for the slowest
      participant, so a large spread means the FAST hosts burned that
      time blocked at the barrier.
    """
    per_host: dict[int, dict] = {}
    by_step: dict[int, dict[int, float]] = {}
    for h, evs in streams.items():
        steps = _span_durs(evs, "step")
        waits = _span_durs(evs, "data_wait")
        ckpt = sum(_span_durs(evs, "ckpt_save")
                   + _span_durs(evs, "ckpt_wait")
                   + _span_durs(evs, "ckpt_restore"))
        per_host[h] = {
            "step": _mean(steps),
            "data_wait": _mean(waits),
            "data_wait_total_s": round(sum(waits), 4),
            "checkpoint_total_s": round(ckpt, 4),
            "steps": len(steps),
        }
        for e in evs:
            if (e.get("kind") == "span" and e.get("name") == "step"
                    and isinstance(e.get("step"), int)
                    and isinstance(e.get("dur_s"), (int, float))):
                by_step.setdefault(e["step"], {})[h] = e["dur_s"]
    spreads = []
    slowest_count: dict[int, int] = {}
    worst = None
    for step, durs in sorted(by_step.items()):
        if len(durs) < 2:
            continue
        spread = max(durs.values()) - min(durs.values())
        slow_host = max(durs, key=durs.get)
        slowest_count[slow_host] = slowest_count.get(slow_host, 0) + 1
        spreads.append(spread)
        if worst is None or spread > worst["spread_s"]:
            worst = {"step": step, "spread_s": round(spread, 6),
                     "slowest_host": slow_host}
    ckpts = [d["checkpoint_total_s"] for d in per_host.values()]
    out: dict = {
        "per_host": per_host,
        "steps_compared": len(spreads),
        "ckpt_barrier_spread_s": (round(max(ckpts) - min(ckpts), 4)
                                  if len(ckpts) >= 2 else None),
    }
    if spreads:
        out["step_spread"] = {
            "mean_s": round(float(np.mean(spreads)), 6),
            "max_s": round(float(np.max(spreads)), 6),
            "worst": worst,
            "worst_host": max(slowest_count, key=slowest_count.get),
        }
    return out


def _configured_threshold(run_dir: str) -> float | None:
    """The run's own ``train.straggler_threshold`` from its
    resolved_config.yaml, or None when absent/unreadable. The offline
    pass must judge by the same threshold the runtime detector used —
    a run tuned to 3.0 for heterogeneous input shards must not sprout
    offline verdicts the live detector rejected."""
    try:
        import yaml
        with open(os.path.join(run_dir, "resolved_config.yaml")) as f:
            v = (yaml.safe_load(f) or {}).get(
                "train", {}).get("straggler_threshold")
        return float(v) if isinstance(v, (int, float)) else None
    except Exception:  # noqa: BLE001 — a foreign/partial run dir
        # still gets a report, on the default threshold.
        return None


def aggregate_run(run_dir: str, threshold: float | None = None) -> dict:
    """The merged multi-host summary (JSON-stable; render with
    ``render_multihost``). ``threshold`` defaults to the run's own
    configured ``train.straggler_threshold`` (resolved_config.yaml),
    then 1.5."""
    if threshold is None:
        threshold = _configured_threshold(run_dir)
    if threshold is None:
        threshold = 1.5
    streams = load_host_streams(run_dir)
    offsets = clock_offsets(streams)
    merged = merge_streams(streams, offsets)
    skew = skew_report(streams)
    # Offline straggler pass: same rule as the runtime detector, over
    # whole-run per-host means.
    offline = flag_stragglers(
        {h: {"step": d.get("step"), "data_wait": d.get("data_wait")}
         for h, d in skew["per_host"].items()},
        threshold=threshold)
    # Runtime verdicts: every host computes identical summaries from
    # the same all-gathered table, so the last event seen is THE
    # latest cross-host state.
    runtime_events = [e for e in merged if e.get("kind") == "straggler"]
    # Static collective audit (coordinator-emitted, identical SPMD
    # program on every host).
    coll = next((e for e in merged if e.get("kind") == "collectives"),
                None)
    if coll is not None:
        coll = collectives_lib.summary_of_event(coll)
    postmortems = {}
    for h, d in host_dirs(run_dir).items():
        pm = os.path.join(d, "postmortem")
        if os.path.isdir(pm) and os.listdir(pm):
            postmortems[str(h)] = sorted(os.listdir(pm))
    return {
        "schema": SCHEMA,
        "run_dir": run_dir,
        "multihost": True,
        "hosts": sorted(streams),
        "event_rows": len(merged),
        "clock_offsets_s": {str(h): round(o, 6)
                            for h, o in offsets.items()},
        "loss": _loss_stats(
            load_jsonl(os.path.join(run_dir, "metrics.jsonl"))),
        "goodput_by_host": {str(h): goodput_of_stream(evs)
                            for h, evs in streams.items()},
        "skew": skew,
        "stragglers": {
            "offline": offline,
            "threshold": threshold,
            "runtime_exchanges": len(runtime_events),
            "runtime_last": (runtime_events[-1]
                             if runtime_events else None),
        },
        "collectives": coll,
        # Step-time attribution (coordinator-emitted, telemetry/
        # attribution.py): the measured capture + the static schedule
        # audit. Additive keys — SCHEMA stays 1 (pinned by test).
        "attribution": _attribution(merged),
        "attribution_static": _attribution_static(merged),
        # Recovery/elastic accounting from the COORDINATOR's stream:
        # every host appends its own run_start/resume per incarnation,
        # so segmenting the merged timeline would count one restart N
        # times. Host 0 always exists (process indices refill after an
        # elastic shrink) and tells the one canonical story. Additive
        # key — SCHEMA stays 1 (pinned by test).
        "recovery": _recovery(
            min(streams.items())[1] if streams else []),
        "watchdog_firings": [e for e in merged
                             if e.get("kind") == "watchdog_fired"],
        "postmortems": postmortems,
    }


def render_multihost(summary: dict) -> str:
    """Human-readable merged report (the --json flag skips this)."""
    hosts = summary["hosts"]
    lines = [f"multi-host run: {summary['run_dir']}   "
             f"hosts: {len(hosts)}   "
             f"merged events: {summary['event_rows']}"]
    offs = summary.get("clock_offsets_s") or {}
    if any(offs.values()):
        lines.append("clock offsets vs median host: " + "  ".join(
            f"host{h} {offs[str(h)]:+.3f}s" for h in hosts))
    loss = summary.get("loss")
    if loss:
        lines.append(
            f"loss: {loss['first']:.6g} -> {loss['last']:.6g} "
            f"(min {loss['min']:.6g}) over steps "
            f"{loss['first_step']}..{loss['last_step']}")
    lines.append("goodput by host:")
    for h in hosts:
        gp = (summary.get("goodput_by_host") or {}).get(str(h))
        if not gp:
            lines.append(f"  host {h}: no goodput data")
            continue
        tag = " (reconstructed)" if gp.get("reconstructed") else ""
        buckets = "  ".join(f"{k} {v:.2f}s"
                            for k, v in gp["buckets"].items() if v)
        lines.append(f"  host {h}: {gp['goodput']:.1%} of "
                     f"{gp['wall_s']:.1f}s wall, {gp['steps']} "
                     f"steps{tag}   [{buckets}]")
    skew = summary.get("skew") or {}
    per_host = skew.get("per_host") or {}
    if per_host:
        lines.append("skew (per-host means):")
        for h in hosts:
            d = per_host.get(h, per_host.get(str(h), {}))
            step = d.get("step")
            wait = d.get("data_wait")
            lines.append(
                f"  host {h}: step "
                f"{step * 1e3:.1f}ms" if step is not None else
                f"  host {h}: step -")
            if wait is not None:
                lines[-1] += (f"   data_wait {wait * 1e3:.1f}ms "
                              f"(total {d['data_wait_total_s']:.2f}s)")
            if d.get("checkpoint_total_s"):
                lines[-1] += f"   ckpt {d['checkpoint_total_s']:.2f}s"
        spread = skew.get("step_spread")
        if spread:
            w = spread["worst"]
            lines.append(
                f"  step spread over {skew['steps_compared']} common "
                f"steps: mean {spread['mean_s'] * 1e3:.1f}ms  max "
                f"{spread['max_s'] * 1e3:.1f}ms (step {w['step']}, "
                f"host {w['slowest_host']}); slowest most often: "
                f"host {spread['worst_host']}")
        if skew.get("ckpt_barrier_spread_s"):
            lines.append(f"  checkpoint barrier spread: "
                         f"{skew['ckpt_barrier_spread_s']:.2f}s")
    sv = summary.get("stragglers") or {}
    for v in sv.get("offline") or []:
        lines.append(f"STRAGGLER (offline): {v['text']}")
    last = sv.get("runtime_last")
    if last:
        for text in last.get("persistent", []):
            lines.append(f"STRAGGLER (runtime): {text}")
        if not last.get("persistent"):
            lines.append(
                f"straggler exchanges: {sv['runtime_exchanges']} "
                "(no persistent verdicts)")
    coll = summary.get("collectives")
    if coll:
        lines.extend(collectives_lib.render_lines(coll))
    lines.extend(render_attribution_lines(
        summary.get("attribution"), summary.get("attribution_static")))
    rec = summary.get("recovery")
    if rec:
        lines.extend(render_recovery_lines(rec))
    for w in summary.get("watchdog_firings", []):
        lines.append(f"WATCHDOG FIRED on host {w.get('host', '?')}: "
                     f"{w.get('postmortem')}")
    for h, bundles in (summary.get("postmortems") or {}).items():
        for b in bundles:
            lines.append(f"postmortem bundle: host_{h}/postmortem/{b}")
    return "\n".join(lines)
