"""Offline run doctor: rule-based classification of what went wrong.

    python -m distributed_training_tpu.telemetry <path> --doctor

``<path>`` is either a run dir (events.jsonl, plus host_<i>/ streams
on multi-host runs) or one incident bundle (telemetry/incident.py —
``meta.json`` + ``events_tail.jsonl``, with ``anomaly.json`` /
``attribution.json`` when the recorder had them). The doctor folds
the same derived sections the summarizer computes (attribution,
recovery, goodput, serving SLO ledger) together with the online
detector's ``anomaly`` events and classifies the run into one of:

    serving_engine_crash | preemption_thrash | data_skip_storm |
    straggler | serving_slo_breach | input_bound | exposed_comms |
    compute_bound

Every verdict cites its evidence — the exact anomaly events (value vs
baseline in MADs), the attribution fractions, the recovery table rows
— and the evidence lines are rendered by the SAME functions the
summarizer uses (``render_attribution_lines``,
``render_recovery_lines``), so online and offline verdicts cannot
drift. Rules are ordered: the first matching rule is THE verdict, all
other matches are reported as secondary findings, and
``compute_bound`` is the healthy fallback (nothing pathological
matched, compute dominates by construction).
"""

from __future__ import annotations

import json
import os

SCHEMA = 1

# Priority-ordered rule ids (first match wins the verdict).
RULES = ("serving_engine_crash", "preemption_thrash",
         "data_skip_storm", "straggler", "serving_slo_breach",
         "input_bound", "exposed_comms", "compute_bound")

# Rule thresholds — module constants so tests pin them and the doc
# table in docs/observability.md can cite them.
THRASH_RESTARTS = 3
SKIP_STORM_MIN = 5
SLO_ATTAINED_MIN = 0.95
DATA_WAIT_FRAC = 0.15
HOST_FRAC = 0.40
EXPOSED_COLLECTIVE_FRAC = 0.30


def _anomaly_lines(anoms: list[dict], signal: str,
                   limit: int = 3) -> list[str]:
    """Evidence lines citing the exact online-detector events."""
    rows = [a for a in anoms if a.get("signal") == signal]
    out = []
    for a in rows[:limit]:
        if a.get("detail"):
            out.append(f"  anomaly at step {a.get('step')}: {signal} "
                       f"— {a['detail']}")
        else:
            out.append(
                f"  anomaly at step {a.get('step')}: {signal} "
                f"{a.get('value'):.4g} vs median "
                f"{a.get('median'):.4g} "
                f"({a.get('deviation')} MADs, window "
                f"{a.get('window')})")
    if len(rows) > limit:
        out.append(f"  ... and {len(rows) - limit} more {signal} "
                   f"anomalies")
    return out


def load_target(path: str) -> dict:
    """Resolve ``path`` into {source, events, anomaly, meta}.

    An incident bundle contributes its events tail, its recorded
    anomaly verdict and its cached attribution; a run dir contributes
    the full event stream (host_<i>/ streams concatenated on
    multi-host layouts) and any on-disk bundles' names."""
    from distributed_training_tpu.telemetry.incident import (
        is_incident_bundle)
    from distributed_training_tpu.telemetry.summarize import \
        load_jsonl
    if is_incident_bundle(path):
        meta, anomaly, attribution = {}, None, None
        try:
            with open(os.path.join(path, "meta.json")) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            pass
        for name, slot in (("anomaly.json", "anomaly"),
                           ("attribution.json", "attribution")):
            fp = os.path.join(path, name)
            if os.path.exists(fp):
                try:
                    with open(fp) as f:
                        if slot == "anomaly":
                            anomaly = json.load(f)
                        else:
                            attribution = json.load(f)
                except (OSError, ValueError):
                    pass
        events = load_jsonl(os.path.join(path, "events_tail.jsonl"))
        if attribution is not None and not any(
                e.get("kind") == "attribution" for e in events):
            events.append(attribution)
        return {"source": "bundle", "path": path, "meta": meta,
                "events": events, "anomaly": anomaly, "bundles": []}
    events = load_jsonl(os.path.join(path, "events.jsonl"))
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            sub = os.path.join(path, name, "events.jsonl")
            if name.startswith("host_") and os.path.exists(sub):
                events.extend(load_jsonl(sub))
    bundles = []
    for sub in ("incidents", "postmortem"):
        d = os.path.join(path, sub)
        if os.path.isdir(d):
            bundles += [f"{sub}/{n}" for n in sorted(os.listdir(d))
                        if os.path.isdir(os.path.join(d, n))]
    return {"source": "run_dir", "path": path, "meta": {},
            "events": events, "anomaly": None, "bundles": bundles}


def diagnose(events: list[dict], anomaly: dict | None = None,
             slo: tuple[float, float] | None = None,
             incident: dict | None = None) -> dict:
    """Classify one event stream. Returns the report dict:
    ``verdict`` (a RULES member), ``findings`` (every matched rule,
    verdict first, each with its evidence lines), and the per-signal
    anomaly counts."""
    from distributed_training_tpu.telemetry.summarize import (
        _attribution, _attribution_static, _goodput, _recovery,
        _serving, render_attribution_lines, render_recovery_lines)
    anoms = [e for e in events if e.get("kind") == "anomaly"]
    counts: dict[str, int] = {}
    for a in anoms:
        sig = a.get("signal") or "?"
        counts[sig] = counts.get(sig, 0) + 1
    if anomaly:  # a bundle's recorded verdict extends the tail's view
        for sig, n in (anomaly.get("anomalies_total") or {}).items():
            counts[sig] = max(counts.get(sig, 0), n)
    att = _attribution(events)
    static = _attribution_static(events)
    rec = _recovery(events)
    gp = _goodput(events)
    try:
        srv = _serving(events, slo=slo)
    except Exception:  # noqa: BLE001 — serving conf may be absent in
        # a stripped bundle; the serving rule simply cannot match.
        srv = None
    faults = [str(f) for f in (rec or {}).get("faults_injected", [])]
    att_lines = ["  " + ln for ln in
                 render_attribution_lines(att, static)]

    findings: list[dict] = []

    def add(rule: str, summary: str, evidence: list[str]) -> None:
        findings.append({"rule": rule, "summary": summary,
                         "evidence": evidence})

    # 0. serving engine crash: the engine thread died (or the serving
    # supervisor salvaged/gave up). Matched from the crash events the
    # supervisor/server emit BEFORE writing their bundle — so a
    # bundle's events_tail always carries the evidence — plus the
    # bundle's own meta kind for stripped tails.
    crashes = [e for e in events
               if e.get("kind") == "serving_engine_crash"]
    give_ups = [e for e in events
                if e.get("kind") == "supervisor_give_up"
                and e.get("scope") == "serving"]
    bundle_says_crash = (incident or {}).get("kind") == "engine_crash"
    if crashes or give_ups or bundle_says_crash:
        ev = []
        for c in crashes[-3:]:
            ev.append(
                f"  engine crash (incarnation "
                f"{c.get('incarnation', '?')}, launch "
                f"{c.get('launches', c.get('launch_count', '?'))}): "
                f"{c.get('error', '?')}")
            if c.get("weights_version") is not None:
                ev.append(f"    weights_version "
                          f"{c['weights_version']}, kv_salvaged "
                          f"{c.get('kv_salvaged', 0)}, resubmitted "
                          f"{c.get('resubmitted', 0)}")
        crash_faults = [f for f in faults
                        if f.startswith(("engine_crash",
                                         "swap_corrupt"))]
        if crash_faults:
            ev.append(f"  injected fault(s): "
                      f"{', '.join(crash_faults)}")
        if give_ups:
            ev.append(f"  supervisor GAVE UP after "
                      f"{give_ups[-1].get('incarnations', '?')} "
                      f"incarnation(s)")
        if bundle_says_crash and not crashes:
            ev.append("  bundle meta: kind=engine_crash (events "
                      "tail carries no crash record — stripped "
                      "tail)")
        summary = (f"serving engine crashed "
                   f"{max(len(crashes), 1)} time(s)")
        if give_ups:
            summary += "; supervisor gave up"
        elif crashes:
            summary += "; supervisor restarted it"
        add("serving_engine_crash", summary, ev)

    # 1. preemption thrash: the run spent its life restarting.
    if rec and rec.get("restarts", 0) >= THRASH_RESTARTS:
        lost = sum(i.get("steps_lost") or 0
                   for i in rec["incidents"])
        add("preemption_thrash",
            f"{rec['restarts']} restarts (>= {THRASH_RESTARTS}), "
            f"{lost} step(s) lost across incidents",
            ["  " + ln for ln in render_recovery_lines(rec)])

    # 2. data-skip storm: the corpus is feeding corrupt samples.
    skips = (rec or {}).get("data_skips") or []
    if len(skips) >= SKIP_STORM_MIN:
        srcs = sorted({str(s.get("source")) for s in skips})
        add("data_skip_storm",
            f"{len(skips)} corrupt sample(s) skipped "
            f"(>= {SKIP_STORM_MIN}) from source(s) "
            f"{', '.join(srcs)}",
            ["  " + ln for ln in render_recovery_lines(rec)])

    # 3. straggler: one host is slow, the collective waits for it.
    straggler_ev: list[str] = []
    named = None
    persistent = [txt for e in events if e.get("kind") == "straggler"
                  for txt in (e.get("persistent") or [])]
    if persistent:
        straggler_ev += [f"  {t}" for t in persistent[-3:]]
        named = persistent[-1]
    for ev in (rec or {}).get("eviction_requests", []):
        named = (f"host {ev.get('host')} ({ev.get('ratio')}x median "
                 f"on {ev.get('metric')})")
        straggler_ev.append(
            f"  eviction requested: {named} at step "
            f"{ev.get('step')}")
    slow_faults = [f for f in faults if f.startswith("slow_host")]
    if slow_faults and counts.get("step_time", 0) >= 1:
        hosts = sorted({a.get("host") for a in anoms
                        if a.get("signal") == "step_time"
                        and a.get("host") is not None})
        if named is None:
            named = (f"host {hosts[0]}" if hosts
                     else f"fault {slow_faults[0]}")
        straggler_ev.append(
            f"  injected fault(s) {', '.join(slow_faults)} with "
            f"{counts['step_time']} step_time anomaly(ies)"
            + (f" on host(s) {', '.join(map(str, hosts))}"
               if hosts else ""))
        straggler_ev += _anomaly_lines(anoms, "step_time")
    if named is not None:
        add("straggler", f"slow host stalls the step: {named}",
            straggler_ev)

    # 4. serving SLO breach: requests finished, deadlines didn't.
    slo_rep = ((srv or {}).get("overall") or {}).get("slo") or {}
    attained = slo_rep.get("attained")
    if isinstance(attained, (int, float)) \
            and attained < SLO_ATTAINED_MIN:
        worst = min(
            ((name, ((t.get("slo") or {}).get("attained", 1.0)))
             for name, t in (srv.get("tenants") or {}).items()),
            key=lambda kv: kv[1], default=(None, None))
        ev = [f"  overall SLO attainment {attained:.1%} "
              f"(< {SLO_ATTAINED_MIN:.0%}) over "
              f"{slo_rep.get('met', 0) + slo_rep.get('missed', 0)} "
              f"finished request(s)"]
        if worst[0] is not None:
            ev.append(f"  worst tenant: {worst[0]} at "
                      f"{worst[1]:.1%} attained")
        ev += _anomaly_lines(anoms, "serving_ttft")
        ev += _anomaly_lines(anoms, "serving_queue_depth")
        add("serving_slo_breach",
            f"SLO attainment {attained:.1%} < "
            f"{SLO_ATTAINED_MIN:.0%}"
            + (f"; worst tenant {worst[0]}" if worst[0] else ""), ev)

    # 5. input-bound: the step waits on the data pipeline.
    dw_frac = None
    if gp and gp.get("wall_s"):
        dw_frac = (gp["buckets"].get("data_wait", 0.0)
                   / gp["wall_s"])
    host_frac = (att or {}).get("host_frac")
    data_faults = [f for f in faults
                   if f.startswith(("data_stall", "source_stall",
                                    "data_error"))]
    input_hit = (counts.get("data_wait", 0) >= 2
                 or (dw_frac is not None and dw_frac > DATA_WAIT_FRAC)
                 or (isinstance(host_frac, (int, float))
                     and host_frac > HOST_FRAC)
                 or (data_faults and counts.get("data_wait", 0) >= 1))
    if input_hit:
        ev = []
        if dw_frac is not None:
            ev.append(f"  goodput: data_wait "
                      f"{gp['buckets'].get('data_wait', 0.0):.3f}s "
                      f"= {dw_frac:.1%} of {gp['wall_s']:.1f}s wall")
        if isinstance(host_frac, (int, float)):
            ev += att_lines
        if data_faults:
            ev.append(f"  injected fault(s): "
                      f"{', '.join(data_faults)}")
        ev += _anomaly_lines(anoms, "data_wait")
        add("input_bound",
            "step time is dominated by waiting on input data"
            + (f" (data_wait {dw_frac:.1%} of wall)"
               if dw_frac is not None else ""), ev)

    # 6. exposed comms: collectives the schedule failed to hide.
    coll_frac = (att or {}).get("collective_frac")
    if isinstance(coll_frac, (int, float)) \
            and coll_frac > EXPOSED_COLLECTIVE_FRAC:
        add("exposed_comms",
            f"exposed collective time is {coll_frac:.1%} of the "
            f"step (> {EXPOSED_COLLECTIVE_FRAC:.0%}); overlap "
            f"{(att or {}).get('overlap_frac', 0):.1%}", att_lines)

    # 7. healthy fallback.
    if not findings:
        ev = list(att_lines)
        if gp and gp.get("wall_s"):
            ev.append(f"  goodput {gp['goodput']:.1%} over "
                      f"{gp['steps']} step(s)")
        if not ev:
            ev.append("  no pathological signal in the stream")
        add("compute_bound",
            "no pathological signal dominates; the run is spending "
            "its wall clock on compute", ev)

    order = {r: i for i, r in enumerate(RULES)}
    findings.sort(key=lambda f: order.get(f["rule"], len(RULES)))
    return {"schema": SCHEMA, "verdict": findings[0]["rule"],
            "findings": findings, "anomalies": counts,
            "event_rows": len(events)}


def diagnose_path(path: str,
                  slo: tuple[float, float] | None = None) -> dict:
    target = load_target(path)
    report = diagnose(target["events"], anomaly=target["anomaly"],
                      slo=slo, incident=target["meta"] or None)
    report["source"] = target["source"]
    report["path"] = path
    if target["meta"]:
        report["incident"] = {
            k: target["meta"].get(k)
            for k in ("kind", "reason", "time_unix")
            if target["meta"].get(k) is not None}
    if target["bundles"]:
        report["bundles"] = target["bundles"]
    return report


def render_doctor(report: dict) -> str:
    lines = [f"doctor: {report.get('path')} "
             f"({report.get('source', 'stream')}, "
             f"{report['event_rows']} event(s))"]
    inc = report.get("incident")
    if inc:
        lines.append(f"  incident bundle: kind={inc.get('kind')} — "
                     f"{inc.get('reason')}")
    lines.append(f"VERDICT: {report['verdict']} — "
                 f"{report['findings'][0]['summary']}")
    lines.extend(report["findings"][0]["evidence"])
    for f in report["findings"][1:]:
        lines.append(f"also matched: {f['rule']} — {f['summary']}")
        lines.extend(f["evidence"])
    if report.get("anomalies"):
        lines.append("anomalies observed: " + ", ".join(
            f"{k} x{v}" for k, v in
            sorted(report["anomalies"].items())))
    for b in report.get("bundles", []):
        lines.append(f"incident bundle on disk: {b}")
    return "\n".join(lines)
