"""XSpace (``.xplane.pb``) access: the one xplane parsing surface.

``jax.profiler`` traces land on disk as XSpace protobufs. Two
consumers used to read them in two different ways — the offline
``benchmarks/analyze_trace.py`` through the ``xprof`` pip package, and
nothing at runtime at all (the trainer could *capture* a trace but
never look inside it). This module is the shared implementation both
ride (the ``plan_memory.py``-over-planner precedent):

- a **stdlib-only wire-format reader** (``parse_xspace`` /
  ``load_xspace``) that decodes the XPlane schema directly from
  protobuf wire bytes — no ``xprof``, no ``tensorflow``, no generated
  protos. The runtime attribution path (telemetry/attribution.py)
  must work inside the trainer on any backend, and the container's
  tensorboard_plugin_profile vintage is protobuf-incompatible anyway;
- ``timeline_lanes`` — the device-op lanes of a trace (``/device:*``
  planes' "XLA Ops" lines when present; the host plane's XLA executor
  lanes as the CPU-platform fallback, where XLA ops run on host
  threadpools), with python-frame and profiler-infrastructure events
  filtered out;
- ``attribution_of_lanes`` — interval arithmetic over those lanes:
  step time decomposed into compute / exposed-collective / host+data,
  plus the **overlap fraction** (share of collective time concurrent
  with compute — comms the schedule actually hid);
- the ``xprof``-backed ``op_rows`` / ``op_category`` (moved verbatim
  from analyze_trace.py) for the per-op self-time view, raising a
  typed ``XplaneError`` with an actionable message when the package
  is missing or incompatible instead of a raw ImportError traceback;
- ``encode_xspace`` — the matching minimal encoder, so tests can
  synthesize device timelines with known intervals and pin the
  attribution arithmetic to exact expected fractions.

Times: XPlane stores a line-level ``timestamp_ns`` plus per-event
``offset_ps``/``duration_ps``. Everything here computes in integer
picoseconds (exact) and converts to seconds only at the report edge.

Proto field numbers (tensorflow/tsl/profiler/protobuf/xplane.proto):
XSpace.planes=1; XPlane.name=2/.lines=3/.event_metadata=4;
XLine.name=2/.display_name=11/.timestamp_ns=3/.events=4;
XEvent.metadata_id=1/.offset_ps=2/.duration_ps=3;
XEventMetadata.id=1/.name=2.
"""

from __future__ import annotations

import glob
import json
import os
from dataclasses import dataclass, field

SCHEMA = 1


class XplaneError(RuntimeError):
    """A trace-tooling failure with its remedy in the message (the
    analyze_trace CLI prints it and exits nonzero; runtime attribution
    degrades to an ``error`` field on the event)."""


# ---------------------------------------------------------------------------
# protobuf wire format (decode + encode) — stdlib only
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes):
    """Yield ``(field_number, wire_type, value)`` triples; varints come
    back as ints, length-delimited fields as bytes, fixed32/64 as raw
    bytes. Unknown wire types abort the message (corrupt input)."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        fn, wt = key >> 3, key & 7
        if fn == 0:
            # Protobuf field numbers start at 1; 0 means the cursor
            # landed in garbage.
            raise XplaneError(
                f"protobuf field number 0 at byte {i} — corrupt or "
                "not an XSpace file?")
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v, i = buf[i:i + 8], i + 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v, i = buf[i:i + ln], i + ln
            if len(v) < ln:
                # Slicing past the end is silent in Python — a
                # truncated file must fail loudly, not decode a
                # partial payload as a shorter message.
                raise XplaneError(
                    f"truncated length-delimited field at byte {i} "
                    f"(need {ln} bytes)")
        elif wt == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise XplaneError(
                f"unsupported protobuf wire type {wt} at byte {i} — "
                "not an XSpace file?")
        yield fn, wt, v


def _enc_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _enc_field(fn: int, payload: bytes) -> bytes:
    """Length-delimited field (wire type 2)."""
    return _enc_varint((fn << 3) | 2) + _enc_varint(len(payload)) \
        + payload


def _enc_varint_field(fn: int, value: int) -> bytes:
    return _enc_varint(fn << 3) + _enc_varint(value)


# ---------------------------------------------------------------------------
# decoded model
# ---------------------------------------------------------------------------


@dataclass
class Event:
    """One timeline event, absolute times in integer picoseconds."""

    name: str
    start_ps: int
    dur_ps: int

    @property
    def end_ps(self) -> int:
        return self.start_ps + self.dur_ps


@dataclass
class Lane:
    """One XLine: a thread / device stream of non-nested events."""

    name: str
    events: list[Event] = field(default_factory=list)


@dataclass
class Plane:
    """One XPlane (a host or a device)."""

    name: str
    lanes: list[Lane] = field(default_factory=list)


def parse_xspace(data: bytes) -> list[Plane]:
    """Decode XSpace wire bytes into planes/lanes/events."""
    planes: list[Plane] = []
    for fn, _wt, v in _fields(data):
        if fn != 1:  # XSpace.planes
            continue
        name = ""
        raw_lines: list[bytes] = []
        emeta: dict[int, str] = {}
        for f2, _w2, v2 in _fields(v):
            if f2 == 2:
                name = v2.decode("utf-8", "replace")
            elif f2 == 3:
                raw_lines.append(v2)
            elif f2 == 4:  # map<int64, XEventMetadata>
                k, meta = None, b""
                for f3, _w3, v3 in _fields(v2):
                    if f3 == 1:
                        k = v3
                    elif f3 == 2:
                        meta = v3
                mname = ""
                for f4, _w4, v4 in _fields(meta):
                    if f4 == 2:
                        mname = v4.decode("utf-8", "replace")
                if k is not None:
                    emeta[k] = mname
        plane = Plane(name=name)
        for raw in raw_lines:
            lname = disp = ""
            ts_ns = 0
            raw_events: list[bytes] = []
            for f3, _w3, v3 in _fields(raw):
                if f3 == 2:
                    lname = v3.decode("utf-8", "replace")
                elif f3 == 11:
                    disp = v3.decode("utf-8", "replace")
                elif f3 == 3:
                    ts_ns = v3
                elif f3 == 4:
                    raw_events.append(v3)
            lane = Lane(name=disp or lname)
            base_ps = ts_ns * 1000
            for raw_e in raw_events:
                mid = off_ps = dur_ps = 0
                for f4, _w4, v4 in _fields(raw_e):
                    if f4 == 1:
                        mid = v4
                    elif f4 == 2:
                        off_ps = v4
                    elif f4 == 3:
                        dur_ps = v4
                lane.events.append(Event(
                    name=emeta.get(mid, ""),
                    start_ps=base_ps + off_ps, dur_ps=dur_ps))
            plane.lanes.append(lane)
        planes.append(plane)
    return planes


def load_xspace(path: str) -> list[Plane]:
    try:
        with open(path, "rb") as f:
            data = f.read()
        return parse_xspace(data)
    except XplaneError:
        raise
    except Exception as e:  # noqa: BLE001 — a truncated/corrupt file
        # misaligns the wire parse into arbitrary exception types
        # (TypeError from a bytes-typed varint field, IndexError off
        # the end, ...); all of them mean one thing to the caller,
        # and the runtime consumer (ProfileCapture) must be able to
        # catch ONE typed error — a raw parse crash propagating into
        # the step loop would violate the attribution contract.
        raise XplaneError(
            f"cannot decode {path} as an XSpace protobuf "
            f"({type(e).__name__}: {e})") from e


def encode_xspace(planes: list[Plane]) -> bytes:
    """Serialize planes back to XSpace wire bytes. Fixture writer for
    tests (synthesized timelines with exact known intervals); the
    output round-trips through ``parse_xspace``. Each lane keeps
    ``timestamp_ns = 0`` — event starts are encoded as absolute
    offsets, which the parser reads back identically."""
    space = bytearray()
    for plane in planes:
        pb = bytearray()
        pb += _enc_field(2, plane.name.encode())
        names: dict[str, int] = {}
        for lane in plane.lanes:
            for ev in lane.events:
                names.setdefault(ev.name, len(names) + 1)
        for name, mid in names.items():
            meta = (_enc_varint_field(1, mid)
                    + _enc_field(2, name.encode()))
            pb += _enc_field(4, _enc_varint_field(1, mid)
                             + _enc_field(2, meta))
        for lane in plane.lanes:
            lb = bytearray()
            lb += _enc_field(2, lane.name.encode())
            lb += _enc_varint_field(3, 0)  # timestamp_ns
            for ev in lane.events:
                eb = (_enc_varint_field(1, names[ev.name])
                      + _enc_varint_field(2, ev.start_ps)
                      + _enc_varint_field(3, ev.dur_ps))
                lb += _enc_field(4, eb)
            pb += _enc_field(3, bytes(lb))
        space += _enc_field(1, bytes(pb))
    return bytes(space)


# ---------------------------------------------------------------------------
# locating traces
# ---------------------------------------------------------------------------


def find_xplane(trace_dir: str) -> str:
    hits = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.xplane.pb"), recursive=True))
    if not hits:
        raise XplaneError(
            f"no .xplane.pb under {trace_dir} — pass the dir given to "
            "jax.profiler.trace / profile_step.py --trace")
    return hits[-1]  # latest session


# ---------------------------------------------------------------------------
# timeline extraction + classification
# ---------------------------------------------------------------------------

# Collective patterns FIRST: they embed 'gather'/'scatter' as
# substrings (see op_category below, same rationale).
COLLECTIVE_PATTERNS = ("all-to-all", "all-reduce", "all-gather",
                       "reduce-scatter", "collective", "permute")

# Profiler / executor scaffolding on host lanes — present in the
# timeline but not op work; counting it as compute would book the
# runtime's own bookkeeping as device-busy time.
_INFRA_PREFIXES = ("ThreadpoolListener", "ThunkExecutor", "TfrtCpu",
                   "PjitFunction", "ParseArguments", "Pjrt", "RunId",
                   "DevicePut", "np.asarray")
# Host events marking "XLA is executing a program here": on the CPU
# platform ops run on host threads — the calling thread (tiny
# programs execute inline, interleaved with python frames) or Eigen
# threadpool lanes — and the only robust way to separate op events
# from python frames and telemetry trace annotations ("step",
# "data_wait" spans are TraceAnnotations too) is containment inside
# one of these executor windows.
_EXEC_WINDOW_PREFIXES = ("TfrtCpuExecutable::Execute",
                         "ThunkExecutor::Execute")


# The repo's own telemetry span names (events.py opens a
# TraceAnnotation per span, so these ARE on the host timeline):
# window markers, never op work — classifying a "step" annotation as
# compute would book the whole step busy.
_TELEMETRY_SPANS = frozenset({
    "step", "compile", "data_wait", "data_assemble", "eval",
    "ckpt_save", "ckpt_restore", "ckpt_wait", "collectives_audit"})


def classify_event(name: str) -> str | None:
    """``"collective"`` / ``"compute"`` for op events, None for
    profiler/executor scaffolding, python frames, and the repo's own
    span annotations."""
    if not name or name.startswith("$") or name in _TELEMETRY_SPANS:
        return None
    for p in _INFRA_PREFIXES:
        if name.startswith(p):
            return None
    low = name.lower()
    for p in COLLECTIVE_PATTERNS:
        if p in low:
            return "collective"
    return "compute"


def _contained_filter(events: list["Event"],
                      windows: list[tuple[int, int]]) -> list["Event"]:
    """Events lying fully inside one of the merged windows."""
    import bisect
    starts = [w[0] for w in windows]
    out = []
    for ev in events:
        i = bisect.bisect_right(starts, ev.start_ps) - 1
        if i >= 0 and ev.end_ps <= windows[i][1]:
            out.append(ev)
    return out


def timeline_events(planes: list[Plane]) -> tuple[list[Event], str,
                                                  int]:
    """The op events attribution should measure: ``(events, source,
    lane_count)`` with ``source`` "device" or "host".

    Device planes win: each contributes its "XLA Ops" line when one
    exists (the per-op device timeline; other lines — "Steps", "XLA
    Modules" — cover the same wall-clock at coarser granularity and
    would double-count), else all its lines. A CPU-platform trace has
    no device planes — XLA ops run on host threads — so the fallback
    takes every ``/host:`` plane event that sits INSIDE an XLA
    executor window (python frames and telemetry span annotations
    either carry the ``$`` frame prefix or contain/straddle the
    window rather than sitting inside it), mirroring analyze_trace's
    Device→Host fallthrough. Hosts without recognizable executor
    windows (a foreign vintage) keep every classifiable event —
    honest best-effort over silence.
    """
    device = [p for p in planes if p.name.startswith("/device:")]
    if device:
        lanes: list[Lane] = []
        for p in device:
            ops = [ln for ln in p.lanes if ln.name == "XLA Ops"]
            lanes.extend(ops if ops else p.lanes)
        return ([ev for ln in lanes for ev in ln.events], "device",
                len(lanes))
    host_lanes = [ln for p in planes
                  if p.name.startswith("/host:") for ln in p.lanes]
    events = [ev for ln in host_lanes for ev in ln.events]
    windows = _union([(ev.start_ps, ev.end_ps) for ev in events
                      if any(ev.name.startswith(p)
                             for p in _EXEC_WINDOW_PREFIXES)])
    if windows:
        events = _contained_filter(events, windows)
    return events, "host", len(host_lanes)


# ---------------------------------------------------------------------------
# interval arithmetic (integer picoseconds, exact)
# ---------------------------------------------------------------------------


def _union(intervals: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Merged, sorted, disjoint intervals."""
    out: list[tuple[int, int]] = []
    for s, e in sorted(i for i in intervals if i[1] > i[0]):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _measure(merged: list[tuple[int, int]]) -> int:
    return sum(e - s for s, e in merged)


def _intersect_measure(a: list[tuple[int, int]],
                       b: list[tuple[int, int]]) -> int:
    """Total overlap between two merged interval lists."""
    i = j = total = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def attribution_of_events(events: list[Event], source: str = "",
                          lanes: int = 0, classify=classify_event,
                          window: tuple[int, int] | None = None
                          ) -> dict:
    """Decompose a captured window into compute / collective / host.

    Definitions (all unions taken ACROSS lanes, so concurrent streams
    are measured once):

    - window   = [earliest op start, latest op end], widened by
      ``window`` when given (the capture's step/data_wait annotation
      extent — without it, host time BEFORE the first op of the
      first captured step would silently fall outside the window and
      an input-bound run would report a near-zero host fraction);
    - compute  = union of compute-op intervals — includes time where a
      collective ran concurrently (that is comms the schedule HID);
    - collective (exposed) = collective-op time NOT under compute —
      the step time communication actually costs;
    - host     = window minus all op time — the device waiting on
      host/data;
    - overlap_frac = (collective ∩ compute) / total collective time —
      the share of comms hidden under compute (0.0 with no
      collectives).

    ``compute_frac + collective_frac + host_frac == 1`` exactly, by
    construction.
    """
    comp: list[tuple[int, int]] = []
    coll: list[tuple[int, int]] = []
    n_events = 0
    for ev in events:
        kind = classify(ev.name)
        if kind is None:
            continue
        n_events += 1
        (coll if kind == "collective" else comp).append(
            (ev.start_ps, ev.end_ps))
    comp_u, coll_u = _union(comp), _union(coll)
    busy_u = _union(comp + coll)
    base = {"schema": SCHEMA, "source": source, "lanes": lanes}
    if not busy_u:
        w = ((window[1] - window[0]) * 1e-12) if window else 0.0
        return {**base, "window_s": round(w, 9), "busy_s": 0.0,
                "compute_s": 0.0, "collective_s": 0.0,
                "overlap_s": 0.0, "compute_frac": 0.0,
                "collective_frac": 0.0, "host_frac": 1.0,
                "overlap_frac": 0.0, "events": 0}
    t0 = busy_u[0][0]
    t1 = busy_u[-1][1]
    if window is not None:
        # Only widen — a marker narrower than the op extent must not
        # clip real op time out of the denominator.
        t0, t1 = min(t0, window[0]), max(t1, window[1])
    window = t1 - t0
    compute_ps = _measure(comp_u)
    coll_total_ps = _measure(coll_u)
    overlap_ps = _intersect_measure(comp_u, coll_u)
    exposed_ps = coll_total_ps - overlap_ps
    busy_ps = _measure(busy_u)
    ps = 1e-12

    def frac(x: int) -> float:
        return round(x / window, 6) if window else 0.0

    return {
        **base,
        "window_s": round(window * ps, 9),
        "busy_s": round(busy_ps * ps, 9),
        "compute_s": round(compute_ps * ps, 9),
        "collective_s": round(coll_total_ps * ps, 9),
        "overlap_s": round(overlap_ps * ps, 9),
        "compute_frac": frac(compute_ps),
        "collective_frac": frac(exposed_ps),
        "host_frac": frac(window - busy_ps),
        "overlap_frac": (round(overlap_ps / coll_total_ps, 6)
                         if coll_total_ps else 0.0),
        "events": n_events,
    }


# The telemetry span names whose TraceAnnotations bound a captured
# step on the host timeline (events.py emits every span as an
# annotation, so they are IN the trace): used to widen the
# attribution window so host/data time before the first device op —
# the input-bound case attribution exists to diagnose — is counted.
WINDOW_MARKERS = frozenset({"step", "data_wait", "compile"})


def annotation_window(planes: list[Plane]) -> tuple[int, int] | None:
    """Extent of the capture's step/data_wait annotations across host
    planes; None when the trace has none (offline fixtures)."""
    t0 = t1 = None
    for p in planes:
        if not p.name.startswith("/host:"):
            continue
        for ln in p.lanes:
            for ev in ln.events:
                if ev.name not in WINDOW_MARKERS:
                    continue
                t0 = ev.start_ps if t0 is None else min(t0,
                                                        ev.start_ps)
                t1 = ev.end_ps if t1 is None else max(t1, ev.end_ps)
    return None if t0 is None else (t0, t1)


def attribution_of_planes(planes: list[Plane]) -> dict:
    """Attribution straight from decoded planes — the composition
    every consumer (runtime capture, analyze_trace --attribution)
    uses, so lane selection and arithmetic cannot drift apart."""
    events, source, lanes = timeline_events(planes)
    return attribution_of_events(events, source=source, lanes=lanes,
                                 window=annotation_window(planes))


# ---------------------------------------------------------------------------
# xprof-backed per-op self-time rows (moved from analyze_trace.py)
# ---------------------------------------------------------------------------


def op_rows(xplane_path: str) -> list[dict]:
    """Per-op self-time rows from the framework_op_stats tool (via the
    standalone ``xprof`` package — the tensorboard_plugin_profile in
    this image is protobuf-incompatible). Raises ``XplaneError`` with
    the remedy when the package is missing or cannot read the trace."""
    try:
        from xprof.convert import raw_to_tool_data
    except ImportError as e:
        raise XplaneError(
            "the per-op self-time view needs the standalone `xprof` "
            f"package, which is not importable here ({e}). Install it "
            "(`pip install xprof`) or use the dependency-free "
            "attribution view (`analyze_trace.py --attribution`, "
            "telemetry/xplane.py), which reads the trace directly."
        ) from e
    try:
        data, _ = raw_to_tool_data.xspace_to_tool_data(
            [xplane_path], "framework_op_stats", {"tqx": "out:json;"})
        tables = json.loads(data)
    except Exception as e:  # noqa: BLE001 — version drift inside
        # xprof/protobuf surfaces as assorted exception types; all
        # mean the same thing to the operator.
        raise XplaneError(
            f"xprof could not convert {xplane_path} "
            f"({type(e).__name__}: {e}) — likely an xprof/protobuf "
            "version mismatch; `pip install -U xprof` or fall back "
            "to `analyze_trace.py --attribution`.") from e
    # First table = the op breakdown (subsequent ones are summaries).
    table = tables[0] if isinstance(tables, list) else tables
    cols = [c["label"] for c in table["cols"]]
    rows = []
    for r in table["rows"]:
        # gviz represents empty cells as nulls in the 'c' array.
        vals = [(c or {}).get("v") for c in r["c"]]
        rows.append(dict(zip(cols, vals)))
    return rows


def op_category(row: dict) -> str:
    """Subsystem label for one op row. Prefers the tool's own Category
    column (lowercased so it can't split one subsystem across two
    rollup lines against fallback labels); the op-name patterns are
    the fallback classifier. Collective patterns come FIRST — they
    embed 'gather'/'scatter' as substrings, and communication being
    misfiled under memory ops would invert the matmul-vs-comms
    conclusion this rollup exists to draw."""
    cat = row.get("Category")
    if cat:
        return str(cat).lower()
    name = str(row.get("Operation Name") or row.get("Operation")
               or "").lower()
    for pat, label in (("all-to-all", "collective"),
                       ("all-reduce", "collective"),
                       ("all-gather", "collective"),
                       ("reduce-scatter", "collective"),
                       ("collective", "collective"),
                       ("permute", "collective"),
                       ("dot", "matmul"), ("conv", "conv"),
                       ("fusion", "fusion"), ("copy", "copy"),
                       ("transpose", "transpose"),
                       ("gather", "gather"), ("scatter", "scatter"),
                       ("custom-call", "custom-call")):
        if pat in name:
            return label
    return "other"
