"""Online anomaly detection over the telemetry event stream.

Every observability surface so far is passive (events.jsonl, the
Prometheus endpoint, serving traces) or operator-triggered (the
``profile_now`` drop file). This module closes the loop: an
``AnomalyDetector`` registered through ``Telemetry.add_observer`` —
the metrics_server precedent, so it is a pure host-side function of
records the sink already emits and adds ZERO device syncs — keeps
rolling median/MAD baselines per signal and emits schema-pinned
``anomaly`` events with the evidence behind each verdict.

Signals (each a field of a record the run already emits):

- ``step_time``   — ``span``/``step`` ``dur_s`` (high side)
- ``data_wait``   — ``span``/``data_wait`` ``dur_s`` (high side)
- ``throughput``  — ``train_metrics`` ``samples_per_sec_per_chip``
  (low side; the entry MetricsLogger already materialized host-side
  at log_every cadence — the loss float it carries is the ONE
  existing sync, never a new one)
- ``loss_nan``    — ``train_metrics`` loss missing/non-finite
  (sanitize_for_json turns NaN into null)
- ``loss_spike``  — ``train_metrics`` loss (high side)
- ``serving_queue_depth`` — engine ``serving`` step records (high)
- ``serving_ttft``        — ``serving_request`` ``ttft_s`` (high)

Median/MAD (median absolute deviation) is the robust pair: one
outlier moves a mean+stddev baseline, but the median of a window
containing one spike is the same window without it. A value is
anomalous when ``|value - median| / mad'`` exceeds ``threshold``,
where ``mad' = max(mad, rel_floor * median, abs_floor)`` — the floor
keeps a near-zero-variance window (synthetic sleeps, idle queues)
from flagging scheduler jitter as a regression.

Closed-loop actions ride on top (telemetry/incident.py): a SUSTAINED
step-time regression (``sustain`` consecutive anomalous steps) arms
an in-run profile capture by dropping the existing ``profile_now``
trigger file — one-shot across supervisor restarts via the
write-before-action ledger discipline — and an ``IncidentRecorder``
observing the same stream snapshots the flight-recorder ring buffer
(``Telemetry.tail()``) into an incident bundle on every anomaly.

Determinism across restart/resume: the detector's whole state is a
pure function of the event stream, so ``replay(restored_events)``
(the CLI feeds the resumed run's existing events.jsonl) rebuilds
baselines, cooldowns and the sustain counter exactly — no side
effects, no emissions — and the live stream continues from there.
"""

from __future__ import annotations

import collections
import logging
import math
import threading

logger = logging.getLogger(__name__)

SCHEMA = 1

# The stable consumer surface of an ``anomaly`` event (the
# attribution.SUMMARY_KEYS discipline: summarize/doctor/metrics_server
# filter through this, so online and offline verdicts cannot drift).
ANOMALY_KEYS = ("schema", "signal", "value", "median", "mad",
                "deviation", "threshold", "step", "window", "host",
                "detail")

# Baseline snapshot event, emitted at low cadence so the live
# /metrics gauges (dtt_anomaly_baseline_*_s) stay fresh even when
# nothing is anomalous.
BASELINE_KEYS = ("schema", "step_time_s", "data_wait_s", "throughput",
                 "samples", "step")

SIGNALS = ("step_time", "data_wait", "throughput", "loss_nan",
           "loss_spike", "serving_queue_depth", "serving_ttft")

# Kinds this module (and its incident consumers) emit: the detector
# must never observe its own output, or one anomaly recurses forever.
_SELF_KINDS = frozenset({"anomaly", "anomaly_baseline", "incident"})

# Wall-clock signals get a 5ms absolute deviation floor: a prefetched
# data_wait baseline sits at microseconds with microsecond MAD, where
# a harmless 30us scheduler blip would read as dozens of "MADs".
# Nothing under 5ms is ever an incident on these signals.
TIME_SIGNALS = frozenset({"step_time", "data_wait", "serving_ttft"})
_TIME_ABS_FLOOR = 0.005


def summary_of_event(rec: dict, keys=ANOMALY_KEYS) -> dict:
    return {k: rec[k] for k in keys if k in rec}


def median_mad(values) -> tuple[float, float]:
    """(median, median-absolute-deviation) of a sequence."""
    vals = sorted(values)
    n = len(vals)
    if not n:
        return 0.0, 0.0
    med = (vals[n // 2] if n % 2
           else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))
    dev = sorted(abs(v - med) for v in vals)
    mad = (dev[n // 2] if n % 2
           else 0.5 * (dev[n // 2 - 1] + dev[n // 2]))
    return med, mad


class _Baseline:
    """Rolling window + robust deviation test for one signal."""

    def __init__(self, window: int, min_samples: int,
                 rel_floor: float = 0.05, abs_floor: float = 1e-6):
        self.values: collections.deque = collections.deque(
            maxlen=window)
        self.min_samples = min_samples
        self.rel_floor = rel_floor
        self.abs_floor = abs_floor
        self.cooldown = 0  # observations until re-fire allowed

    def test(self, value: float, threshold: float,
             low_side: bool = False) -> dict | None:
        """Deviation verdict for ``value`` against the CURRENT window
        (value is appended afterwards, so a spike is judged against
        the window that precedes it). Returns the evidence dict when
        anomalous, else None."""
        out = None
        if len(self.values) >= self.min_samples:
            med, mad = median_mad(self.values)
            floor = max(mad, self.rel_floor * abs(med), self.abs_floor)
            dev = (value - med) / floor
            if low_side:
                dev = -dev
            if dev > threshold:
                out = {"value": value, "median": round(med, 6),
                       "mad": round(mad, 6),
                       "deviation": round(dev, 3),
                       "window": len(self.values)}
        self.values.append(value)
        return out


class AnomalyDetector:
    """Observer-registered online detector (module docstring).

    ``telemetry`` is the sink to emit ``anomaly`` events through
    (``None`` → detect-only, nothing emitted — the replay mode).
    ``run_dir`` enables the auto-profile action (the ``profile_now``
    drop file + its one-shot ledger live there). ``on_sustained`` is
    an optional extra callback for the sustained-regression action.
    Thread-safe: observers run on whatever thread emits the record.
    """

    def __init__(self, telemetry=None, run_dir: str | None = None,
                 window: int = 64, min_samples: int = 16,
                 threshold: float = 8.0, sustain: int = 5,
                 autoprofile: bool = True, baseline_every: int = 50,
                 host: int | None = None, on_sustained=None):
        self._tel = telemetry
        self.run_dir = run_dir
        self.window = int(window)
        self.min_samples = max(2, int(min_samples))
        self.threshold = float(threshold)
        self.sustain = max(1, int(sustain))
        self.autoprofile = autoprofile
        self.baseline_every = max(1, int(baseline_every))
        self.host = host
        self.on_sustained = on_sustained
        # RLock: _fire emits under the lock, and a synchronous
        # observer of that emission (IncidentRecorder) calls straight
        # back into verdict() on the same thread.
        self._lock = threading.RLock()
        self._base: dict[str, _Baseline] = {
            s: _Baseline(self.window, self.min_samples,
                         abs_floor=(_TIME_ABS_FLOOR
                                    if s in TIME_SIGNALS else 1e-6))
            for s in SIGNALS if s != "loss_nan"}
        self._cooldown_n = 8  # observations between re-fires/signal
        self._sustained_steps = 0   # consecutive anomalous step_times
        self._autoprofile_armed = False
        self.anomalies_total: dict[str, int] = {}
        self._last: dict[str, dict] = {}  # latest evidence per signal
        self._step_obs = 0
        self._last_step: int | None = None

    # -- feed ----------------------------------------------------------

    def observe(self, rec: dict) -> None:
        """Telemetry observer: fold one emitted record. Never raises
        past the sink's guard; cheap (sorting a <=window deque)."""
        self._observe(rec, emit=True)

    def replay(self, events: list[dict]) -> int:
        """Rebuild detector state from a restored event stream
        (resume/restart): identical folding, zero emissions, zero
        side effects. Returns the number of records folded."""
        n = 0
        for rec in events:
            if isinstance(rec, dict):
                self._observe(rec, emit=False)
                n += 1
        return n

    def _observe(self, rec: dict, emit: bool) -> None:
        kind = rec.get("kind")
        if kind in _SELF_KINDS:
            return
        with self._lock:
            if kind == "span":
                self._span(rec, emit)
            elif kind == "train_metrics":
                self._train_metrics(rec, emit)
            elif kind == "serving":
                self._num(rec, "serving_queue_depth",
                          rec.get("queue_depth"), emit)
            elif kind == "serving_request":
                self._num(rec, "serving_ttft", rec.get("ttft_s"),
                          emit)

    def _span(self, rec: dict, emit: bool) -> None:
        name, dur = rec.get("name"), rec.get("dur_s")
        if not isinstance(dur, (int, float)):
            return
        if name == "step":
            self._last_step = rec.get("step", self._last_step)
            hit = self._num(rec, "step_time", dur, emit)
            self._sustained_steps = (self._sustained_steps + 1
                                     if hit else 0)
            if self._sustained_steps >= self.sustain:
                self._sustained(rec, emit)
            self._step_obs += 1
            if emit and self._step_obs % self.baseline_every == 0:
                self._emit_baseline(rec)
        elif name == "data_wait":
            self._num(rec, "data_wait", dur, emit)

    def _train_metrics(self, rec: dict, emit: bool) -> None:
        loss = rec.get("loss")
        if not isinstance(loss, (int, float)) \
                or not math.isfinite(loss):
            # sanitize_for_json turned NaN/inf into null upstream.
            self._fire(rec, "loss_nan",
                       {"value": None, "detail": "non-finite loss"},
                       emit)
            return
        self._num(rec, "loss_spike", float(loss), emit)
        if not rec.get("warmup"):
            self._num(rec, "throughput",
                      rec.get("samples_per_sec_per_chip"), emit,
                      low_side=True)

    def _num(self, rec: dict, signal: str, value, emit: bool,
             low_side: bool = False) -> bool:
        if not isinstance(value, (int, float)):
            return False
        base = self._base[signal]
        evidence = base.test(float(value), self.threshold,
                             low_side=low_side)
        if base.cooldown > 0:
            base.cooldown -= 1
        if evidence is None:
            return False
        if base.cooldown > 0:
            return True  # anomalous, but recently reported
        base.cooldown = self._cooldown_n
        self._fire(rec, signal, evidence, emit)
        return True

    # -- actions -------------------------------------------------------

    def _fire(self, rec: dict, signal: str, evidence: dict,
              emit: bool) -> None:
        self.anomalies_total[signal] = \
            self.anomalies_total.get(signal, 0) + 1
        payload = {"schema": SCHEMA, "signal": signal,
                   "threshold": self.threshold,
                   "step": rec.get("step", self._last_step),
                   **evidence}
        if self.host is not None:
            payload.setdefault("host", self.host)
        self._last[signal] = payload
        if emit and self._tel is not None:
            self._tel.event("anomaly", **payload)

    def _sustained(self, rec: dict, emit: bool) -> None:
        """``sustain`` consecutive anomalous step times: arm the
        in-run profile capture via the existing drop-file trigger,
        one-shot across restarts (write-before-action ledger)."""
        self._sustained_steps = 0
        if self._autoprofile_armed:
            return
        self._autoprofile_armed = True
        if not emit:
            return  # replay: the pre-restart run already acted
        if self.on_sustained is not None:
            try:
                self.on_sustained(dict(self._last.get("step_time")
                                       or {}))
            except Exception as e:  # noqa: BLE001 — action must not
                # take down the emission path (observer discipline).
                logger.debug("on_sustained callback failed: %s: %s",
                             type(e).__name__, e)
        if self.autoprofile and self.run_dir:
            from distributed_training_tpu.telemetry.incident import (
                arm_autoprofile)
            armed = arm_autoprofile(
                self.run_dir, key="step_time_sustained",
                evidence=self._last.get("step_time"))
            if armed and self._tel is not None:
                self._tel.event(
                    "anomaly", schema=SCHEMA, signal="step_time",
                    step=rec.get("step", self._last_step),
                    detail="sustained regression: profile capture "
                           "armed (profile_now)",
                    **{k: v for k, v in
                       (self._last.get("step_time") or {}).items()
                       if k in ("value", "median", "mad",
                                "deviation", "window")})

    # -- snapshots -----------------------------------------------------

    def _emit_baseline(self, rec: dict) -> None:
        snap = self.baselines()
        if self._tel is not None:
            self._tel.event(
                "anomaly_baseline", schema=SCHEMA,
                step=rec.get("step", self._last_step),
                step_time_s=snap.get("step_time"),
                data_wait_s=snap.get("data_wait"),
                throughput=snap.get("throughput"),
                samples=len(self._base["step_time"].values))

    def baselines(self) -> dict[str, float | None]:
        """Current per-signal baseline medians (None before
        min_samples) — the determinism surface the resume test pins."""
        out: dict[str, float | None] = {}
        for sig, base in self._base.items():
            if len(base.values) >= base.min_samples:
                out[sig] = round(median_mad(base.values)[0], 9)
            else:
                out[sig] = None
        return out

    def state_fingerprint(self) -> dict:
        """Full rebuildable-state snapshot (windows + counters), for
        the restart-determinism test: two detectors fed the same
        stream must produce identical fingerprints."""
        with self._lock:
            return {
                "windows": {s: [round(v, 9) for v in b.values]
                            for s, b in self._base.items()},
                "cooldowns": {s: b.cooldown
                              for s, b in self._base.items()},
                "sustained_steps": self._sustained_steps,
                "autoprofile_armed": self._autoprofile_armed,
                "anomalies_total": dict(self.anomalies_total),
            }

    def verdict(self) -> dict:
        """The online verdict an incident bundle snapshots
        (anomaly.json): totals, latest evidence per signal, and the
        baselines they were judged against."""
        with self._lock:
            return {
                "schema": SCHEMA,
                "anomalies_total": dict(self.anomalies_total),
                "latest": {s: dict(p) for s, p in self._last.items()},
                "baselines": self.baselines(),
                "autoprofile_armed": self._autoprofile_armed,
            }
