"""Step-time attribution: where a training step's wall-clock goes.

Two complementary views, one module, shared schemas:

**Measured** (``attribute_trace_dir`` + ``ProfileCapture``): the
trainer captures a short ``jax.profiler`` trace mid-run — at
configured steps (``train.profile_at``) or on demand (drop a
``profile_now`` file in the run dir) — and immediately decomposes the
captured device timeline (telemetry/xplane.py) into compute /
exposed-collective / host+data fractions plus the **overlap
fraction** (share of collective time concurrent with compute — comms
the schedule actually hid). Emitted as an ``attribution`` event;
rendered by the summarizer next to MFU. Capture is coordinator-gated,
one-shot across supervisor restarts (the resilience/faults.py
write-before-action ledger discipline: the trigger is recorded
*before* the trace starts, so a crash mid-capture cannot re-fire it
every incarnation), and the attribution work happens after the step
span closes — it lands in the ``idle`` goodput bucket, never in
``step``, so captured runs keep an honest goodput story.

**Static** (``hlo_overlap_report``): overlap is a property of the
compiled schedule (SimpleFSDP, arXiv 2411.00284 — comms/compute
overlap comes from compiler passes, not hand scheduling), so it can
be audited from optimized HLO with no chip at all. For every
collective in a scheduled module this measures how much independent
compute the schedule places between the collective's issue point and
its first consumer — for async ``-start``/``-done`` pairs, between
start and done; for sync-form collectives in a scheduled module
(``is_scheduled=true``: textual order IS the schedule), between the
op and the first use of its result. A collective with independent
compute in that gap is one a latency-hiding backend can run under
compute; one consumed immediately is exposed by construction. The
per-module score (fraction of collectives with a nonempty gap) is
ratcheted by the analysis gate against ``OVERLAP_baseline.json``
(analysis/__main__.py), so a plan or model change that destroys
overlap scheduling fails tier-1 without a TPU.

The trainer also emits a one-shot ``attribution_static`` event from
the same compiled HLO its ``collectives`` audit walks, with the
planner roofline's expected comms/compute seconds as denominator
context (parallel/planner.py score provenance, when a plan is
pinned).
"""

from __future__ import annotations

import json
import logging
import os
import re

from distributed_training_tpu.telemetry import xplane

logger = logging.getLogger(__name__)

SCHEMA = 1

# The stable consumer surface of a trainer-emitted ``attribution``
# event (summarize.py / aggregate.py filter through this — the
# collectives.SUMMARY_KEYS discipline, so single-host and multi-host
# reports cannot drift).
SUMMARY_KEYS = ("schema", "step", "steps_captured", "trace_dir",
                "source", "window_s", "compute_frac",
                "collective_frac", "host_frac", "overlap_frac",
                "compute_s", "collective_s", "overlap_s", "error")

# Same for the one-shot ``attribution_static`` event.
# ``xla_overlap_flags``: which plan-derived latency-hiding flags were
# ACTIVE in this process's XLA_FLAGS (parallel/overlap.py) — the
# provenance that makes a static score attributable to its scheduler
# config. Additive; SCHEMA stays 1.
STATIC_SUMMARY_KEYS = ("schema", "step", "scored", "overlapped",
                       "overlap_score", "mean_compute_between",
                       "async_pairs", "expected_comms_s",
                       "expected_compute_s", "sharding_plan",
                       "xla_overlap_flags")


def summary_of_event(rec: dict, keys=SUMMARY_KEYS) -> dict:
    return {k: rec[k] for k in keys if k in rec}


def attribute_trace_dir(trace_dir: str) -> dict:
    """Attribution report for the newest ``.xplane.pb`` under
    ``trace_dir`` (xplane.py arithmetic + provenance fields)."""
    path = xplane.find_xplane(trace_dir)
    rep = xplane.attribution_of_planes(xplane.load_xspace(path))
    rep["xplane"] = path
    return rep


# ---------------------------------------------------------------------------
# in-run capture
# ---------------------------------------------------------------------------

TRIGGER_FILE = "profile_now"


def parse_profile_at(spec: str) -> tuple[int, ...]:
    """``train.profile_at`` grammar: comma-separated global step
    numbers (``"20"`` / ``"20,500"``). The capture begins at that
    step and runs ``train.profile_steps`` steps."""
    steps = []
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if not part.isdigit():
            raise ValueError(
                f"train.profile_at: {part!r} is not a step number "
                "(grammar: comma-separated ints, e.g. '20,500')")
        steps.append(int(part))
    return tuple(sorted(set(steps)))


class ProfileCapture:
    """State machine for in-run trace capture + attribution.

    The trainer calls ``maybe_start(step)`` before dispatching each
    step and ``maybe_stop(step, sync=...)`` after its bookkeeping;
    everything else — trigger evaluation (scheduled steps, the
    drop-a-file trigger), the one-shot restart ledger, trace dir
    naming, the attribution parse — lives here so it is testable
    without a trainer. Failures never propagate: observability must
    not take down the run it observes (the collectives-audit
    discipline); a failed parse returns an event payload with an
    ``error`` field instead.
    """

    def __init__(self, run_dir: str, at_steps=(), n_steps: int = 2,
                 enabled: bool = True):
        self.run_dir = run_dir
        # The config layer yaml-parses `train.profile_at=20` into an
        # int and `=20,500` into a string; accept both plus iterables.
        self.at_steps = (parse_profile_at(str(at_steps))
                         if isinstance(at_steps, (str, int)) else
                         tuple(int(s) for s in at_steps))
        self.n_steps = max(1, int(n_steps))
        self.enabled = enabled
        self.profiles_dir = os.path.join(run_dir, "profiles")
        self.trigger_path = os.path.join(run_dir, TRIGGER_FILE)
        self.ledger_path = os.path.join(self.profiles_dir,
                                        "fired.json")
        self._fired: set[str] = set()
        self._active: dict | None = None
        if enabled and os.path.exists(self.ledger_path):
            try:
                with open(self.ledger_path, encoding="utf-8") as f:
                    self._fired = set(json.load(f))
            except (OSError, ValueError) as e:
                logger.warning("profile ledger unreadable (%s); "
                               "treating all triggers as unfired", e)

    # -- trigger ledger (write-before-action, faults.py discipline) ----

    def _record_fired(self, key: str) -> None:
        self._fired.add(key)
        os.makedirs(self.profiles_dir, exist_ok=True)
        tmp = self.ledger_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(sorted(self._fired), f)
        os.replace(tmp, self.ledger_path)

    def _trigger(self, step: int) -> str | None:
        """The trigger key firing at ``step``, or None. Scheduled
        steps fire at-or-after (a resume may land past the exact
        step) and are one-shot via the ledger; the drop-file trigger
        is one-shot by consumption (re-dropping the file re-arms it,
        which is the point of an on-demand trigger)."""
        due = [s for s in self.at_steps
               if step >= s and f"step_{s}" not in self._fired]
        if due:
            # All overdue triggers are satisfied by THIS capture: a
            # resume landing past several profile_at steps must not
            # run back-to-back redundant captures of the same code
            # region, one per stale entry.
            for s in due[1:]:
                self._fired.add(f"step_{s}")
            return f"step_{due[0]}"
        if os.path.exists(self.trigger_path):
            try:
                os.remove(self.trigger_path)
            except OSError:
                return None  # another host consumed it first
            return f"file_at_{step}"
        return None

    # -- capture lifecycle ---------------------------------------------

    @property
    def active(self) -> bool:
        return self._active is not None

    def maybe_start(self, step: int) -> bool:
        """Start a capture if a trigger fires at ``step`` (the step
        about to be dispatched). Returns whether a trace is now
        recording."""
        if not self.enabled or self._active is not None:
            return False
        key = self._trigger(step)
        if key is None:
            return False
        trace_dir = os.path.join(self.profiles_dir, f"step_{step:06d}")
        try:
            # Ledger BEFORE the trace: a crash mid-capture must not
            # re-fire the trigger every restarted incarnation.
            self._record_fired(key)
            import jax
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
        except Exception:  # noqa: BLE001 — e.g. a trace is already
            # live via train.profile_dir; profiling is best-effort.
            logger.exception("profile capture at step %d failed to "
                             "start; continuing untraced", step)
            return False
        self._active = {"start_step": step, "dir": trace_dir,
                        "remaining": self.n_steps, "trigger": key}
        logger.info("profiling steps %d..%d into %s", step,
                    step + self.n_steps - 1, trace_dir)
        return True

    def maybe_stop(self, step: int, sync=None) -> dict | None:
        """Count down the active capture; when the window completes,
        drain the device (``sync``), stop the trace, attribute it,
        and return the ``attribution`` event payload."""
        if self._active is None:
            return None
        self._active["remaining"] -= 1
        if self._active["remaining"] > 0:
            return None
        active, self._active = self._active, None
        payload = {"schema": SCHEMA, "step": step,
                   "steps_captured": step - active["start_step"] + 1,
                   "trace_dir": os.path.relpath(active["dir"],
                                                self.run_dir),
                   "trigger": active["trigger"]}
        try:
            import jax
            if sync is not None:
                # The traced steps dispatched async; the device work
                # must land in the trace before stop. This drain is
                # after the step span closed — it books to idle, not
                # to the goodput step bucket.
                sync()
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            logger.exception("profile capture failed to stop")
            payload["error"] = f"stop_trace: {type(e).__name__}: {e}"
            return payload
        try:
            payload.update(attribute_trace_dir(active["dir"]))
            payload["schema"] = SCHEMA
        except (xplane.XplaneError, OSError) as e:
            payload["error"] = str(e)
        return payload

    def abort(self) -> None:
        """Stop an in-flight trace without attributing (run ended
        mid-window — preemption, eviction, crash teardown). The
        partial trace stays on disk for offline analysis; the ledger
        already recorded the trigger, so a restart won't re-fire."""
        if self._active is None:
            return
        active, self._active = self._active, None
        try:
            import jax
            jax.profiler.stop_trace()
            logger.warning(
                "run ended mid-capture; partial trace left at %s "
                "(analyze offline: benchmarks/analyze_trace.py "
                "--attribution)", active["dir"])
        except Exception as e:  # noqa: BLE001
            logger.debug("profile capture abort: %s: %s",
                         type(e).__name__, e)


# ---------------------------------------------------------------------------
# static overlap audit of a compiled (scheduled) HLO module
# ---------------------------------------------------------------------------

OVERLAP_SCHEMA = 1

# Opcodes that count as independent COMPUTE between a collective's
# issue point and its consumer — work a latency-hiding scheduler can
# run under the collective. Deliberately excludes data movement
# (copy/bitcast/slice/tuple plumbing): shuffling bytes while a
# collective is in flight does not hide its latency budget the way op
# work does, and including it would let pure-plumbing gaps score as
# overlap.
COMPUTE_OPS = frozenset({
    "fusion", "dot", "convolution", "custom-call", "reduce",
    "reduce-window", "select-and-scatter", "scatter", "sort",
    "cholesky", "triangular-solve", "fft", "rng", "rng-bit-generator",
})

_SYNC_COLLECTIVES = frozenset(
    {"all-reduce", "all-gather", "reduce-scatter",
     "collective-permute", "all-to-all"})
_ASYNC_START = frozenset(f"{k}-start" for k in _SYNC_COLLECTIVES)

# "  %name = TYPE opcode(" — instruction lines inside a computation.
# TYPE is either a single "dt[shape]{layout}" token or a TUPLE —
# possibly of tuples: a combiner-grouped async start over N operands
# prints "((dt[s], dt[s]), (dt[s], dt[s]))". Both carry SPACES, and
# a \S+ type matcher would silently drop exactly the instructions
# the overlap audit exists to score (the collectives.py tuple-type
# lesson, schedule edition); the alternation below accepts one level
# of nesting, the deepest HLO result types go.
_INSTR = re.compile(
    r"^\s+(?:ROOT\s+)?%([\w.\-]+)\s*=\s*"
    r"(?:\((?:[^()]|\([^()]*\))*\)|\S+)\s+([\w\-]+)\(")
# The TPU pipeline's fused reduce-scatter (collectives.py rationale).
_FUSED_RS = re.compile(r"calls=%all-reduce-scatter")


def _uses(line: str, name: str) -> bool:
    """Whether an instruction line consumes ``%name`` (exact operand
    match; ``%ag.1`` must not match ``%ag.10``)."""
    return re.search(r"%" + re.escape(name) + r"(?![\w.\-])",
                     line) is not None


def hlo_overlap_report(text: str) -> dict:
    """Score how much independent compute the schedule places inside
    each collective's latency window (module docstring). Sync-form
    collectives are scored only in scheduled modules
    (``is_scheduled=true``), where textual order is the schedule;
    ``-start``/``-done`` pairs are scored always (hand-written or
    dumped HLO included). Collectives whose consumer is outside the
    scoring window (ROOT results, cross-computation uses) are counted
    but excluded from the score."""
    scheduled = "is_scheduled=true" in text[:2000]
    pairs: list[dict] = []
    unscored = 0
    # Computation-by-computation: each computation's instruction list
    # is its own schedule (collectives.py's block-splitting idiom).
    for block in re.split(r"\n(?=%|ENTRY)", text):
        instrs: list[tuple[str, str, str]] = []  # (name, opcode, line)
        for line in block.splitlines():
            m = _INSTR.match(line)
            if m:
                instrs.append((m.group(1), m.group(2), line))
        # A fused reduce-scatter prints as a fusion, but it is COMMS:
        # it must neither count as independent compute in another
        # collective's gap (two back-to-back fused RS would score
        # each other as overlap) nor be missed as a collective.
        is_coll_fusion = [op == "fusion" and bool(_FUSED_RS.search(ln))
                          for _n, op, ln in instrs]
        for idx, (name, opcode, line) in enumerate(instrs):
            is_async = opcode in _ASYNC_START
            is_sync = (opcode in _SYNC_COLLECTIVES
                       or is_coll_fusion[idx])
            if not is_async and not is_sync:
                continue
            if is_sync and not scheduled:
                unscored += 1
                continue
            kind = opcode[:-6] if is_async else (
                "reduce-scatter" if opcode == "fusion" else opcode)
            # The latency window closes at the matching -done (async)
            # or at the first consumer of the result (sync form).
            end = None
            for j in range(idx + 1, len(instrs)):
                _n2, op2, line2 = instrs[j]
                if is_async:
                    if op2 == f"{kind}-done" and _uses(line2, name):
                        end = j
                        break
                elif _uses(line2, name):
                    end = j
                    break
            if end is None:
                unscored += 1
                continue
            between = sum(
                1 for j in range(idx + 1, end)
                if instrs[j][1] in COMPUTE_OPS
                and not is_coll_fusion[j])
            pairs.append({"kind": kind, "name": name,
                          "compute_between": between,
                          "form": "async" if is_async else
                          "scheduled"})
    scored = len(pairs)
    overlapped = sum(1 for p in pairs if p["compute_between"] > 0)
    return {
        "schema": OVERLAP_SCHEMA,
        "scheduled_module": scheduled,
        "scored": scored,
        "unscored": unscored,
        "async_pairs": sum(1 for p in pairs if p["form"] == "async"),
        "overlapped": overlapped,
        "overlap_score": (round(overlapped / scored, 6)
                          if scored else None),
        "mean_compute_between": (round(
            sum(p["compute_between"] for p in pairs) / scored, 3)
            if scored else None),
        "pairs": pairs,
    }


def overlap_summary(rep: dict) -> dict:
    """The row the analysis gate ratchets and the audit doc embeds —
    everything except the per-pair detail."""
    return {k: rep[k] for k in
            ("schema", "scheduled_module", "scored", "unscored",
             "async_pairs", "overlapped", "overlap_score",
             "mean_compute_between") if k in rep}
