"""Static collective-traffic accounting for a compiled SPMD step.

The sharding design never spells out its communication — XLA's SPMD
partitioner derives psum/all-gather/reduce-scatter/all-to-all from the
sharding annotations on the jitted step. This module walks the
compiled step's optimized HLO text and accounts every collective (op
kind, element type, shape, estimated bytes moved per step) and, when
given the mesh, attributes each one to the mesh axis (or axis combo)
whose replica groups it communicates over — so the summarizer can put
a comms roofline next to MFU and a layout regression shows up as a
diffable number instead of silent extra traffic.

This is the library form of ``benchmarks/audit_collectives.py`` (which
now imports its parser from here); the CLI stays in benchmarks, the
schema here is stable (``schema`` version field) because trainer-emitted
``collectives`` events and the multi-host aggregator both consume it.

Why HLO text and not the jaxpr: under GSPMD there are no collective
primitives in the jaxpr at all — the partitioner inserts them during
compilation, so the compiled artifact is the only truthful source.

Byte accounting: each row's ``bytes`` is the collective's result-tuple
payload on one participant (the '-done' form's output for async HLO) —
an estimate of traffic per step per device, not a link-level model.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import re
import sys
import tempfile
from collections import defaultdict

import numpy as np

# Bump when the report dict's keys change meaning — consumers
# (summarize.py, aggregate.py) check this before rendering.
SCHEMA = 1

# The stable consumer surface of a trainer-emitted ``collectives``
# event (everything except the per-row detail). Single-host and
# multi-host summaries both filter through this, so a SCHEMA bump
# cannot leave the two reports disagreeing about which keys exist.
# ``sharding_plan`` (additive, absent on unplanned runs) is the
# resolved auto-parallelism plan's provenance — name/fingerprint/
# remat/base_strategy from parallel/planner.py.
SUMMARY_KEYS = ("schema", "total_collectives", "bytes_per_step",
                "by_kind", "by_axis", "mesh", "spmd_reshard_warnings",
                "sharding_plan")


def summary_of_event(rec: dict) -> dict:
    """The SUMMARY_KEYS subset of a ``collectives`` event/report."""
    return {k: rec[k] for k in SUMMARY_KEYS if k in rec}


def render_lines(coll: dict) -> list[str]:
    """Human-readable lines for a collectives summary — one headline
    (total MB/step by kind, or the explicit none case) plus one line
    per mesh axis. Shared by the single-run summarizer and the
    multi-host report so the same event never renders two ways."""
    parts = ", ".join(
        f"{k} x{v['count']} {v['bytes'] / 1e6:.2f}MB"
        for k, v in sorted(coll.get("by_kind", {}).items(),
                           key=lambda kv: -kv[1]["bytes"]))
    lines = [
        f"collectives: {coll['bytes_per_step'] / 1e6:.2f} MB/step"
        f" ({parts})" if parts else
        "collectives: none (single-device or fully replicated)"]
    for axis, v in sorted(coll.get("by_axis", {}).items(),
                          key=lambda kv: -kv[1]["bytes"]):
        lines.append(f"  axis {axis:10s} x{v['count']:3d}  "
                     f"{v['bytes'] / 1e6:9.3f} MB")
    if coll.get("spmd_reshard_warnings"):
        lines.append(
            f"  SPMD reshard warnings: {coll['spmd_reshard_warnings']} "
            "(involuntary full rematerialization — see "
            "docs/static-analysis.md)")
    sp = coll.get("sharding_plan")
    if sp:
        lines.append(
            f"  sharding plan: {sp.get('name')}@"
            f"{sp.get('fingerprint')} ({sp.get('base_strategy')}, "
            f"remat={sp.get('remat')})")
    return lines

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4,
                "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                "pred": 1, "s64": 8, "u64": 8}

# One optimized-HLO instruction: "%name = TYPE op(...)" where TYPE is
# either a single "dt[shape]{layout}" or a tuple "(dt[s], dt[s], ...)"
# — tuple results are how XLA emits FUSED collectives (e.g. one
# all-reduce syncing every gradient leaf), so a single-type parser
# silently undercounts exactly the most important instruction.
# Async HLO (the TPU compiler's usual form) splits a collective into a
# '-start'/'-done' pair; counting both would double the count and
# ~triple the bytes (the start's result tuple aliases operand AND
# result buffers). Count sync base forms and async '-done' lines —
# the done's result type is the collective's true output — and let
# '-start' lines fall through unmatched (the base-form alternative
# cannot match them: the char after the op name is '-', not '(').
_OP_LINE = re.compile(
    r"=\s+(.*?)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-done)?\(")
_TYPE = re.compile(r"(\w+)\[([\d,]*)\]")

# A TPU-pipeline fused reduce-scatter: the executed op is one RS
# kernel, but its HLO form is a kCustom fusion whose CALLED computation
# holds an all-reduce + dynamic-slice pair. Count the fusion (output
# shape = the true bytes moved per receiver) and skip the called
# computation's body — otherwise the inner all-reduce is double-counted
# at FULL pre-scatter bytes, which is exactly how the r4 audit misread
# the TPU grad sync as "all-reduce at 2x optimal traffic".
_FUSED_RS_LINE = re.compile(
    r"=\s+(.*?)\s+fusion\([^\n]*kind=kCustom,\s*"
    r"calls=(%all-reduce-scatter[\w.\-]*)")
_RS_COMPUTATION = re.compile(r"^(%all-reduce-scatter[\w.\-]*)\s", re.M)

# replica_groups in either explicit form {{0,1},{2,3}} or the iota
# form [G,S]<=[d0,d1,...]T(p...) (iota over [d...], transpose p,
# reshape to G groups of S).
_GROUPS_EXPLICIT = re.compile(r"replica_groups=\{(\{[\d, \{\}]*\})\}")
_GROUPS_IOTA = re.compile(
    r"replica_groups=\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")


# ---------------------------------------------------------------------------
# SPMD-partitioner diagnostics. XLA's spmd_partitioner.cc reports the
# "Involuntary full rematerialization" cliff (it must fully replicate a
# tensor to move between two shardings — silent extra traffic that
# scales with the tensor, exactly the pod-scale perf cliff ROADMAP item
# 1 gates on) as a C++ log line on the process's stderr FD. It never
# surfaces through any Python API, so the only faithful way to observe
# it is to capture fd 2 around the ``.compile()`` call. Wording differs
# across XLA vintages ("cannot go from sharding X to Y efficiently" vs
# "was not able to go from sharding X to Y without doing a full
# rematerialization"); the regexes below accept both.
# ---------------------------------------------------------------------------

RESHARD_MARKER = "Involuntary full rematerialization"
_RESHARD_SHARDINGS = re.compile(
    r"from sharding \{(.*?)\} to \{(.*?)\}")
_RESHARD_OP = re.compile(
    r"for HLO operation:?\s+%([\w.\-]+)\s*=\s*(\w+)\[([\d,]*)\]")


@contextlib.contextmanager
def capture_stderr_fd():
    """Capture everything written to the stderr FILE DESCRIPTOR (not
    just ``sys.stderr``) for the duration of the block — C++ XLA logs
    bypass the Python-level stream. Yields an object whose ``.text``
    holds the captured bytes after exit. Anything captured is swallowed
    from the real stderr (including unrelated concurrent writers, e.g.
    logging from other threads), so keep the window tight: one compile.
    """
    class _Cap:
        text = ""

    cap = _Cap()
    sys.stderr.flush()
    saved = os.dup(2)
    tmp = tempfile.TemporaryFile(mode="w+b")
    try:
        os.dup2(tmp.fileno(), 2)
        yield cap
    finally:
        sys.stderr.flush()
        os.dup2(saved, 2)
        os.close(saved)
        tmp.seek(0)
        cap.text = tmp.read().decode("utf-8", "replace")
        tmp.close()


def parse_reshard_warnings(stderr_text: str) -> list[dict]:
    """Structured rows for every involuntary-reshard warning in a
    captured compile stderr: op name/dtype/shape plus the source and
    destination shardings the partitioner could not bridge. Fields
    the vintage's wording omits come back empty rather than missing."""
    rows: list[dict] = []
    for line in stderr_text.splitlines():
        if RESHARD_MARKER not in line:
            continue
        row = {"op": "", "dtype": "", "shape": "",
               "from_sharding": "", "to_sharding": "",
               "raw": line.strip()[:2000]}
        m = _RESHARD_SHARDINGS.search(line)
        if m:
            row["from_sharding"], row["to_sharding"] = m.groups()
        m = _RESHARD_OP.search(line)
        if m:
            # Strip SSA numeric suffixes (%gather.123 → gather) so the
            # fingerprint survives unrelated HLO renumbering.
            row["op"] = re.sub(r"[.\d]+$", "", m.group(1))
            row["dtype"], row["shape"] = m.group(2), m.group(3)
        rows.append(row)
    return rows


def _bytes_of(dtype: str, shape: str) -> int:
    n = 1
    for d in filter(None, shape.split(",")):
        n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def _strip_fused_rs_bodies(text: str, names: set[str]) -> str:
    """Remove the bodies of the NAMED %all-reduce-scatter called
    computations so their inner all-reduce/dynamic-slice never reach
    the parser. Only computations whose calling fusion was actually
    COUNTED are stripped — a name-based strip with an uncounted caller
    would make the grad-sync collective vanish from the report
    entirely (and the zero-collective contract tests pass vacuously)."""
    out = []
    for block in re.split(r"\n(?=%|ENTRY)", text):
        m = _RS_COMPUTATION.match(block)
        if m and m.group(1) in names:
            continue
        out.append(block)
    return "\n".join(out)


def parse_replica_groups(text: str) -> list[tuple[int, ...]] | None:
    """Parse an instruction's ``replica_groups=`` annotation (either
    form) into a list of participant-id tuples; None when absent."""
    m = _GROUPS_EXPLICIT.search(text)
    if m:
        groups = []
        for part in re.findall(r"\{([\d, ]*)\}", m.group(1)):
            ids = [int(x) for x in part.replace(" ", "").split(",")
                   if x]
            if ids:
                groups.append(tuple(ids))
        return groups or None
    m = _GROUPS_IOTA.search(text)
    if m:
        out_dims = [int(x) for x in m.group(1).split(",")]
        in_dims = [int(x) for x in m.group(2).split(",")]
        arr = np.arange(int(np.prod(in_dims))).reshape(in_dims)
        if m.group(3):
            arr = arr.transpose([int(x) for x in m.group(3).split(",")])
        arr = arr.reshape(out_dims[0], -1)
        return [tuple(int(x) for x in row) for row in arr]
    return None


def mesh_axis_groupings(mesh) -> list[tuple[str, frozenset]]:
    """Every way the partitioner can group this mesh's devices along
    axis combinations: ``[(label, {frozenset(ids), ...}), ...]`` for
    each non-empty combination of non-trivial axes.

    Participant ids in HLO replica groups are device numbers in the
    program's device assignment; depending on pipeline and mode they
    can be either positions in the mesh's flattened device order or
    PjRT device ids — on the standard identity layouts the two agree,
    and where they differ we emit BOTH groupings so either matches.
    """
    shape = mesh.devices.shape
    names = list(mesh.axis_names)
    axes = [i for i, s in enumerate(shape) if s > 1]
    by_pos = np.arange(mesh.devices.size).reshape(shape)
    by_id = np.vectorize(lambda d: d.id)(mesh.devices).reshape(shape)
    out: list[tuple[str, frozenset]] = []
    for r in range(1, len(axes) + 1):
        for combo in itertools.combinations(axes, r):
            label = "+".join(names[i] for i in combo)
            for ids in (by_pos, by_id):
                moved = np.moveaxis(
                    ids, combo, range(ids.ndim - len(combo), ids.ndim))
                group_sz = int(np.prod([shape[i] for i in combo]))
                grouped = moved.reshape(-1, group_sz)
                key = frozenset(frozenset(int(x) for x in row)
                                for row in grouped)
                out.append((label, key))
    return out


def _axes_label(groups: list[tuple[int, ...]] | None,
                groupings: list[tuple[str, frozenset]]) -> str:
    if groups is None:
        return "unknown"
    if all(len(g) <= 1 for g in groups):
        return "self"  # degenerate: no cross-device traffic
    key = frozenset(frozenset(g) for g in groups)
    for label, candidate in groupings:
        if key == candidate:
            return label
    return "unknown"


def audit_hlo_text(text: str, mesh=None) -> dict:
    """Parse optimized HLO text → per-collective counts and bytes.

    With ``mesh``, each row additionally carries ``axes`` (the mesh
    axis combination its replica groups communicate over) and the
    report gains a ``by_axis`` rollup. The stable consumer surface:
    ``schema``, ``total_collectives``, ``bytes_per_step``, ``by_kind``
    (kind → {count, bytes}), ``by_axis`` (mesh only), ``rows``.
    """
    groupings = mesh_axis_groupings(mesh) if mesh is not None else None
    rows = []
    counted_rs: set[str] = set()
    # Bodies of called computations, for fused-RS axis attribution:
    # the replica_groups live on the INNER all-reduce, which the strip
    # below removes before the main scan.
    blocks = {m.group(1): b
              for b in re.split(r"\n(?=%|ENTRY)", text)
              for m in [_RS_COMPUTATION.match(b)] if m}
    for m in _FUSED_RS_LINE.finditer(text):
        parts = _TYPE.findall(m.group(1))
        if not parts:
            continue
        total = sum(_bytes_of(dt, sh) for dt, sh in parts)
        big_dt, big_sh = max(parts, key=lambda p: _bytes_of(p[0], p[1]))
        row = {"kind": "reduce-scatter", "dtype": big_dt,
               "shape": big_sh or "scalar",
               "tuple_arity": len(parts), "bytes": total,
               "fused": True}
        if groupings is not None:
            row["axes"] = _axes_label(
                parse_replica_groups(blocks.get(m.group(2), "")),
                groupings)
        rows.append(row)
        counted_rs.add(m.group(2))
    text = _strip_fused_rs_bodies(text, counted_rs)
    for line in text.splitlines():
        m = _OP_LINE.search(line)
        if not m:
            continue
        types, kind = m.group(1), m.group(2)
        parts = _TYPE.findall(types)
        if not parts:
            continue
        total = sum(_bytes_of(dt, sh) for dt, sh in parts)
        big_dt, big_sh = max(
            parts, key=lambda p: _bytes_of(p[0], p[1]))
        row = {"kind": kind, "dtype": big_dt,
               "shape": big_sh or "scalar",
               "tuple_arity": len(parts),
               "bytes": total}
        if groupings is not None:
            row["axes"] = _axes_label(parse_replica_groups(line),
                                      groupings)
        rows.append(row)
    by_kind: dict = defaultdict(lambda: {"count": 0, "bytes": 0})
    by_axis: dict = defaultdict(lambda: {"count": 0, "bytes": 0})
    for r in rows:
        by_kind[r["kind"]]["count"] += 1
        by_kind[r["kind"]]["bytes"] += r["bytes"]
        if "axes" in r:
            by_axis[r["axes"]]["count"] += 1
            by_axis[r["axes"]]["bytes"] += r["bytes"]
    rep = {
        "schema": SCHEMA,
        "total_collectives": len(rows),
        "bytes_per_step": sum(r["bytes"] for r in rows),
        "by_kind": dict(by_kind),
        "largest": sorted(rows, key=lambda r: -r["bytes"])[:10],
        # Full row list: contract tests must scan EVERY collective —
        # a pathological row ranked 11th would hide from "largest".
        "rows": rows,
    }
    if groupings is not None:
        rep["by_axis"] = dict(by_axis)
    return rep
