"""Goodput ledger: wall-clock decomposition into named buckets.

Headline throughput alone cannot attribute a 0.32-vs-0.44 MFU
regression; the decomposition of time into compute vs. everything else
is what makes a distributed config debuggable (the DDP/FSDP
characterization stance, arxiv 2505.12832). The ledger accumulates
host-side seconds into fixed buckets — ``compile``, ``data_wait``,
``step``, ``checkpoint``, ``eval`` — fed by the telemetry span layer
(events.py feeds depth-0 spans only); anything untracked is ``idle``,
derived as wall minus the tracked sum, so the report always sums to
wall-clock exactly.

Interpretation under async dispatch: ``step`` is host time spent in
(or blocked on) the dispatch path. Once the device queue backs up,
dispatch blocks on device availability, so over any window longer
than a few steps ``step`` tracks device busy time; ``goodput`` =
step / wall is the fraction of wall-clock the accelerator spent on
training steps.
"""

from __future__ import annotations

import time

# Report bucket order (idle appended by report()).
BUCKETS = ("compile", "data_wait", "step", "checkpoint", "eval")

# span name -> bucket. Spans not named here (e.g. the loader's
# data_assemble, which runs concurrently in the prefetch thread and
# would double-count) appear in the event stream only.
SPAN_BUCKET = {
    "compile": "compile",
    "data_wait": "data_wait",
    "step": "step",
    "ckpt_save": "checkpoint",
    "ckpt_restore": "checkpoint",
    "ckpt_wait": "checkpoint",
    "eval": "eval",
}


class GoodputLedger:
    """Accumulates bucket seconds + step counts; reports goodput/MFU.

    ``flops_per_step`` (model FLOPs per optimizer step, all chips) and
    ``peak_flops`` (per chip) turn the window arithmetic into MFU —
    the same accounting as utils/metrics.py but measured against
    *wall* clock, so (goodput x step-window MFU) decomposes a headline
    MFU shortfall into "device was idle" vs "device was slow".
    """

    def __init__(self, flops_per_step: float = 0.0,
                 num_devices: int = 1, peak_flops: float = 0.0):
        self.flops_per_step = flops_per_step
        self.num_devices = max(1, num_devices)
        self.peak_flops = peak_flops
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._buckets = dict.fromkeys(BUCKETS, 0.0)
        self._steps = 0
        self._w_t0 = self._t0
        self._w_buckets = dict.fromkeys(BUCKETS, 0.0)
        self._w_steps = 0

    def add(self, span_name: str, dur_s: float, steps: int = 0) -> None:
        bucket = SPAN_BUCKET.get(span_name)
        if bucket is None:
            return
        self._buckets[bucket] += dur_s
        self._w_buckets[bucket] += dur_s
        if bucket == "step":  # compile steps don't count toward MFU
            self._steps += steps
            self._w_steps += steps

    def _report(self, t0: float, buckets: dict, steps: int) -> dict:
        wall = max(time.perf_counter() - t0, 1e-9)
        tracked = sum(buckets.values())
        rep = {k: round(v, 4) for k, v in buckets.items()}
        rep["idle"] = round(max(wall - tracked, 0.0), 4)
        out = {
            "wall_s": round(wall, 4),
            "buckets": rep,
            "steps": steps,
            "goodput": round(buckets["step"] / wall, 4),
        }
        if self.flops_per_step and self.peak_flops:
            out["mfu_wall"] = round(
                steps * self.flops_per_step
                / (wall * self.num_devices * self.peak_flops), 4)
            step_s = buckets["step"]
            if step_s > 0:
                out["mfu_step"] = round(
                    steps * self.flops_per_step
                    / (step_s * self.num_devices * self.peak_flops), 4)
        return out

    def window_report(self) -> dict:
        """Report since the last window_report (or reset), then start a
        new window — the per-``log_every`` trajectory record."""
        rep = self._report(self._w_t0, self._w_buckets, self._w_steps)
        self._w_t0 = time.perf_counter()
        self._w_buckets = dict.fromkeys(BUCKETS, 0.0)
        self._w_steps = 0
        return rep

    def report(self) -> dict:
        """Cumulative report since reset (the run-level summary)."""
        return self._report(self._t0, self._buckets, self._steps)


def goodput_of_stream(events: list[dict]) -> dict | None:
    """Ledger-style report for one host's raw event records.

    Prefer the trainer's run-scope ledger report; fall back to
    re-aggregating depth-0 spans (a killed run emits no final report,
    but its spans are all on disk). Shared by the single-run
    summarizer and the multi-host aggregator (per-host goodput), so
    the two can never disagree about bucket accounting.
    """
    runs = [e for e in events
            if e.get("kind") == "goodput" and e.get("scope") == "run"]
    if runs:
        return {k: runs[-1][k] for k in
                ("wall_s", "buckets", "steps", "goodput", "mfu_wall",
                 "mfu_step") if k in runs[-1]}
    buckets = dict.fromkeys(BUCKETS, 0.0)
    steps = 0
    # Wall-clock is summed PER run_start segment: the stream may hold
    # several sessions (a resume, or an eval appended hours after a
    # crash — eval.py's fresh=False path), and spanning first-to-last
    # timestamp across sessions would book the dead time between them
    # as idle.
    wall = 0.0
    t_first = t_last = None
    for e in events:
        t = e.get("t")
        if isinstance(t, (int, float)):
            if e.get("kind") == "run_start" and t_first is not None:
                wall += max(t_last - t_first, 0.0)
                t_first = None
            t_first = t if t_first is None else t_first
            t_last = t
        if e.get("kind") != "span" or e.get("depth", 0) != 0:
            continue
        bucket = SPAN_BUCKET.get(e.get("name"))
        if bucket is None or not isinstance(e.get("dur_s"),
                                            (int, float)):
            continue
        buckets[bucket] += e["dur_s"]
        steps += 1 if e.get("name") == "step" else 0
    if t_first is not None:
        wall += max(t_last - t_first, 0.0)
    if wall <= 0:
        return None
    buckets = {k: round(v, 4) for k, v in buckets.items()}
    buckets["idle"] = round(max(wall - sum(buckets.values()), 0.0), 4)
    return {"wall_s": round(wall, 4), "buckets": buckets,
            "steps": steps,
            "goodput": round(buckets["step"] / wall, 4),
            "reconstructed": True}
