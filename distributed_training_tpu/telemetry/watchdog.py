"""Hang watchdog + postmortem bundles.

BENCH_r05 ended as "accelerator backend unresponsive after 3 probes"
with zero artifacts explaining where the hang was. This module makes a
hang produce evidence: a daemon thread is armed before each step and
disarmed after; if a step exceeds the timeout it writes a postmortem
directory — faulthandler stacks of ALL threads (works even when the
main thread is blocked inside an uninterruptible C call, e.g. a wedged
PJRT collective), per-device ``memory_stats()``, and the tail of the
telemetry event stream — before optionally aborting the process.

``write_postmortem`` is also callable directly (bench.py's run
watchdog, probe budget expiry), and ``arm_process_watchdog`` arms a
pure-faulthandler fallback for subprocesses that may be SIGKILLed from
outside (benchmarks/probe_loop.sh): the stack dump is scheduled inside
the interpreter, so it lands on disk before the external ``timeout -k``
fires.

Dump ordering is deliberate: meta + stacks first (pure host-side,
cannot hang), device memory stats last (touches the backend, which is
exactly what may be wedged) — a hang mid-dump still leaves the stacks.
"""

from __future__ import annotations

import atexit
import faulthandler
import itertools
import json
import logging
import os
import sys
import threading
import time

logger = logging.getLogger(__name__)

# Monotonic per-process suffix: two postmortems in the same second
# (e.g. a watchdog firing while a budget timer also fires) must land
# in distinct bundles, not overwrite each other.
_SEQ = itertools.count()


def _device_memory_stats() -> list[dict]:
    """Best-effort per-device ``memory_stats()``. Queries jax only if a
    backend is ALREADY initialized — merely-imported is not enough (this
    package's own __init__ imports jax), and ``jax.devices()`` in a
    jax-idle process would initialize (and claim) a backend from inside
    a postmortem, which is how a dump turns into a second hang."""
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return []
    out = []
    for i, d in enumerate(jax.devices()):
        try:
            stats = d.memory_stats()
        except Exception as e:  # noqa: BLE001 — postmortem best-effort
            out.append({"id": i, "error": f"{type(e).__name__}: {e}"})
            continue
        out.append({"id": i, "kind": d.device_kind,
                    "stats": dict(stats) if stats else None})
    return out


def write_postmortem(base_dir: str, reason: str,
                     events_tail: list | None = None,
                     extra: dict | None = None) -> str:
    """Write one timestamped postmortem bundle; returns its path.

    Since the incident flight recorder landed, a postmortem IS an
    incident bundle (``kind="watchdog"``): this delegates to
    ``telemetry.incident.write_incident_bundle``, so postmortems and
    anomaly/preemption/give-up incidents share one on-disk format
    (meta.json with schema+kind, stacks.txt, events_tail.jsonl,
    memory_stats.json) and the offline ``--doctor`` reads either.
    Never raises — a postmortem writer that can crash its host process
    is worse than no postmortem."""
    from distributed_training_tpu.telemetry.incident import (
        write_incident_bundle)
    return write_incident_bundle(base_dir, reason=reason,
                                 kind="watchdog",
                                 events_tail=events_tail, extra=extra)


class HangWatchdog:
    """Per-step hang detector: ``arm()`` before dispatch, ``disarm()``
    after the step's host work completes. A step that stays armed past
    ``timeout_s`` gets a postmortem bundle under ``postmortem_dir``;
    ``abort=True`` then hard-exits (rc 42) — the mode for unattended
    runs where a hung process holding the accelerator is worse than a
    dead one. Re-arming after a firing resets the trigger, so a run
    that recovers can still document a later hang.
    """

    EXIT_CODE = 42

    def __init__(self, timeout_s: float, postmortem_dir: str,
                 telemetry=None, abort: bool = False,
                 poll_s: float | None = None):
        self.timeout_s = timeout_s
        self.postmortem_dir = postmortem_dir
        self.telemetry = telemetry
        self.abort = abort
        self.fired_path: str | None = None
        self._cond = threading.Condition()
        self._armed_at: float | None = None
        self._timeout_cur = timeout_s
        self._info: dict = {}
        self._context: dict = {}
        self._fired = False
        self._stopped = False
        self._poll = poll_s if poll_s is not None else max(
            0.05, min(1.0, timeout_s / 4))
        self._thread = threading.Thread(
            target=self._loop, name="hang-watchdog", daemon=True)
        self._thread.start()

    def arm(self, timeout_s: float | None = None, **info) -> None:
        """Start the countdown for one step. ``timeout_s`` overrides
        the default for this arm only (the trainer gives the first,
        compile-dominated step a larger allowance)."""
        with self._cond:
            self._armed_at = time.monotonic()
            self._timeout_cur = (timeout_s if timeout_s is not None
                                 else self.timeout_s)
            self._info = info
            self._fired = False
            self._cond.notify()

    def disarm(self) -> None:
        with self._cond:
            self._armed_at = None
            self._cond.notify()

    def set_context(self, ctx: dict) -> None:
        """Replace the persistent context merged into every future
        postmortem (on top of the per-arm info). The trainer feeds the
        straggler detector's latest verdicts through here, so a
        postmortem for a collective hang says "host 3 is 2.1x median
        on data_wait" instead of nothing. Pass {} to clear."""
        with self._cond:
            self._context = dict(ctx)

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify()
        self._thread.join(timeout=5)

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stopped:
                    return
                armed_at, fired = self._armed_at, self._fired
                timeout = self._timeout_cur
                info = {**self._info, **self._context}
                self._cond.wait(self._poll)
            if (armed_at is None or fired
                    or time.monotonic() - armed_at < timeout):
                continue
            with self._cond:
                # Re-check under the lock: the step may have disarmed
                # (or re-armed a NEWER step) while we were deciding.
                if self._armed_at != armed_at or self._fired:
                    continue
                self._fired = True
            self._fire(info, timeout)

    def _fire(self, info: dict, timeout_s: float) -> None:
        tail = self.telemetry.tail() if self.telemetry else None
        self.fired_path = write_postmortem(
            self.postmortem_dir,
            f"step exceeded watchdog timeout {timeout_s}s",
            events_tail=tail,
            extra={"watchdog_timeout_s": timeout_s, **info})
        if self.telemetry is not None:
            self.telemetry.event("watchdog_fired",
                                 postmortem=self.fired_path,
                                 timeout_s=timeout_s, **info)
        if self.abort:
            # Exit-status sentinel FIRST: the restart supervisor
            # classifies this death as watchdog_abort (vs crash) by
            # reading it — rc 42 alone also classifies, but the
            # sentinel carries the postmortem path into the incident
            # log. Best-effort: the abort must fire regardless.
            try:
                from distributed_training_tpu.resilience.supervisor \
                    import WATCHDOG_ABORT, write_exit_status
                write_exit_status(WATCHDOG_ABORT,
                                  postmortem=self.fired_path)
            except Exception as e:  # noqa: BLE001
                logger.debug("watchdog abort sentinel not written: "
                             "%s: %s", type(e).__name__, e)
            # The stacks are on disk; a process wedged in a C call
            # cannot run atexit handlers anyway.
            os._exit(self.EXIT_CODE)


def arm_process_watchdog(timeout_s: float, postmortem_dir: str,
                         reason: str):
    """Faulthandler-only process watchdog for externally-killed
    subprocesses (the probe loop's ``timeout -k`` children): schedules
    an all-thread stack dump into a postmortem bundle at ``timeout_s``.
    Returns ``cancel()`` — call it on success to cancel the dump and
    remove the (then-empty) bundle. ``cancel`` is idempotent and also
    registered atexit, so an error exit that never reaches the success
    path doesn't litter the postmortem dir with empty decoy bundles; a
    bundle whose dump actually FIRED (non-empty stacks) is always
    kept."""
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = os.path.join(
        postmortem_dir, f"{stamp}_pid{os.getpid()}_{next(_SEQ)}")
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({"reason": reason, "armed_at_unix": time.time(),
                   "timeout_s": timeout_s, "pid": os.getpid()}, f,
                  indent=1)
    stacks_path = os.path.join(path, "stacks.txt")
    stacks = open(stacks_path, "w")
    faulthandler.dump_traceback_later(timeout_s, file=stacks)
    done = []

    def cancel() -> None:
        if done:
            return
        done.append(True)
        faulthandler.cancel_dump_traceback_later()
        stacks.close()
        try:
            if os.path.getsize(stacks_path) > 0:
                return  # the dump fired: the bundle is evidence
        except OSError:
            pass
        for name in ("stacks.txt", "meta.json"):
            try:
                os.remove(os.path.join(path, name))
            except OSError:
                pass
        try:
            os.rmdir(path)
        except OSError:
            pass

    atexit.register(cancel)
    return cancel
