"""HBM telemetry: periodic ``device.memory_stats()`` samples.

utils/memory.py predicts the footprint before a run; this records what
the allocator actually did during one, into the same event stream the
goodput ledger and watchdog share — so an OOM (or a near-miss that
degrades scheduling, the measured batch-48 regression in
docs/performance.md) is attributable from the run's own artifacts. The
optional ``estimate_bytes`` (e.g. utils/memory.py's exact
params+grads+opt-state accounting) rides along on every sample as the
cross-check: a large, growing gap between estimate and ``bytes_in_use``
means activations/fragmentation, not state.

CPU backends report no allocator stats (``memory_stats()`` is None);
samples then carry ``"stats": null`` so a run's stream is
schema-stable across platforms.
"""

from __future__ import annotations

# memory_stats keys worth streaming (full dicts carry ~20 noisy
# counters; these are the ones a postmortem actually reads).
_KEYS = ("bytes_in_use", "peak_bytes_in_use", "largest_alloc_size",
         "bytes_limit", "num_allocs")


class HBMSampler:
    """Emit an ``hbm`` event every ``every`` steps (0 disables)."""

    def __init__(self, telemetry, every: int = 0,
                 estimate_bytes: int = 0, devices=None):
        self.telemetry = telemetry
        self.every = every
        self.estimate_bytes = int(estimate_bytes)
        self._devices = devices

    def _device_list(self):
        if self._devices is None:
            import jax
            self._devices = list(jax.local_devices())
        return self._devices

    def maybe_sample(self, step: int) -> None:
        if self.every > 0 and step % self.every == 0:
            self.sample(step)

    def sample(self, step: int) -> None:
        devices = []
        for i, d in enumerate(self._device_list()):
            try:
                raw = d.memory_stats()
            except Exception as e:  # noqa: BLE001 — telemetry must not kill the step loop
                devices.append({"id": i,
                                "error": f"{type(e).__name__}: {e}"})
                continue
            stats = ({k: int(raw[k]) for k in _KEYS if k in raw}
                     if raw else None)
            devices.append({"id": i, "stats": stats})
        rec = {"step": step, "devices": devices}
        if self.estimate_bytes:
            rec["estimate_bytes"] = self.estimate_bytes
        self.telemetry.event("hbm", **rec)
