"""Telemetry: the observability subsystem.

One instrumentation surface, four consumers:

- ``span()``/``event()`` (events.py) — the structured ``events.jsonl``
  stream, doubling as XProf trace annotations;
- ``GoodputLedger`` (goodput.py) — wall-clock decomposed into
  compile/data_wait/step/checkpoint/eval/idle, goodput% + MFU;
- ``HangWatchdog`` (watchdog.py) — per-step hang detection with
  faulthandler/memory-stats/event-tail postmortem bundles;
- ``HBMSampler`` (hbm.py) — periodic ``device.memory_stats()``
  samples cross-checked against utils/memory.py estimates;
- ``StragglerDetector`` (straggler.py) — on-cadence cross-host
  step/data_wait exchange flagging persistently slow hosts;
- ``audit_hlo_text`` (collectives.py) — static collective-traffic
  accounting of a compiled SPMD step (counts + bytes per mesh axis);
- ``ProfileCapture`` (attribution.py) — in-run ``jax.profiler``
  capture at configured steps (or a drop-file trigger) decomposed
  into compute / collective / host+data + overlap %, and the static
  schedule-overlap audit the analysis gate ratchets; trace parsing
  lives in xplane.py (stdlib XSpace reader, shared with
  benchmarks/analyze_trace.py);
- ``MetricsServer`` (metrics_server.py) — the coordinator's live
  Prometheus endpoint + /healthz, fed from this sink (plus the
  tenant-labeled serving latency histograms);
- ``AnomalyDetector`` (anomaly.py) — online median/MAD anomaly
  detection over the same event stream (registered through
  ``add_observer`` like the metrics server: pure host-side, zero
  device syncs), arming an in-run profile capture on sustained
  step-time regressions;
- ``IncidentRecorder``/``write_incident_bundle`` (incident.py) —
  flight-recorder incident bundles (event tail + anomaly verdict +
  latest attribution + serving snapshot) written atomically under
  ``<run_dir>/incidents/``; watchdog postmortems share the format;
- the offline doctor (doctor.py) — rule-based classification of a
  run dir or incident bundle (``--doctor``);
- ``analyze_traces`` (serving_trace.py) — per-tenant SLO ledger
  reconstructed offline from the serving engine's ``serving_trace``
  request-lifecycle records (``--serving-report``);
- the multi-host aggregator (aggregate.py) — merges per-host
  ``host_<i>/events.jsonl`` streams into one clock-aligned report.

``python -m distributed_training_tpu.telemetry <run_dir>`` renders it
all (summarize.py; multi-host run dirs get the merged report). Event
schema and bucket definitions: docs/observability.md.
"""

from distributed_training_tpu.telemetry.anomaly import (  # noqa: F401
    AnomalyDetector,
)
from distributed_training_tpu.telemetry.attribution import (  # noqa: F401
    ProfileCapture,
    hlo_overlap_report,
)
from distributed_training_tpu.telemetry.collectives import (  # noqa: F401
    audit_hlo_text,
)
from distributed_training_tpu.telemetry.events import (  # noqa: F401
    Telemetry,
    current,
    event,
    install,
    span,
    uninstall,
)
from distributed_training_tpu.telemetry.goodput import (  # noqa: F401
    GoodputLedger,
)
from distributed_training_tpu.telemetry.hbm import (  # noqa: F401
    HBMSampler,
)
from distributed_training_tpu.telemetry.incident import (  # noqa: F401
    IncidentRecorder,
    write_incident_bundle,
)
from distributed_training_tpu.telemetry.metrics_server import (  # noqa: F401
    MetricsServer,
)
from distributed_training_tpu.telemetry.serving_trace import (  # noqa: F401
    analyze_traces,
    render_serving_lines,
    slo_attainment,
)
from distributed_training_tpu.telemetry.straggler import (  # noqa: F401
    StragglerDetector,
    flag_stragglers,
)
from distributed_training_tpu.telemetry.watchdog import (  # noqa: F401
    HangWatchdog,
    write_postmortem,
)
