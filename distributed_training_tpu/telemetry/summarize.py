"""Run summarizer: render a run_dir's jsonl streams as one report.

    python -m distributed_training_tpu.telemetry <run_dir> [--json]

Reads ``metrics.jsonl`` (loss/throughput/MFU trajectory, written by
utils/metrics.py) and ``events.jsonl`` (spans, goodput windows, hbm
samples, watchdog firings — written by this package) and prints the
answers a post-run triage actually asks: did the loss move, where did
the wall-clock go, how close to the HBM ceiling did it run, and did
anything hang. Works on partial streams (a crashed run's artifacts are
exactly when this gets used), and lists any ``postmortem/`` bundles it
finds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from distributed_training_tpu.telemetry.goodput import (
    goodput_of_stream)


def load_jsonl(path: str) -> list[dict]:
    """Tolerant jsonl reader: skips torn/corrupt lines (a crashed
    writer's last line is often half-flushed)."""
    rows: list[dict] = []
    if not os.path.exists(path):
        return rows
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                rows.append(rec)
    return rows


def _loss_stats(rows: list[dict]) -> dict | None:
    pts = [(r["step"], r["loss"]) for r in rows
           if isinstance(r.get("loss"), (int, float))
           and isinstance(r.get("step"), int)]
    if not pts:
        return None
    losses = [v for _, v in pts]
    return {"first": losses[0], "last": losses[-1],
            "min": min(losses), "points": len(pts),
            "first_step": pts[0][0], "last_step": pts[-1][0]}


def _trajectory(rows: list[dict], key: str) -> dict | None:
    vals = [r[key] for r in rows
            if isinstance(r.get(key), (int, float))
            and not r.get("warmup")]
    if not vals:
        return None
    return {"first": vals[0], "last": vals[-1], "max": max(vals)}


def _goodput(events: list[dict]) -> dict | None:
    """Run-scope ledger report, or span reconstruction for killed
    runs — shared with the multi-host aggregator (goodput.py)."""
    return goodput_of_stream(events)


def _collectives(events: list[dict]) -> dict | None:
    """Latest static collective-traffic audit (trainer-emitted
    ``collectives`` event, telemetry/collectives.py schema)."""
    rows = [e for e in events if e.get("kind") == "collectives"]
    if not rows:
        return None
    from distributed_training_tpu.telemetry.collectives import (
        summary_of_event)
    return summary_of_event(rows[-1])


def _attribution(events: list[dict]) -> dict | None:
    """Latest in-run step-time attribution (trainer-emitted
    ``attribution`` event, telemetry/attribution.py schema)."""
    rows = [e for e in events if e.get("kind") == "attribution"]
    if not rows:
        return None
    from distributed_training_tpu.telemetry.attribution import (
        summary_of_event)
    return summary_of_event(rows[-1])


def _attribution_static(events: list[dict]) -> dict | None:
    """Latest compiled-schedule overlap audit (``attribution_static``
    event — one-shot after first compile)."""
    rows = [e for e in events
            if e.get("kind") == "attribution_static"]
    if not rows:
        return None
    from distributed_training_tpu.telemetry.attribution import (
        STATIC_SUMMARY_KEYS, summary_of_event)
    return summary_of_event(rows[-1], keys=STATIC_SUMMARY_KEYS)


def render_attribution_lines(att: dict | None,
                             static: dict | None) -> list[str]:
    """Attribution lines — shared by the single-run report and the
    multi-host aggregate so the two renderings cannot drift."""
    lines: list[str] = []
    if att and att.get("error"):
        lines.append(
            f"attribution (step {att.get('step')}): capture failed — "
            f"{att['error']}")
    elif att:
        lines.append(
            f"attribution (step {att.get('step')}, "
            f"{att.get('steps_captured')} step(s), "
            f"{att.get('source')} timeline): "
            f"compute {att.get('compute_frac', 0):.1%} / "
            f"collective {att.get('collective_frac', 0):.1%} / "
            f"host+data {att.get('host_frac', 0):.1%}; "
            f"overlap {att.get('overlap_frac', 0):.1%} of collective "
            f"time hidden")
        if att.get("trace_dir"):
            lines.append(f"  trace: {att['trace_dir']}")
    if static and static.get("scored"):
        line = (
            f"static overlap (compiled schedule): "
            f"{static['overlap_score']:.2f} of {static['scored']} "
            f"collective(s) scheduled with independent compute "
            f"(mean {static.get('mean_compute_between', 0):.1f} "
            f"op(s))")
        if isinstance(static.get("expected_comms_s"), (int, float)):
            line += (f"; roofline expects comms "
                     f"{static['expected_comms_s'] * 1e3:.3f}ms vs "
                     f"compute "
                     f"{static.get('expected_compute_s', 0) * 1e3:.3f}"
                     "ms/step")
        lines.append(line)
    return lines


def _hbm(events: list[dict]) -> dict | None:
    """Per-device high-water marks over all hbm samples."""
    peak: dict[int, int] = {}
    estimate = None
    samples = 0
    for e in events:
        if e.get("kind") != "hbm":
            continue
        samples += 1
        estimate = e.get("estimate_bytes", estimate)
        for d in e.get("devices", []):
            stats = d.get("stats") or {}
            v = stats.get("peak_bytes_in_use",
                          stats.get("bytes_in_use"))
            if isinstance(v, int):
                peak[d.get("id", -1)] = max(
                    peak.get(d.get("id", -1), 0), v)
    if not samples:
        return None
    out: dict = {"samples": samples}
    if peak:
        out["peak_bytes_by_device"] = peak
        out["peak_gib"] = round(max(peak.values()) / 1024 ** 3, 3)
    if estimate:
        out["estimate_bytes"] = estimate
    return out


def _segment_world(seg: dict) -> int | None:
    """World size a segment ran at: the resume event's ``world_size``
    (elastic-aware incarnations) or the segment's ``clock_sync``
    ``process_count`` (every incarnation emits one at setup)."""
    resume = seg.get("resume") or {}
    if isinstance(resume.get("world_size"), int):
        return resume["world_size"]
    if isinstance(seg.get("process_count"), int):
        return seg["process_count"]
    return None


def _recovery(events: list[dict]) -> dict | None:
    """Recovery table (docs/robustness.md): every restart appends a
    new ``run_start`` marker to the same stream, so incidents are the
    segment boundaries — time-to-recover is the gap between a
    segment's last record and the next ``run_start``, and steps lost
    is the crashed segment's high-water step minus the step the next
    incarnation resumed from. Quarantines, injected faults, data
    retries, and elastic world resizes (an incarnation resuming at a
    different world size than its predecessor ran at) ride along.
    None when the run had nothing to recover from (the common case —
    the section stays out of the report)."""
    segments: list[dict] = []
    for e in events:
        t = e.get("t")
        if e.get("kind") == "run_start" or not segments:
            segments.append({"t_start": t, "t_last": t,
                             "start_step": e.get("step"),
                             "max_step": None, "resume": None,
                             "process_count": None})
        seg = segments[-1]
        if isinstance(t, (int, float)):
            seg["t_last"] = max(seg["t_last"] or t, t)
        if e.get("kind") == "resume" and seg["resume"] is None:
            seg["resume"] = e
        if (e.get("kind") == "clock_sync"
                and seg["process_count"] is None):
            seg["process_count"] = e.get("process_count")
        step = e.get("step")
        if isinstance(step, int):
            seg["max_step"] = max(seg["max_step"] or 0, step)
    incidents = []
    for prev, cur in zip(segments, segments[1:]):
        if cur["resume"] is None:
            # A later session appended to the stream without resuming
            # training (e.g. an offline eval, PR2 semantics) is not a
            # recovery incident.
            continue
        resume_step = cur["resume"].get("step", cur["start_step"])
        lost = None
        if (isinstance(prev["max_step"], int)
                and isinstance(resume_step, int)):
            lost = max(0, prev["max_step"] - resume_step)
        gap = None
        if (isinstance(prev["t_last"], (int, float))
                and isinstance(cur["t_start"], (int, float))):
            gap = round(max(0.0, cur["t_start"] - prev["t_last"]), 3)
        incident = {
            "resumed_at_step": resume_step,
            "prev_max_step": prev["max_step"],
            "steps_lost": lost,
            "time_to_recover_s": gap,
            "restarts": (cur["resume"] or {}).get("restarts"),
        }
        # Exactly-once columns (docs/data.md): the resume event
        # carries the restored pipeline cursor; relative to the
        # restored optimizer step, every divergence is either a
        # replay (cursor behind step * global_batch — the optimizer
        # will re-consume samples it already saw) or a skip (cursor
        # ahead). Both must be 0 for a loader whose state rides the
        # checkpoint; the legacy epoch-replay resume shows its replay
        # count here honestly. Additive keys — consumers of the old
        # incident shape are unaffected.
        cursor = cur["resume"].get("samples_consumed")
        gb = cur["resume"].get("global_batch")
        if (isinstance(cursor, int) and isinstance(gb, int)
                and isinstance(resume_step, int)):
            expected = resume_step * gb
            incident["samples_replayed"] = max(0, expected - cursor)
            incident["samples_skipped"] = max(0, cursor - expected)
        realized = cur["resume"].get("realized_mixture")
        target = cur["resume"].get("target_mixture")
        if isinstance(realized, dict) and isinstance(target, dict):
            incident["mixture_drift"] = round(max(
                (abs(float(realized.get(k, 0.0))
                     - float(target.get(k, 0.0)))
                 for k in set(realized) | set(target)),
                default=0.0), 6)
        old_w, new_w = _segment_world(prev), _segment_world(cur)
        if (isinstance(old_w, int) and isinstance(new_w, int)
                and old_w != new_w):
            # An elastic resize: the incarnation re-formed at a
            # different world size (shrink on host loss/eviction,
            # grow-back at a checkpoint boundary).
            incident["old_world"] = old_w
            incident["new_world"] = new_w
            evicted = (cur["resume"] or {}).get("evicted_hosts")
            if evicted:
                incident["evicted_hosts"] = evicted
        incidents.append(incident)
    quarantined = [e for e in events
                   if e.get("kind") == "ckpt_quarantined"]
    faults = [e for e in events if e.get("kind") == "fault_injected"]
    retries = [e for e in events if e.get("kind") == "data_retry"]
    evictions = [e for e in events
                 if e.get("kind") == "eviction_request"]
    # Deliberate skip-and-record corrupt-sample skips (data/stream.py
    # ``data_skip`` events) — distinct from the incident-level
    # samples_skipped column, which measures RESUME skips.
    skips = [e for e in events if e.get("kind") == "data_skip"]
    elastic = [i for i in incidents if "new_world" in i]
    if not incidents and not quarantined and not faults \
            and not retries and not evictions and not skips:
        return None
    return {
        "restarts": len(incidents),
        "incidents": incidents,
        "elastic": elastic,
        "quarantined": [{"step": e.get("step"), "path": e.get("path")}
                        for e in quarantined],
        "faults_injected": [e.get("fault") for e in faults],
        "eviction_requests": [
            {"host": e.get("host"), "step": e.get("step"),
             "metric": e.get("metric"), "ratio": e.get("ratio")}
            for e in evictions],
        "data_retries": len(retries),
        "data_skips": [
            {"source": e.get("source"), "sample_id": e.get("sample_id"),
             "step": e.get("step")} for e in skips],
    }


def _serving(events: list[dict],
             slo: tuple[float, float] | None = None) -> dict | None:
    """Per-tenant serving SLO ledger reconstructed from the
    ``serving_trace`` stream (telemetry/serving_trace.py — the same
    analyzer bench_serving.py ledgers with, so the report and
    SERVING_rNN.json cannot disagree). None when the run served
    nothing."""
    from distributed_training_tpu.telemetry.serving_trace import (
        analyze_traces, slo_deadlines_from_conf)
    ttft_s, per_token_s = slo if slo is not None \
        else slo_deadlines_from_conf()
    return analyze_traces(events, ttft_deadline_s=ttft_s,
                          per_token_deadline_s=per_token_s)


def _spans(events: list[dict]) -> dict:
    agg: dict[str, dict] = {}
    for e in events:
        if e.get("kind") != "span":
            continue
        a = agg.setdefault(e.get("name", "?"),
                           {"count": 0, "total_s": 0.0, "max_s": 0.0})
        dur = e.get("dur_s") or 0.0
        a["count"] += 1
        a["total_s"] = round(a["total_s"] + dur, 4)
        a["max_s"] = round(max(a["max_s"], dur), 4)
    return agg


def summarize_run(run_dir: str) -> dict:
    metrics = load_jsonl(os.path.join(run_dir, "metrics.jsonl"))
    events = load_jsonl(os.path.join(run_dir, "events.jsonl"))
    pm_dir = os.path.join(run_dir, "postmortem")
    postmortems = (sorted(os.listdir(pm_dir))
                   if os.path.isdir(pm_dir) else [])
    summary: dict = {
        "run_dir": run_dir,
        "metrics_rows": len(metrics),
        "event_rows": len(events),
        "loss": _loss_stats(metrics),
        "samples_per_sec_per_chip": _trajectory(
            metrics, "samples_per_sec_per_chip"),
        "mfu": _trajectory(metrics, "mfu"),
        "goodput": _goodput(events),
        "hbm": _hbm(events),
        "collectives": _collectives(events),
        "attribution": _attribution(events),
        "attribution_static": _attribution_static(events),
        "recovery": _recovery(events),
        "serving": _serving(events),
        "spans": _spans(events),
        "watchdog_firings": [e for e in events
                             if e.get("kind") == "watchdog_fired"],
        "postmortems": postmortems,
    }
    return summary


def render_recovery_lines(rec: dict) -> list[str]:
    """Recovery-table lines — shared by the single-host report and the
    multi-host aggregate so the two renderings cannot drift. Elastic
    incidents (world resizes) annotate their incident line with the
    old→new world size; eviction requests get their own lines."""
    skips = rec.get("data_skips") or []
    lines = [
        f"recovery: {rec['restarts']} restart(s), "
        f"{len(rec['quarantined'])} checkpoint(s) quarantined, "
        f"{rec['data_retries']} data retr"
        f"{'y' if rec['data_retries'] == 1 else 'ies'}"
        + (f", {len(rec['elastic'])} elastic resize(s)"
           if rec.get("elastic") else "")
        + (f", {len(skips)} corrupt sample(s) skipped"
           if skips else "")]
    for i, inc in enumerate(rec["incidents"]):
        ttr = inc.get("time_to_recover_s")
        lost = inc.get("steps_lost")
        line = (
            f"  incident {i}: resumed at step "
            f"{inc.get('resumed_at_step')}"
            + (f" ({lost} step(s) lost)" if lost is not None else "")
            + (f", recovered in {ttr:.1f}s" if ttr is not None
               else ""))
        if "samples_replayed" in inc:
            # The exactly-once proof line: a loader whose state rides
            # the checkpoint reports 0 / 0 here.
            line += (f", {inc['samples_replayed']} sample(s) replayed"
                     f" / {inc.get('samples_skipped', 0)} skipped")
        if inc.get("mixture_drift") is not None:
            line += f", mixture drift {inc['mixture_drift']:.4f}"
        if "new_world" in inc:
            line += (f", world {inc.get('old_world')} -> "
                     f"{inc['new_world']}")
            if inc.get("evicted_hosts"):
                line += (" (evicted host(s) "
                         + ",".join(map(str, inc["evicted_hosts"]))
                         + ")")
        lines.append(line)
    for ev in rec.get("eviction_requests", []):
        lines.append(
            f"  EVICTION REQUESTED: host {ev.get('host')} at step "
            f"{ev.get('step')} ({ev.get('ratio')}x median on "
            f"{ev.get('metric')})")
    for q in rec["quarantined"]:
        lines.append(f"  QUARANTINED step {q.get('step')}: "
                     f"{q.get('path')}")
    for s in skips:
        lines.append(
            f"  SKIPPED corrupt sample {s.get('source')}"
            f"[{s.get('sample_id')}] at step {s.get('step')}")
    if rec["faults_injected"]:
        lines.append("  faults injected: "
                     + ", ".join(map(str, rec["faults_injected"])))
    return lines


def render(summary: dict) -> str:
    """Human-readable report (the --json flag skips this)."""
    lines = [f"run: {summary['run_dir']}",
             f"  metrics rows: {summary['metrics_rows']}   "
             f"event rows: {summary['event_rows']}"]
    loss = summary.get("loss")
    if loss:
        lines.append(
            f"loss: {loss['first']:.6g} -> {loss['last']:.6g} "
            f"(min {loss['min']:.6g}) over steps "
            f"{loss['first_step']}..{loss['last_step']}")
    for key, label in (("samples_per_sec_per_chip",
                        "samples/s/chip"), ("mfu", "mfu")):
        t = summary.get(key)
        if t:
            lines.append(f"{label}: first {t['first']:.4g}  "
                         f"last {t['last']:.4g}  max {t['max']:.4g}")
    gp = summary.get("goodput")
    if gp:
        tag = " (reconstructed from spans)" if gp.get(
            "reconstructed") else ""
        lines.append(f"goodput: {gp['goodput']:.1%} of "
                     f"{gp['wall_s']:.1f}s wall, {gp['steps']} "
                     f"steps{tag}")
        width = max(len(k) for k in gp["buckets"])
        for k, v in gp["buckets"].items():
            pct = v / gp["wall_s"] if gp["wall_s"] else 0.0
            lines.append(f"  {k.ljust(width)}  {v:9.3f}s  {pct:6.1%}")
        for k in ("mfu_wall", "mfu_step"):
            if k in gp:
                lines.append(f"  {k}: {gp[k]:.4f}")
    hbm = summary.get("hbm")
    if hbm:
        line = f"hbm: {hbm['samples']} samples"
        if "peak_gib" in hbm:
            line += f", peak {hbm['peak_gib']} GiB"
        if "estimate_bytes" in hbm:
            line += (f" (state estimate "
                     f"{hbm['estimate_bytes'] / 1024 ** 3:.3f} GiB)")
        lines.append(line)
    coll = summary.get("collectives")
    spans = summary.get("spans") or {}
    if coll:
        from distributed_training_tpu.telemetry.collectives import (
            render_lines)
        headline, *axis_lines = render_lines(coll)
        lines.append(headline)
        step_agg = spans.get("step")
        if step_agg and step_agg["count"] and coll["bytes_per_step"]:
            # The comms roofline next to MFU: bytes the step's
            # collectives move divided by measured step time — the
            # interconnect bandwidth the run sustains.
            mean_step = step_agg["total_s"] / step_agg["count"]
            lines.append(
                f"  ~{coll['bytes_per_step'] / mean_step / 1e9:.2f} "
                f"GB/s sustained over {mean_step * 1e3:.1f}ms steps")
        lines.extend(axis_lines)
    # Step-time attribution next to MFU: where the measured step went
    # (compute / exposed collective / host+data, overlap hidden) and
    # what the compiled schedule statically promises.
    lines.extend(render_attribution_lines(
        summary.get("attribution"), summary.get("attribution_static")))
    if spans:
        lines.append("spans (count / total / max):")
        for name in sorted(spans, key=lambda n: -spans[n]["total_s"]):
            a = spans[name]
            lines.append(f"  {name:14s} {a['count']:5d}  "
                         f"{a['total_s']:9.3f}s  {a['max_s']:8.3f}s")
    rec = summary.get("recovery")
    if rec:
        lines.extend(render_recovery_lines(rec))
    srv = summary.get("serving")
    if srv:
        from distributed_training_tpu.telemetry.serving_trace import (
            render_serving_lines)
        lines.extend(render_serving_lines(srv))
    for w in summary.get("watchdog_firings", []):
        lines.append(f"WATCHDOG FIRED: {w.get('postmortem')}")
    for p in summary.get("postmortems", []):
        lines.append(f"postmortem bundle: postmortem/{p}")
    if not summary["metrics_rows"] and not summary["event_rows"]:
        lines.append("no metrics.jsonl / events.jsonl rows found")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_training_tpu.telemetry",
        description="Summarize a run_dir's metrics/events streams "
                    "(multi-host run dirs with host_<i>/ subdirs get "
                    "the merged cross-host report)")
    p.add_argument("run_dir")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as one JSON object")
    p.add_argument("--write-merged", default=None, metavar="PATH",
                   help="multi-host only: also write the merged, "
                        "clock-aligned event timeline as jsonl")
    p.add_argument("--doctor", action="store_true",
                   help="rule-based diagnosis of a run dir OR an "
                        "incident bundle: classify input-bound / "
                        "exposed-comms / compute-bound / straggler / "
                        "data-skip storm / preemption thrash / "
                        "serving SLO breach, citing the exact "
                        "events and attribution fractions")
    p.add_argument("--serving-report", action="store_true",
                   help="print ONLY the serving SLO ledger "
                        "reconstructed from serving_trace records "
                        "(per-tenant p50/p95/p99 TTFT/e2e, SLO "
                        "attainment, preemption retry cost)")
    p.add_argument("--slo-ttft-s", type=float, default=None,
                   help="TTFT deadline for --serving-report "
                        "(default: conf/serving/default.yaml slo:)")
    p.add_argument("--slo-per-token-s", type=float, default=None,
                   help="per-token decode deadline for "
                        "--serving-report (default: conf/serving/"
                        "default.yaml slo:)")
    args = p.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"not a directory: {args.run_dir}", file=sys.stderr)
        return 2
    if args.doctor:
        from distributed_training_tpu.telemetry.doctor import (
            diagnose_path, render_doctor)
        slo = None
        if (args.slo_ttft_s is not None
                and args.slo_per_token_s is not None):
            slo = (args.slo_ttft_s, args.slo_per_token_s)
        report = diagnose_path(args.run_dir, slo=slo)
        if args.json:
            print(json.dumps(report))
        else:
            print(render_doctor(report))
        return 0
    if args.serving_report:
        from distributed_training_tpu.telemetry.serving_trace import (
            render_serving_lines, slo_deadlines_from_conf)
        ttft_s, per_token_s = slo_deadlines_from_conf()
        if args.slo_ttft_s is not None:
            ttft_s = args.slo_ttft_s
        if args.slo_per_token_s is not None:
            per_token_s = args.slo_per_token_s
        # serving_trace records are self-contained (span times are
        # arrival-relative), so multi-host dirs just concatenate —
        # no clock alignment needed.
        events = load_jsonl(os.path.join(args.run_dir,
                                         "events.jsonl"))
        for name in sorted(os.listdir(args.run_dir)):
            sub = os.path.join(args.run_dir, name, "events.jsonl")
            if name.startswith("host_") and os.path.exists(sub):
                events.extend(load_jsonl(sub))
        rep = _serving(events, slo=(ttft_s, per_token_s))
        if rep is None:
            print("no serving_trace records in "
                  f"{args.run_dir}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(rep))
        else:
            print("\n".join(render_serving_lines(rep)))
        return 0
    from distributed_training_tpu.telemetry import aggregate
    if aggregate.is_multihost_run_dir(args.run_dir):
        summary = aggregate.aggregate_run(args.run_dir)
        if args.write_merged:
            aggregate.write_merged(args.run_dir, args.write_merged)
        if args.json:
            print(json.dumps(summary))
        else:
            print(aggregate.render_multihost(summary))
        return 0
    summary = summarize_run(args.run_dir)
    if args.json:
        print(json.dumps(summary))
    else:
        print(render(summary))
    return 0
