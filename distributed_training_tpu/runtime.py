"""Runtime layer: process environment + device mesh.

TPU-native replacement for the reference's ``DistributedEnvironment``
(reference: src/distributed_trainer.py:42-70) and its NCCL/Gloo process-group
bootstrap. Where the reference reads torchrun-injected RANK/LOCAL_RANK/
WORLD_SIZE and calls ``init_process_group`` (src/distributed_trainer.py:48-62),
here multi-host rendezvous is ``jax.distributed.initialize`` (auto-detected on
Cloud TPU pods) and the unit of parallelism is not a process rank but a
``jax.sharding.Mesh`` over all addressable devices, with logical axes:

- ``dp``   pure data parallelism (outermost; rides DCN across slices)
- ``fsdp`` parameter sharding (ZeRO-3 analogue; rides ICI)
- ``tp``   tensor/model parallelism (innermost, highest-bandwidth ICI)
- ``sp``   sequence/context parallelism (ring attention)
- ``pp``   pipeline stages

Collectives are never called imperatively at this layer: XLA emits
psum/all-gather/reduce-scatter/ppermute from sharding annotations on the
jitted train step (the compiled-collective counterpart of NCCL; SURVEY.md
§2.2/§2.4).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import time
from dataclasses import dataclass

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_tpu.config import Config, MeshConfig

logger = logging.getLogger(__name__)

# Canonical mesh axis order, outermost (slowest-varying, DCN-adjacent)
# to innermost (fastest ICI links).
AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_PP = "pp"
MESH_AXES = (AXIS_PP, AXIS_DP, AXIS_FSDP, AXIS_SP, AXIS_TP)

# The batch dimension is sharded over both data-parallel-like axes: FSDP
# shards data as well as params (torch-FSDP semantics, reference
# src/dist_strategy/fsdp_strategy.py), and dp adds pure replication groups.
BATCH_AXES = (AXIS_DP, AXIS_FSDP)


class RuntimeError_(RuntimeError):
    pass


@dataclass(frozen=True)
class MeshSpec:
    """Resolved (all-positive) mesh shape."""

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def total(self) -> int:
        return self.pp * self.dp * self.fsdp * self.sp * self.tp

    def as_dict(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in MESH_AXES}

    @staticmethod
    def resolve(cfg: MeshConfig, num_devices: int) -> "MeshSpec":
        """Fill at most one ``-1`` axis with the remaining device count."""
        sizes = {a: getattr(cfg, a) for a in MESH_AXES}
        bad = [a for a, s in sizes.items() if s != -1 and s < 1]
        if bad:
            raise RuntimeError_(
                f"mesh axis size must be -1 or >= 1; got "
                f"{ {a: sizes[a] for a in bad} }")
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise RuntimeError_(f"at most one mesh axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if num_devices % fixed != 0:
                raise RuntimeError_(
                    f"fixed mesh axes {sizes} (product {fixed}) do not divide "
                    f"device count {num_devices}")
            sizes[wild[0]] = num_devices // fixed
        elif fixed != num_devices:
            raise RuntimeError_(
                f"mesh {sizes} needs {fixed} devices but {num_devices} are "
                f"available")
        return MeshSpec(**sizes)


def build_mesh(spec: MeshSpec, devices: list | None = None) -> Mesh:
    """Build the device mesh.

    Uses ``mesh_utils.create_device_mesh`` so logical axes map onto the
    physical ICI torus sensibly (innermost logical axis → nearest
    neighbours); falls back to a plain reshape for platforms where the
    topology helper is unsupported (CPU fake devices).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    shape = tuple(spec.as_dict()[a] for a in MESH_AXES)
    if math.prod(shape) != len(devices):
        raise RuntimeError_(
            f"mesh shape {shape} != device count {len(devices)}")
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices, allow_split_physical_axes=True)
    except Exception:  # pragma: no cover - topology helper unavailable
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


@dataclass
class Runtime:
    """Everything a training program needs to know about where it runs.

    Interface parity with ``DistributedEnvironment`` (reference:
    src/distributed_trainer.py:42-70): ``process_index`` ↔ global rank,
    ``process_count`` ↔ world size (in units of hosts, as is natural on
    TPU where one process drives all local chips), ``is_coordinator`` ↔
    rank-0 checks used to gate logging/checkpointing.
    """

    mesh: Mesh
    spec: MeshSpec
    platform: str
    process_index: int
    process_count: int
    # Unix time captured right after a cross-host barrier at runtime
    # setup (initialize_runtime). Because every host leaves the barrier
    # at (nearly) the same instant, the per-host readings of this one
    # shared moment let the multi-host aggregator align the hosts'
    # wall clocks (telemetry/aggregate.py). None for runtimes built
    # without initialize_runtime (tests, dryruns) and for hosts whose
    # setup barrier failed — those merge with zero clock correction.
    clock_sync_unix: float | None = None

    @property
    def is_coordinator(self) -> bool:
        return self.process_index == 0

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def local_device_count(self) -> int:
        return jax.local_device_count()

    @property
    def device_kind(self) -> str:
        """e.g. "TPU v5 lite" — feeds MFU's peak-FLOPs lookup."""
        return self.mesh.devices.flat[0].device_kind

    # -- shardings ---------------------------------------------------------

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    @property
    def batch_sharding(self) -> NamedSharding:
        """Batch split across all data-parallel-like axes (dp, fsdp)."""
        return NamedSharding(self.mesh, P(BATCH_AXES))

    @property
    def data_shard_count(self) -> int:
        """Number of distinct data shards (≅ reference world_size for the
        DistributedSampler arithmetic)."""
        return self.spec.dp * self.spec.fsdp

    def clock_sync_record(self) -> dict:
        """Payload for this host's ``clock_sync`` telemetry event
        (docs/observability.md): the barrier-anchored timestamp plus
        process identity. ``t_sync`` is None when the runtime has no
        barrier-anchored reading (built without initialize_runtime, or
        the barrier failed): the aggregator only trusts numeric
        ``t_sync`` values, so these hosts merge with zero clock
        correction instead of a spurious one computed from startup
        skew."""
        return {
            "t_sync": self.clock_sync_unix,
            "process_index": self.process_index,
            "process_count": self.process_count,
        }

    def describe(self) -> str:
        return (f"platform={self.platform} devices={self.num_devices} "
                f"processes={self.process_count} mesh={self.spec.as_dict()}")


def _maybe_init_distributed() -> None:
    """Multi-host rendezvous.

    On Cloud TPU pods ``jax.distributed.initialize()`` auto-detects
    coordinator/process_id from the TPU metadata server (replacing the
    reference's torchrun + MASTER_ADDR:29500 rendezvous and the worker
    nc-probe loop, cloud-init.tftpl:18-32,61-77). Off-pod multi-process
    runs configure it with env vars; single-process runs skip it.
    """
    # NOTE: must not touch jax.devices()/process_count() before
    # jax.distributed.initialize() — that would initialize the local
    # backend and break pod formation. Decide from env vars only.
    coord = os.environ.get("DTT_COORDINATOR")
    nproc = os.environ.get("DTT_NUM_PROCESSES")
    pid = os.environ.get("DTT_PROCESS_ID")
    try:
        if coord and nproc and pid:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(nproc),
                process_id=int(pid),
            )
        elif os.environ.get("DTT_AUTO_DISTRIBUTED", "0") == "1":
            # TPU pod: everything auto-detected from the metadata server.
            jax.distributed.initialize()
    except RuntimeError as e:
        if "already" in str(e).lower():
            logger.info("jax.distributed already initialized by launcher")
        else:
            raise


# Sentinel + saved value for the device=cpu platform force (see
# initialize_runtime): lets a later auto/tpu call in the same process
# restore the original platform selection.
_UNFORCED = object()
_PLATFORMS_BEFORE_CPU_FORCE: object = _UNFORCED


def apply_env_platforms() -> str | None:
    """Make an explicit ``JAX_PLATFORMS`` env var win over site
    customizations that pin ``jax_platforms`` at interpreter start
    (some managed images pin their accelerator plugin, which would
    silently override the documented env-var contract). Returns the
    env value, or None if unset. Shared by every entrypoint."""
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)
    return env_platforms or None


def initialize_runtime(cfg: Config) -> Runtime:
    """Build the runtime: rendezvous (if multi-host), pick devices per
    ``cfg.train.device`` ("auto" prefers TPU, parity with reference
    device="auto" → cuda-if-available, src/distributed_trainer.py:53-58),
    resolve the mesh shape, and construct the mesh."""
    global _PLATFORMS_BEFORE_CPU_FORCE
    env_platforms = apply_env_platforms()
    device_pref = cfg.train.device
    if device_pref == "cpu":
        # Hard-select the CPU platform BEFORE anything (including
        # jax.distributed auto-detection below) can initialize a
        # backend: probing an accelerator plugin can block or fail when
        # the TPU runtime is present but unhealthy, and `device=cpu`
        # (the reference's CPU/Gloo fallback, src/distributed_trainer
        # .py:55-61) must never depend on accelerator health.
        if _PLATFORMS_BEFORE_CPU_FORCE is _UNFORCED:
            _PLATFORMS_BEFORE_CPU_FORCE = jax.config.jax_platforms
        jax.config.update("jax_platforms", "cpu")
    elif _PLATFORMS_BEFORE_CPU_FORCE is not _UNFORCED:
        # A previous device=cpu call forced the platform; undo it so
        # "auto"/"tpu" in the same process sees accelerators again
        # (best effort — backends a prior run already initialized on a
        # forced-cpu platform set may persist in jax's cache). An
        # explicit JAX_PLATFORMS env var still wins: never overwrite
        # the value the block above just applied.
        if not env_platforms:
            jax.config.update("jax_platforms",
                              _PLATFORMS_BEFORE_CPU_FORCE)
        _PLATFORMS_BEFORE_CPU_FORCE = _UNFORCED
    _maybe_init_distributed()

    if device_pref in ("auto", ""):
        devices = jax.devices()
    else:
        try:
            devices = jax.devices(device_pref)
        except RuntimeError as e:
            raise RuntimeError_(
                f"requested device '{device_pref}' unavailable: {e}") from e

    spec = MeshSpec.resolve(cfg.mesh, len(devices))
    mesh = build_mesh(spec, devices)
    # Clock-sync sample for multi-host telemetry merging: every host
    # leaves this barrier at (to collective latency) the same instant,
    # so the per-host wall-clock readings of that one shared moment
    # give the offline aggregator each host's clock offset. Skipped
    # single-process — there is nothing to align.
    clock_sync_unix: float | None = time.time()
    if jax.process_count() > 1:
        try:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                "dtt_telemetry_clock_sync")
            clock_sync_unix = time.time()
        except Exception as e:  # noqa: BLE001 — a telemetry nicety
            # must never take down runtime setup (some backends, e.g.
            # multi-process CPU, lack cross-process computations). NO
            # t_sync is recorded for this host: an unsynced timestamp
            # would read as a barrier instant and the aggregator would
            # correct this host's timeline by what is actually startup
            # skew. Without one it merges with zero correction.
            clock_sync_unix = None
            logger.warning("telemetry clock-sync barrier failed "
                           "(%s); merged timelines will carry this "
                           "host's raw clock offset", e)
    rt = Runtime(
        mesh=mesh,
        spec=spec,
        platform=devices[0].platform,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        clock_sync_unix=clock_sync_unix,
    )
    logger.info("runtime initialized: %s", rt.describe())
    return rt


def runtime_for_mesh(mesh: Mesh) -> Runtime:
    """Wrap an externally-built mesh (tests, dryruns) in a Runtime."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    spec = MeshSpec(**{a: sizes.get(a, 1) for a in MESH_AXES})
    return Runtime(
        mesh=mesh,
        spec=spec,
        platform=mesh.devices.flat[0].platform,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
    )


def topology_runtime(num_devices: int = 4,
                     topology_name: str = "v5e:2x2",
                     **axis_sizes: int) -> Runtime:
    """A Runtime over DEVICE-LESS TPU topology descriptors
    (``jax.experimental.topologies``): the real TPU compiler (libtpu)
    compiles real SPMD programs for the named topology with no
    attached chips. Audit/AOT use only — the resulting mesh cannot
    hold data, so pair it with ``Trainer(..., abstract=True)`` and
    ShapeDtypeStruct inputs. This is how the repo inspects what the
    TPU backend (vs the CPU partitioner) compiles a sharded step into
    — e.g. whether FSDP's gradient sync becomes reduce-scatter
    (benchmarks/audit_collectives.py --tpu-topology)."""
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topology_name)
    devices = list(topo.devices)
    if len(devices) < num_devices:
        raise RuntimeError_(
            f"topology {topology_name} has {len(devices)} devices, "
            f"need {num_devices}")
    devices = devices[:num_devices]
    cfg = MeshConfig(**{**{a: 1 for a in MESH_AXES}, "dp": -1,
                        **axis_sizes})
    spec = MeshSpec.resolve(cfg, num_devices)
    return dataclasses.replace(
        runtime_for_mesh(build_mesh(spec, devices)), platform="tpu",
        process_index=0, process_count=1)


def fake_cpu_runtime(num_devices: int = 8, **axis_sizes: int) -> Runtime:
    """Test/dryrun helper: a Runtime over CPU fake devices.

    The CPU analogue of the reference's Gloo fallback
    (src/distributed_trainer.py:55-61) — requires the process to have been
    started with ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
    (tests/conftest.py does this).
    """
    devices = jax.devices("cpu")[:num_devices]
    if len(devices) < num_devices:
        raise RuntimeError_(
            f"need {num_devices} cpu devices, have {len(devices)}; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={num_devices}")
    cfg = MeshConfig(**{**{a: 1 for a in MESH_AXES}, "dp": -1, **axis_sizes})
    spec = MeshSpec.resolve(cfg, num_devices)
    return dataclasses.replace(
        runtime_for_mesh(build_mesh(spec, devices)), platform="cpu")
