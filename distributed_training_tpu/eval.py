"""Offline evaluation CLI: score a trained checkpoint on a dataset.

Completes the train → eval → generate loop (the reference evaluates
nothing; its loss is the degenerate single-logit xent — SURVEY.md §8
B5). The model is rebuilt from the run's resolved_config.yaml, params
restore topology-free from the newest (or a named) step, and the
dataset defaults to the run's own training dataset — override it to
score held-out corpora:

    python -m distributed_training_tpu.eval --run-dir outputs/default
    python -m distributed_training_tpu.eval --run-dir outputs/byte \
        --dataset bytes_file --dataset-kwargs '{"path": "corpus.txt",
        "seq_len": 256}' --batch-size 8 --max-batches 50

Prints ONE JSON line: {"loss": ..., "perplexity": ..., "tokens": ...,
"batches": ..., "step": ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dtt-eval",
        description="Score a trained checkpoint on a dataset")
    p.add_argument("--run-dir", required=True,
                   help="training run dir (resolved_config.yaml + "
                        "checkpoints)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: newest)")
    p.add_argument("--dataset", default=None,
                   help="dataset registry name (default: the run's "
                        "train.dataset)")
    p.add_argument("--dataset-kwargs", default=None,
                   help="JSON dict (default: the run's "
                        "train.dataset_kwargs)")
    p.add_argument("--batch-size", type=int, default=None,
                   help="default: the run's train.batch_size")
    p.add_argument("--max-batches", type=int, default=0,
                   help="0 = the whole dataset")
    p.add_argument("--device", default="auto",
                   help="platform for scoring (auto|tpu|cpu) — the "
                        "run's trained topology is NOT required; eval "
                        "replicates params over whatever is local")
    p.add_argument("--events-jsonl", default=None,
                   help="write telemetry spans/events here (default: "
                        "off; the summarizer CLI reads the stream)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_argparser().parse_args(argv)

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import numpy as np

    from distributed_training_tpu import telemetry as telemetry_lib
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               build_dataset)
    from distributed_training_tpu.generate import (
        _build_model_from_cfg, _load_run_config, _restore_params)
    from distributed_training_tpu.runtime import initialize_runtime

    if args.events_jsonl:
        # fresh=False: the natural target is the run's own
        # events.jsonl — eval must append after a run_start marker,
        # never truncate the training run's telemetry.
        telemetry_lib.install(telemetry_lib.Telemetry(
            events_jsonl=args.events_jsonl, fresh=False))

    cfg = _load_run_config(args.run_dir)
    model = _build_model_from_cfg(cfg)
    params, step = _restore_params(args.run_dir,
                                   cfg.train.snapshot_path, args.step)

    # Score on whatever is LOCAL: the run's trained topology (device
    # kind, mesh shape) is frozen in its resolved config and generally
    # does not exist on the scoring machine — reset to a plain
    # data-parallel mesh over the local devices.
    from distributed_training_tpu.config import MeshConfig
    cfg.mesh = MeshConfig()
    cfg.train.device = args.device
    rt = initialize_runtime(cfg)
    if hasattr(model, "bind_mesh"):
        model.bind_mesh(rt.mesh)
    # Params restored single-device; the loader yields mesh-sharded
    # batches — replicate params across the runtime mesh so the jitted
    # score sees one consistent device set.
    from jax.sharding import NamedSharding, PartitionSpec
    params = jax.device_put(
        params, NamedSharding(rt.mesh, PartitionSpec()))
    ds_name = args.dataset or cfg.train.dataset
    # A dataset override starts from EMPTY kwargs: the run's
    # dataset_kwargs belong to its own dataset and are generally
    # invalid for a different one (a silent carry-over would score
    # the wrong corpus parameters).
    if args.dataset_kwargs is not None:
        ds_kwargs = json.loads(args.dataset_kwargs)
    elif args.dataset:
        ds_kwargs = {}
    else:
        ds_kwargs = dict(cfg.train.dataset_kwargs)
    dataset = build_dataset(
        ds_name,
        _defaults={"size": cfg.train.dataset_size,
                   "seed": cfg.train.seed},
        **ds_kwargs)
    # The loader wrap-pads a short final batch to keep shapes static;
    # duplicate rows would bias a held-out score, so only FULL batches
    # are scored — unless the whole dataset is smaller than one global
    # batch (then the padded batch is scored and the output SAYS so).
    batch_size = args.batch_size or cfg.train.batch_size
    loader = ShardedDataLoader(dataset, rt, batch_size=batch_size,
                               shuffle=False)
    full_steps = loader.sampler.num_samples // batch_size
    padded = full_steps == 0
    score_steps = max(full_steps, 1)
    if args.max_batches:
        score_steps = min(score_steps, args.max_batches)

    rng = jax.random.PRNGKey(0)

    @jax.jit
    def score(params, batch):
        loss, _metrics = model.loss(params, batch, rng, train=False)
        return loss

    losses = []
    tokens = 0
    with telemetry_lib.span("eval", run_dir=args.run_dir, step=step):
        for i, batch in enumerate(loader.epoch(0)):
            if i >= score_steps:
                break
            losses.append(float(score(params, batch)))
            first = next(iter(batch.values()))
            tokens += int(np.prod(first.shape))
    if not losses:
        raise ValueError("dataset yielded no batches")
    mean = float(np.mean(losses))
    rec = {
        "loss": round(mean, 6),
        "perplexity": round(float(np.exp(mean)), 4),
        "tokens": tokens,
        "batches": len(losses),
        "step": step,
    }
    if padded:
        rec["padded"] = True  # dataset < one global batch; rows repeat
    telemetry_lib.event("eval_result", **rec)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
