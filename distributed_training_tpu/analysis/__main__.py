"""CLI: ``python -m distributed_training_tpu.analysis [--check]``.

Runs the JAX-pitfall rules (DTT0xx) over the repo and the SPMD audit
over every named target, writes ``spmd_audit.json`` (``schema: 1``),
prints the human report, and — under ``--check`` — exits nonzero on
any rule violation or any audit finding NOT in the committed baseline
(the ratchet). ``--write-baseline`` freezes the current findings as
the new known set.

Platform env (CPU backend, enough fake devices for the largest
target) is forced at import time, BEFORE any jax backend initializes:
the audits are device-less by design and must not touch — or depend
on the health of — a real accelerator.
"""

from __future__ import annotations

import os as _os

# Must precede the first jax backend initialization (package import
# does not initialize a backend; the first devices() call does).
_os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = _os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import argparse  # noqa: E402
import json      # noqa: E402
import os        # noqa: E402
import sys       # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_rules(repo: str = REPO) -> list[str]:
    """DTT0xx pitfall rules over every repo file (tests exempt; walk
    and skip set shared with tools/lint_local.py via pitfalls)."""
    from distributed_training_tpu.analysis import pitfalls
    problems: list[str] = []
    for path in pitfalls.iter_py_files(repo):
        problems += pitfalls.check_file_rules(path, repo=repo)
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_training_tpu.analysis",
        description="Static SPMD audit + JAX-pitfall lint gate.")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any rule violation or any audit "
                         "finding not in the baseline")
    ap.add_argument("--targets", default="",
                    help="comma-separated audit target names "
                         "(default: all)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="where to write spmd_audit.json (default "
                         "outputs/analysis/spmd_audit.json; '-' to "
                         "skip)")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline file (default: the committed "
                         "analysis/spmd_baseline.json)")
    ap.add_argument("--overlap-baseline", default=None,
                    metavar="PATH",
                    help="overlap-ratchet baseline file (default: "
                         "the committed analysis/"
                         "OVERLAP_baseline.json); like --baseline, "
                         "a custom path keeps --write-baseline off "
                         "the committed file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current audit findings as the new "
                         "baseline")
    ap.add_argument("--lower-overlap-floor", action="store_true",
                    help="with --write-baseline: allow writing an "
                         "overlap floor BELOW the committed one (an "
                         "intentional schedule trade-off); refused "
                         "by default, and a min_overlap pin still "
                         "outranks this flag")
    ap.add_argument("--min-replicated-mib", type=float, default=1.0,
                    help="SPMD003 size floor in MiB (default 1)")
    ap.add_argument("--no-audit", action="store_true",
                    help="rules only (no compiles)")
    ap.add_argument("--no-rules", action="store_true",
                    help="audit only")
    args = ap.parse_args(argv)

    rc = 0
    write_failed = False
    if not args.no_rules:
        problems = run_rules()
        for p in problems:
            print(p)
        print(f"[analysis] rules: {len(problems)} violation(s)")
        if problems:
            rc = 1

    if not args.no_audit:
        from distributed_training_tpu.analysis import (audit,
                                                       baseline)
        names = [n for n in args.targets.split(",") if n] or None
        if args.write_baseline and names:
            # A subset run must never rewrite the committed baseline:
            # write() replaces it wholesale, so the unselected
            # targets' known findings would vanish and the next full
            # --check would report them all as NEW.
            ap.error("--write-baseline requires a full run "
                     "(drop --targets)")
        doc = audit.audit_targets(
            names,
            min_replicated_bytes=int(
                args.min_replicated_mib * 2**20))
        from distributed_training_tpu.analysis import targets
        overlap_pins = {
            t.name: t.min_overlap for t in targets.TARGETS.values()
            if t.min_overlap is not None}
        if args.write_baseline:
            path = baseline.write(audit.all_findings(doc),
                                  path=args.baseline)
            print(f"[analysis] baseline written: {path} "
                  f"({doc['totals']['findings']} finding(s))")
            try:
                opath = baseline.write_overlap(
                    doc, path=args.overlap_baseline,
                    min_overlap=overlap_pins,
                    allow_lower=args.lower_overlap_floor)
                print(f"[analysis] overlap baseline written: {opath}")
            except ValueError as e:
                # Pin outranks --write-baseline, and a raised floor
                # outranks a routine regen: neither a destroyed
                # schedule nor a quiet regression can become the new
                # floor. A refused write is a failed REQUESTED action
                # — nonzero even without --check (unlike report-only
                # findings), or a regen script would proceed on a
                # stale floor.
                print(f"[analysis] OVERLAP baseline NOT written: {e}")
                rc = 1
                write_failed = True
        cmp = baseline.compare(audit.all_findings(doc),
                               baseline.load(args.baseline),
                               targets=names)
        for line in audit.render_report(doc, cmp):
            print(line)
        json_path = args.json or os.path.join(
            "outputs", "analysis", "spmd_audit.json")
        if json_path != "-":
            if os.path.dirname(json_path):
                os.makedirs(os.path.dirname(json_path), exist_ok=True)
            with open(json_path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            print(f"[analysis] audit written: {json_path}")
        if cmp["new"] and not args.write_baseline:
            print(f"[analysis] {len(cmp['new'])} NEW audit "
                  "finding(s) not in baseline")
            rc = 1
        pins = audit.pinned_violations(doc)
        for p in pins:
            print(f"[analysis] PIN violation: {p}")
        if pins:
            # Pins outrank the baseline: a fixed-and-pinned finding
            # class returning is a regression even when --write-
            # baseline would happily freeze it.
            rc = 1
        overlap_problems = baseline.compare_overlap(
            doc, baseline.load_overlap(args.overlap_baseline),
            min_overlap=overlap_pins)
        for p in overlap_problems:
            print(f"[analysis] OVERLAP regression: {p}")
        if overlap_problems:
            # The overlap ratchet: a schedule change that stops
            # hiding comms under compute on a gated target is a perf
            # regression tier-1 catches without a chip.
            rc = 1

    if not args.check:
        return 1 if write_failed else 0
    return rc


if __name__ == "__main__":
    sys.exit(main())
