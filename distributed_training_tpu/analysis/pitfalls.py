"""AST-level JAX-pitfall lint rules (DTT0xx) as a registry.

Each rule encodes a discipline the codebase otherwise keeps only by
convention — and conventions are exactly what the next contributor
breaks. The registry form exists so the repo's two gates cannot drift:
``tools/lint_local.py`` (the flake8-parity gate wired into tier-1) and
``python -m distributed_training_tpu.analysis --check`` (the static-
analysis CLI) both run THIS table, not private copies.

IMPORT CONTRACT: stdlib only. ``tools/lint_local.py`` loads this file
by path (``importlib``) precisely so linting never imports the package
``__init__`` — which imports jax — and the lint gate stays fast and
runnable on a machine with a broken accelerator stack. Do not import
jax, numpy, or anything from ``distributed_training_tpu`` here.

Suppression uses flake8 ``# noqa`` scoping: a bare ``# noqa`` on the
flagged line suppresses everything, ``# noqa: DTT003`` only that rule.
``tests/`` is exempt from every rule in this module (fixtures
deliberately write bad patterns; test jit steps reuse buffers).

Rule catalog (details in docs/static-analysis.md):

- DTT001 bare jsonl emission outside the telemetry sink.
- DTT002 silent broad exception swallow.
- DTT003 host sync in the hot step path: ``.item()``, ``float(arr)``,
  ``jax.device_get``, ``block_until_ready`` inside the trainer's step
  loop defeat async dispatch — one blocked host stalls every chip.
- DTT004 collective-cadence divergence: a cross-host collective
  lexically guarded by a host-local condition (``is_coordinator``,
  wall-clock, ...) deadlocks the pod — the discipline
  ``telemetry/straggler.py`` and ``resilience/faults.py`` follow
  (cadence = pure function of ``global_step``), now enforced.
- DTT005 PRNG key reuse: the same key consumed twice without
  ``jax.random.split``/``fold_in`` silently repeats randomness.
- DTT006 jitted train-step without buffer donation: params/opt-state
  double-buffer in HBM, halving the usable memory budget.
- DTT007 hard-coded world size: comparing ``process_count``-like
  values against literals >= 2, or iterating ``range(<literal>)``
  over hosts/shards, in trainer/data/telemetry hot paths — elastic
  runs (resilience/elastic.py) resize the world mid-run, and these
  literals break silently at any other size.
- DTT008 raw PartitionSpec literal: a ``P("fsdp", ...)``-style
  axis-name literal in models/ or train/ bypasses the named sharding
  map (parallel/strategy.py producers, parallel/planner.py resolved
  plans) — the single-spec-source discipline the auto-parallelism
  planner enforces. Specs DERIVED from runtime/strategy objects
  (``P(b_axes, None)``, ``P(*sh.spec[1:])``, ``P()``) stay legal.
- DTT009 unseeded RNG in ``data/``: ``np.random.default_rng()`` bare,
  module-level ``np.random.*`` samplers, stdlib ``random.*`` — the
  exactly-once pipeline's position must serialize into a checkpoint
  as integers (data/stream.py), and hidden global RNG state is
  pipeline position that cannot, so resume silently replays or skips
  samples.
- DTT010 host sync in serving hot paths: ``jax.device_get``,
  ``block_until_ready``, ``np.asarray(device_value)`` anywhere in
  ``serving/`` outside the designated sync helpers
  (``Engine._fetch_host``, disagg's KV export/import) — the
  device-resident decode loop's whole point is ONE host sync per
  K-step burst, and a stray sync re-serializes the loop per token.
- DTT011 serving params rebinding: ``<obj>.params = ...`` in
  ``serving/`` outside ``Engine.__init__``/``Engine.swap_weights``
  (and ``WeightStore.__init__``) — live weights change only through
  the swap path's validated, plan-sharded, atomic install; a bare
  rebinding skips every gate.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Callable

# Repo root when this file sits at <repo>/distributed_training_tpu/
# analysis/pitfalls.py; callers may override per-call.
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Directories never linted/audited (generated artifacts, caches,
# postmortem evidence). ONE copy, used by both gates
# (tools/lint_local.py and the analysis CLI) so they can never walk
# different file sets.
SKIP_DIRS = {".git", "__pycache__", "outputs", "_build", ".venv",
             "state", "evidence", "postmortem"}


def iter_py_files(root: str | None = None):
    """Every lintable .py file under ``root`` (default: repo root)."""
    for dirpath, dirnames, filenames in os.walk(root or REPO):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for name in filenames:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)

# ---------------------------------------------------------------------------
# Registry plumbing
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Rule:
    code: str
    name: str
    summary: str
    check: Callable


RULES: dict[str, Rule] = {}


def _rule(code: str, name: str, summary: str):
    def deco(fn):
        RULES[code] = Rule(code, name, summary, fn)
        return fn
    return deco


class FileContext:
    """One parsed file, shared across rules (parse once, lint many)."""

    def __init__(self, path: str, rel: str, text: str,
                 tree: ast.AST | None = None):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree if tree is not None else ast.parse(text)
        self._parents: dict | None = None

    @property
    def parents(self) -> dict:
        if self._parents is None:
            self._parents = {
                child: parent
                for parent in ast.walk(self.tree)
                for child in ast.iter_child_nodes(parent)}
        return self._parents

    def ancestors(self, node):
        while node in self.parents:
            node = self.parents[node]
            yield node


def noqa_allows(lines: list[str], lineno: int, code: str) -> bool:
    """flake8 noqa scoping: a bare ``# noqa`` suppresses everything,
    ``# noqa: CODE[,CODE]`` only the named codes."""
    if not (0 < lineno <= len(lines)):
        return False
    m = re.search(r"#\s*noqa(?::\s*([A-Z0-9, ]+))?", lines[lineno - 1])
    return bool(m and (m.group(1) is None or code in m.group(1)))


def check_file_rules(path: str, repo: str | None = None,
                     text: str | None = None,
                     tree: ast.AST | None = None) -> list[str]:
    """Run every registered rule over one file; returns formatted
    ``rel:line: CODE message`` problems (noqa-filtered). Files under
    ``tests/`` are exempt wholesale; syntax errors yield no findings
    (the caller's flake8 pass owns E999)."""
    repo = repo or REPO
    rel = os.path.relpath(path, repo)
    if rel.startswith("tests" + os.sep):
        return []
    if text is None:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    try:
        ctx = FileContext(path, rel, text, tree)
    except SyntaxError:
        return []
    problems: list[str] = []
    for code in sorted(RULES):
        for lineno, msg in RULES[code].check(ctx):
            if noqa_allows(ctx.lines, lineno, code):
                continue
            problems.append(f"{rel}:{lineno}: {code} {msg}")
    return problems


def _terminal_name(node) -> str:
    """The rightmost identifier of a Name/Attribute chain ('' else)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _attr_chain(node) -> list[str]:
    """['jax', 'random', 'normal'] for ``jax.random.normal`` (best
    effort; empty when the chain roots in a call/subscript)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _names_in(node) -> set[str]:
    """Every Name id and Attribute attr in a subtree."""
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


# ---------------------------------------------------------------------------
# DTT001 — bare jsonl emission
# ---------------------------------------------------------------------------

# The only modules allowed to open a jsonl stream for writing: the
# event sink (host tagging lives there) and the metrics logger (its
# own sink, predating telemetry; metrics.jsonl is not an event
# stream). Everything else must emit through telemetry/events.py.
JSONL_SINKS = {
    os.path.join("distributed_training_tpu", "telemetry", "events.py"),
    os.path.join("distributed_training_tpu", "utils", "metrics.py"),
}
_WRITE_CHARS = set("wax+")


@_rule("DTT001", "bare-jsonl-write",
       "write-mode open() of a *jsonl* stream outside the event sink")
def _check_jsonl_sink(ctx: FileContext):
    """A write-mode ``open`` of a ``*jsonl*`` stream outside the
    telemetry/metrics sinks skips host tagging, and the multi-host
    aggregator silently mis-attributes the records."""
    if ctx.rel in JSONL_SINKS:
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "open" and node.args):
            continue
        mode = node.args[1] if len(node.args) >= 2 else None
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if not (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and set(mode.value) & _WRITE_CHARS):
            continue
        target = ast.get_source_segment(ctx.text, node.args[0]) or ""
        if "jsonl" not in target.lower():
            continue
        yield (node.lineno,
               "write-mode open() of a jsonl stream outside the "
               "telemetry sink — emit through telemetry/events.py "
               "(host tagging)")


# ---------------------------------------------------------------------------
# DTT002 — silent broad exception swallow
# ---------------------------------------------------------------------------

# Files allowed to contain broad `except ...: pass` swallows.
# Deliberately empty — every current swallow either logs a breadcrumb
# or carries an inline `# noqa: DTT002` with its justification; add a
# path here only when a whole file is best-effort by design.
DTT002_ALLOWLIST: set[str] = set()
_BROAD_EXC_NAMES = {"Exception", "BaseException"}


@_rule("DTT002", "silent-broad-swallow",
       "broad `except ...: pass` discards failure evidence")
def _check_silent_swallow(ctx: FileContext):
    """``except Exception: pass`` (or bare except / BaseException)
    discards failure evidence — in a codebase whose failure model is
    crash-restart-resume, that is how recovery bugs hide. Narrow
    handlers (``except FileNotFoundError: pass``) are fine — naming
    the exception is the evidence the swallow was a decision."""
    if ctx.rel in DTT002_ALLOWLIST:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not all(isinstance(s, ast.Pass) for s in node.body):
            continue
        t = node.type
        names = []
        if t is None:
            names = ["<bare>"]
        elif isinstance(t, ast.Name):
            names = [t.id]
        elif isinstance(t, ast.Tuple):
            names = [e.id for e in t.elts if isinstance(e, ast.Name)]
        if not any(n == "<bare>" or n in _BROAD_EXC_NAMES
                   for n in names):
            continue
        yield (node.lineno,
               "silent broad exception swallow (`except Exception: "
               "pass`) — narrow it, log a breadcrumb, or noqa with "
               "justification")


# ---------------------------------------------------------------------------
# DTT003 — host sync in the hot step path
# ---------------------------------------------------------------------------

# Functions that ARE the hot step path, per file. The trainer's step
# loop is the one place a host sync stalls every chip in the mesh (the
# dispatch queue drains and the devices idle until the host catches
# up). Deliberate once-per-epoch/eval syncs carry `# noqa: DTT003`
# with their justification — the noqa is the documentation.
DTT003_HOT_PATHS: dict[str, set[str]] = {
    os.path.join("distributed_training_tpu", "train", "trainer.py"):
        {"train_step", "_run_epoch", "evaluate"},
}
_HOST_SYNC_ATTRS = {"item", "block_until_ready", "device_get"}
_HOST_SYNC_CASTS = {"float", "int", "bool"}


@_rule("DTT003", "hot-path-host-sync",
       "host-device sync inside the hot step path")
def _check_hot_path_sync(ctx: FileContext):
    """``.item()`` / ``float(arr)`` / ``jax.device_get`` /
    ``block_until_ready`` inside the trainer's step loop force a
    per-step host round-trip, defeating async dispatch (the repo's
    design is ONE host sync per epoch). Casts of constants are fine."""
    hot = DTT003_HOT_PATHS.get(ctx.rel)
    if not hot:
        return
    for fn in ast.walk(ctx.tree):
        if not (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and fn.name in hot):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node.func)
            if name in _HOST_SYNC_ATTRS:
                yield (node.lineno,
                       f"host sync `{name}()` in hot step path "
                       f"`{fn.name}` — keep device values on device "
                       "(one sync per epoch; noqa deliberate syncs)")
            elif (isinstance(node.func, ast.Name)
                  and name in _HOST_SYNC_CASTS and node.args
                  and not all(isinstance(a, ast.Constant)
                              for a in node.args)):
                yield (node.lineno,
                       f"host sync `{name}(...)` in hot step path "
                       f"`{fn.name}` — keep device values on device "
                       "(one sync per epoch; noqa deliberate syncs)")


# ---------------------------------------------------------------------------
# DTT004 — collective cadence must not be host-local
# ---------------------------------------------------------------------------

# Host-level collectives (left) must be reached by EVERY host at the
# same loop point; any lexically-enclosing condition that can evaluate
# differently per host (right) strands the others in the collective.
_DTT004_COLLECTIVES = {
    "process_allgather", "sync_global_devices", "broadcast_one_to_all",
    "assert_equal", "psum", "pmean", "pmax", "pmin", "all_gather",
    "all_to_all", "ppermute",
}
_DTT004_HOST_LOCAL = {
    "is_coordinator", "process_index", "should_stop", "perf_counter",
    "monotonic", "time", "time_ns", "random", "getrandbits", "uuid4",
    "environ", "getenv", "gethostname",
}


@_rule("DTT004", "host-local-collective-guard",
       "collective reachable under a host-local condition")
def _check_collective_cadence(ctx: FileContext):
    """A ``process_allgather``/``psum``/... guarded by a condition
    that differs across hosts (coordinator checks, wall-clock, env)
    deadlocks the pod: some hosts enter the collective, the rest never
    arrive. Cadence must be a pure function of ``global_step`` or of
    config identical on every host (the straggler/faults discipline).
    Lexical check only — early-return guards are invisible to it."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _terminal_name(node.func) in _DTT004_COLLECTIVES):
            continue
        for anc in ctx.ancestors(node):
            if not isinstance(anc, (ast.If, ast.While, ast.IfExp)):
                continue
            markers = _names_in(anc.test) & _DTT004_HOST_LOCAL
            if markers:
                yield (node.lineno,
                       f"collective `{_terminal_name(node.func)}` "
                       "reachable under host-local condition "
                       f"({', '.join(sorted(markers))}) — cadence "
                       "must be a pure function of global_step "
                       "(deadlock risk)")
                break


# ---------------------------------------------------------------------------
# DTT005 — PRNG key reuse
# ---------------------------------------------------------------------------

_KEY_MAKERS = {"PRNGKey", "key", "split", "fold_in"}
_KEY_NONCONSUMERS = _KEY_MAKERS | {"wrap_key_data", "key_data",
                                   "clone"}


def _dtt005_scope_events(scope, skip_nested: bool = True):
    """(lineno, col, kind, name) events for one function/module scope:
    'make' = a name bound from PRNGKey/split/fold_in OR received as a
    function parameter (keys threaded in as arguments are the common
    real reuse pattern), 'bind' = any other rebind of a name, 'use' =
    the name in the KEY position of a ``jax.random.*`` sampler call
    (first positional arg, or a key/rng/seed kwarg — never shape/count
    args, so tracking every parameter cannot false-positive on them).
    """
    events = []
    tracked: set[str] = set()
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = scope.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            tracked.add(arg.arg)
            events.append((scope.lineno, -1, "make", arg.arg))

    def visit(node, top=False):
        if not top and skip_nested and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.Lambda)):
            return
        if isinstance(node, ast.Assign):
            chain = (_attr_chain(node.value.func)
                     if isinstance(node.value, ast.Call) else [])
            is_key = bool(chain) and chain[-1] in _KEY_MAKERS and (
                len(chain) == 1 or "random" in chain)
            for t in node.targets:
                names = (t.elts if isinstance(t, ast.Tuple) else [t])
                for el in names:
                    if isinstance(el, ast.Name):
                        kind = "make" if is_key else "bind"
                        if kind == "make":
                            tracked.add(el.id)
                        events.append((node.lineno, node.col_offset,
                                       kind, el.id))
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if (len(chain) >= 2 and chain[-2] == "random"
                    and chain[-1] not in _KEY_NONCONSUMERS):
                for arg in node.args[:1] + [
                        kw.value for kw in node.keywords
                        if kw.arg in ("key", "rng", "seed")]:
                    if isinstance(arg, ast.Name):
                        events.append((arg.lineno, arg.col_offset,
                                       "use", arg.id))
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(scope, top=True)
    return [e for e in sorted(events) if e[3] in tracked]


@_rule("DTT005", "prng-key-reuse",
       "a PRNG key consumed twice without split/fold_in")
def _check_key_reuse(ctx: FileContext):
    """Passing the same key to two ``jax.random.*`` samplers yields
    IDENTICAL randomness — correlated inits, repeated dropout masks.
    Split (or fold_in) before every consumption. Lexical check per
    scope: reuse across loop iterations is out of reach."""
    scopes = [ctx.tree] + [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in scopes:
        counts: dict[str, int] = {}
        for lineno, _col, kind, name in _dtt005_scope_events(scope):
            if kind in ("make", "bind"):
                counts[name] = 0
            elif kind == "use":
                counts[name] = counts.get(name, 0) + 1
                if counts[name] == 2:
                    yield (lineno,
                           f"PRNG key `{name}` consumed again without "
                           "jax.random.split/fold_in — identical "
                           "randomness at both sites")


# ---------------------------------------------------------------------------
# DTT006 — jitted train step must donate its buffers
# ---------------------------------------------------------------------------

_STEP_NAME = re.compile(r"(^|_)(train_?)?step(_?fn)?$", re.IGNORECASE)


# ---------------------------------------------------------------------------
# DTT007 — hard-coded world size in elastic hot paths
# ---------------------------------------------------------------------------

# Identifiers that carry a world-ish cardinality. Comparisons against
# literals >= 2 bake a topology in; 0/1 are the world-size-agnostic
# single-process / coordinator checks.
_DTT007_WORLD_NAMES = {
    "process_count", "num_processes", "world_size", "num_hosts",
    "host_count", "num_shards", "data_shard_count", "shard_count",
    "nproc",
}
# Paths (relative to the repo root) where the rule applies: the code
# an elastic resize actually flows through. Benchmarks/tools may pin
# worlds deliberately.
DTT007_SCOPED = (
    os.path.join("distributed_training_tpu", "train"),
    os.path.join("distributed_training_tpu", "data"),
    os.path.join("distributed_training_tpu", "telemetry"),
)
# Word-segment match for host/shard-indexed state in a range-loop
# body: ``host_dirs``/``per_host``/``shard``/``shards`` hit;
# ``subprocess``/``multiprocessing`` (substring "process") and other
# incidental names do not — a literal-bounded RETRY loop is not a
# world-size pin.
_DTT007_BODY_RE = re.compile(r"(^|_)(hosts?|shards?)(_|$)")


def _dtt006_step_like(ctx: FileContext, call: ast.Call) -> str:
    """Why this ``jax.jit`` call looks like a train step ('' if not):
    the jitted function's name, or the assignment target's name,
    matches the step pattern."""
    if call.args:
        arg = call.args[0]
        name = _terminal_name(arg)
        if not name and isinstance(arg, ast.Call):
            name = _terminal_name(arg.func)
        if name and _STEP_NAME.search(name):
            return name
    parent = ctx.parents.get(call)
    if isinstance(parent, ast.Assign):
        for t in parent.targets:
            name = _terminal_name(t)
            if name and _STEP_NAME.search(name):
                return name
    return ""


def _donates(call: ast.Call) -> bool:
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in call.keywords)


@_rule("DTT007", "hard-coded-world-size",
       "world-size/process-count literal in an elastic hot path")
def _check_world_size_literal(ctx: FileContext):
    """``process_count == 2`` / ``num_shards >= 4`` /
    ``for h in range(4): ... host_dirs[h] ...`` bake one world size
    into code the elastic supervisor re-forms at ANOTHER size —
    nothing crashes, the logic is just silently wrong at 3 hosts.
    Comparisons against 0/1 stay legal (the single-process check and
    coordinator gating are world-size-agnostic). Scoped to the
    trainer/data/telemetry hot paths (DTT007_SCOPED); one-off scripts
    and benchmarks may pin worlds deliberately."""
    if not any(ctx.rel.startswith(p + os.sep) or ctx.rel == p
               for p in DTT007_SCOPED):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            names = {_terminal_name(s.func) if isinstance(s, ast.Call)
                     else _terminal_name(s) for s in sides}
            lits = [s.value for s in sides
                    if isinstance(s, ast.Constant)
                    and isinstance(s.value, int)
                    and not isinstance(s.value, bool)]
            world = names & _DTT007_WORLD_NAMES
            if world and any(v >= 2 for v in lits):
                yield (node.lineno,
                       f"`{sorted(world)[0]}` compared against a "
                       "world-size literal — elastic runs resize the "
                       "world mid-run; derive from the runtime (or "
                       "noqa a deliberate pin)")
        elif (isinstance(node, ast.For)
              and isinstance(node.iter, ast.Call)
              and _terminal_name(node.iter.func) == "range"
              and node.iter.args
              and isinstance(node.iter.args[0], ast.Constant)
              and isinstance(node.iter.args[0].value, int)
              and node.iter.args[0].value >= 2
              and len(node.iter.args) == 1):
            body_names = set()
            for stmt in node.body:
                body_names |= _names_in(stmt)
            hostish = {n for n in body_names
                       if _DTT007_BODY_RE.search(n.lower())}
            if hostish:
                yield (node.lineno,
                       f"`range({node.iter.args[0].value})` iterated "
                       "over host/shard-indexed state "
                       f"({sorted(hostish)[0]}) — a fixed world size; "
                       "derive the count from the runtime")


# ---------------------------------------------------------------------------
# DTT008 — raw PartitionSpec axis literals outside the sharding map
# ---------------------------------------------------------------------------

# Paths where hard-coded mesh-axis names in PartitionSpec calls are
# banned: model and trainer hot paths. The legitimate homes of axis
# literals — parallel/strategy.py (spec producers), parallel/
# planner.py (resolved plans), runtime.py (axis constants) — are
# outside this scope by construction.
DTT008_SCOPED = (
    os.path.join("distributed_training_tpu", "models"),
    os.path.join("distributed_training_tpu", "train"),
)
_PSPEC_NAMES = {"PartitionSpec", "P"}


@_rule("DTT008", "raw-partition-spec-literal",
       "PartitionSpec axis-name literal outside the sharding map")
def _check_raw_pspec(ctx: FileContext):
    """``P("fsdp")`` / ``PartitionSpec(("dp", "fsdp"), None)`` in
    models/ or train/ hard-codes a layout decision the planner's
    sharding-map-by-name (and the strategy producers behind it) is
    supposed to own — exactly the per-strategy spec scattering
    veScale warns about and PR 8 removed. Only STRING literals in
    the call's arguments flag: ``P()``, ``P(None, ...)``, and specs
    built from runtime-derived variables (``P(b_axes or None,
    head_ax, None)``) are how models legitimately constrain
    activations without naming axes."""
    if not any(ctx.rel.startswith(p + os.sep) or ctx.rel == p
               for p in DTT008_SCOPED):
        return
    def _axis_literals(arg):
        """String constants in the AXIS positions only: the argument
        itself, or direct elements of a tuple/list argument. Strings
        nested deeper (inside comparisons, calls, subscripts —
        ``P(None if kind == "bias" else head_ax)``) are data of a
        DERIVED spec, not axis names, and must not flag."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return [arg.value]
        if isinstance(arg, (ast.Tuple, ast.List)):
            return [e.value for e in arg.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
        return []

    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _terminal_name(node.func) in _PSPEC_NAMES):
            continue
        literals = [
            lit
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]
            for lit in _axis_literals(arg)]
        if literals:
            yield (node.lineno,
                   f"PartitionSpec with axis-name literal(s) "
                   f"{sorted(set(literals))} outside the named "
                   "sharding map — route the layout through "
                   "parallel/strategy.py rules or a resolved plan "
                   "(parallel/planner.py)")


# ---------------------------------------------------------------------------
# DTT009 — unseeded RNG state inside the data pipeline
# ---------------------------------------------------------------------------

# Scope: the data pipeline, whose whole position must round-trip
# through checkpoint meta (data/stream.py StreamState). Models and
# trainers draw from jax PRNG keys (DTT005's domain), not host RNGs.
DTT009_SCOPED = (
    os.path.join("distributed_training_tpu", "data"),
)
# Seeded-constructor / non-sampling names under np.random that are
# fine: constructing a generator from explicit integers IS the
# serializable-position discipline.
_DTT009_NP_OK = {"default_rng", "Generator", "SeedSequence", "Philox",
                 "PCG64", "PCG64DXSM", "MT19937", "SFC64",
                 "BitGenerator"}
# stdlib `random` module functions that consume the hidden global
# generator (a conservative list — attribute chains rooted at a
# variable named `random` don't reach here unless len == 2).
_DTT009_STDLIB = {"random", "randint", "randrange", "uniform",
                  "choice", "choices", "sample", "shuffle", "seed",
                  "getrandbits", "gauss", "betavariate",
                  "expovariate", "normalvariate", "triangular",
                  "randbytes"}


@_rule("DTT009", "unseeded-rng-in-data",
       "RNG without an explicit seed inside the data pipeline")
def _check_unseeded_rng(ctx: FileContext):
    """``np.random.default_rng()`` with no seed, module-level
    ``np.random.rand(...)``-style samplers, and stdlib ``random.*``
    calls inside ``data/`` draw from hidden, unserializable RNG state
    — pipeline position the exactly-once contract cannot checkpoint,
    so a resume silently replays or skips samples. Every RNG in the
    data layer must be constructed from explicit integers
    (``default_rng([seed, stream, epoch])`` — see
    ``data/sampler.epoch_permutation``)."""
    if not any(ctx.rel.startswith(p + os.sep) or ctx.rel == p
               for p in DTT009_SCOPED):
        return
    # Alias resolution: `from numpy.random import default_rng [as d]`
    # and `import numpy.random as npr` must not dodge the rule.
    from_names: dict = {}
    module_aliases = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.ImportFrom)
                and node.module == "numpy.random"):
            for a in node.names:
                from_names[a.asname or a.name] = a.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy.random" and a.asname:
                    module_aliases.add(a.asname)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not chain:
            continue
        attr = chain[-1]
        if len(chain) == 1 and chain[0] in from_names:
            attr = from_names[chain[0]]
            np_random = True
        else:
            np_random = (
                (len(chain) >= 3 and chain[0] in ("np", "numpy")
                 and chain[1] == "random")
                or (len(chain) == 2 and chain[0] in module_aliases))
        if attr == "default_rng" and (np_random or "random" in chain):
            # A seed is "present" only as a non-None positional or
            # keyword value; `default_rng(seed=None)` is exactly the
            # unseeded case. A **kwargs splat is unknowable — pass.
            def _non_none(v):
                return not (isinstance(v, ast.Constant)
                            and v.value is None)
            seeded = ([a for a in node.args if _non_none(a)]
                      or [kw for kw in node.keywords
                          if kw.arg is None or _non_none(kw.value)])
            if not seeded:
                yield (node.lineno,
                       "np.random.default_rng() without an explicit "
                       "seed — unserializable pipeline position; "
                       "derive the seed from config/state integers")
        elif np_random and attr not in _DTT009_NP_OK:
            yield (node.lineno,
                   f"module-level np.random.{attr}(...) draws "
                   "from hidden global RNG state — construct a "
                   "seeded Generator instead")
        elif (len(chain) == 2 and chain[0] == "random"
              and chain[1] in _DTT009_STDLIB):
            yield (node.lineno,
                   f"stdlib random.{chain[1]}(...) draws from hidden "
                   "global RNG state — construct a seeded "
                   "np.random.Generator instead")


@_rule("DTT006", "undonated-train-step",
       "jitted train step without buffer donation")
def _check_step_donation(ctx: FileContext):
    """A jitted train step that does not donate params/opt-state
    double-buffers the whole training state in HBM — the old buffers
    stay live across the update. ``donate_argnums``/``donate_argnames``
    is the contract (trainer.py donates argnum 0). Covers the call
    form (``jax.jit(step)``), the bare decorator (``@jax.jit``), and
    the partial decorator (``@partial(jax.jit, ...)``)."""
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and _terminal_name(node.func) == "jit"):
            why = _dtt006_step_like(ctx, node)
            if why and not _donates(node):
                yield (node.lineno,
                       f"jitted train step `{why}` without "
                       "donate_argnums/donate_argnames — params/opt "
                       "state double-buffer in HBM")
        elif (isinstance(node, (ast.FunctionDef,
                                ast.AsyncFunctionDef))
              and _STEP_NAME.search(node.name)):
            for dec in node.decorator_list:
                bare_jit = _terminal_name(dec) == "jit"
                call_jit = (isinstance(dec, ast.Call)
                            and _terminal_name(dec.func) == "jit")
                partial_jit = (
                    isinstance(dec, ast.Call)
                    and _terminal_name(dec.func) == "partial"
                    and dec.args
                    and _terminal_name(dec.args[0]) == "jit")
                if bare_jit or ((call_jit or partial_jit)
                                and not _donates(dec)):
                    yield (dec.lineno,
                           f"jitted train step `{node.name}` without "
                           "donate_argnums/donate_argnames — params/"
                           "opt state double-buffer in HBM")


# ---------------------------------------------------------------------------
# DTT010 — host sync in serving hot paths
# ---------------------------------------------------------------------------

# Every module under serving/ is a hot path: the engine's step loop,
# the KV pool, the scheduler, the HTTP front-end all sit between a
# request and its tokens. The device-resident decode loop (SERVING_r04)
# exists to sync the host ONCE per K-step burst; one stray
# `device_get` in the wrong function silently re-serializes it back to
# one sync per token. The ONLY functions allowed to materialize device
# values on the host are the designated sync helpers below — every
# other fetch must route through them (or carry `# noqa: DTT010` with
# its justification, e.g. warmup/debug code off the steady-state path).
DTT010_SCOPED = (
    os.path.join("distributed_training_tpu", "serving"),
)
DTT010_SYNC_HELPERS: dict[str, set[str]] = {
    os.path.join("distributed_training_tpu", "serving", "engine.py"):
        {"_fetch_host"},
    os.path.join("distributed_training_tpu", "serving", "disagg.py"):
        {"export_kv_batch", "import_kv_batch"},
}
_DTT010_SYNC_CALLS = {"device_get", "block_until_ready"}


@_rule("DTT010", "serving-hot-path-host-sync",
       "host-device sync in serving/ outside a designated sync helper")
def _check_serving_host_sync(ctx: FileContext):
    """``jax.device_get`` / ``.block_until_ready()`` /
    ``np.asarray(device_value)`` in ``serving/`` outside the
    designated sync helpers (``Engine._fetch_host``, disagg's KV
    export/import) forces an extra host round-trip per call site —
    the resident decode loop's one-sync-per-burst contract dies one
    innocent-looking fetch at a time. Host-side byte/list conversions
    should use ``np.array`` (a copy, never a device sync);
    ``jnp.asarray`` stays on device and stays legal."""
    if not any(ctx.rel.startswith(p + os.sep) or ctx.rel == p
               for p in DTT010_SCOPED):
        return
    allowed = DTT010_SYNC_HELPERS.get(ctx.rel, set())

    def _enclosing_fn(node):
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if name in _DTT010_SYNC_CALLS:
            pass
        elif name == "asarray":
            chain = _attr_chain(node.func)
            if not chain or chain[0] not in ("np", "numpy"):
                continue  # jnp.asarray / bare asarray: no host sync
        else:
            continue
        fn = _enclosing_fn(node)
        if fn is not None and fn.name in allowed:
            continue
        where = f"`{fn.name}`" if fn is not None else "module scope"
        yield (node.lineno,
               f"host sync `{name}(...)` in serving hot path {where} "
               "— route fetches through the designated sync helper "
               "(engine._fetch_host / disagg KV export-import); "
               "host-side conversions use np.array")


# ---------------------------------------------------------------------------
# DTT011 — params rebinding outside swap_weights
# ---------------------------------------------------------------------------

# Live weight hot-swap (Engine.swap_weights) is the ONE sanctioned
# place serving weights change: it validates treedef/shape/dtype/
# provenance, places every leaf on the committed plan's sharding, and
# installs atomically (all gates before the first write). A stray
# `something.params = ...` anywhere else in serving/ bypasses every
# one of those gates — half-installed weights, silent sharding
# mismatches, recompiles — so the rebinding itself is the lint target.
# Reads of `.params` and local variables NAMED params stay legal; only
# attribute REBINDING is flagged.
DTT011_SCOPED = (
    os.path.join("distributed_training_tpu", "serving"),
)
DTT011_ALLOWED: dict[str, set[str]] = {
    # Engine: construction + the swap path itself.
    os.path.join("distributed_training_tpu", "serving", "engine.py"):
        {"__init__", "swap_weights"},
    # WeightStore: loads the artifact's params at construction.
    os.path.join("distributed_training_tpu", "serving", "disagg.py"):
        {"__init__"},
}


@_rule("DTT011", "serving-params-rebinding",
       "serving weights rebound outside Engine.swap_weights")
def _check_serving_params_rebinding(ctx: FileContext):
    """``<obj>.params = ...`` (or ``+=``) in ``serving/`` outside the
    sanctioned sites (``Engine.__init__``/``Engine.swap_weights``,
    ``WeightStore.__init__``) installs weights without the swap path's
    gates — no treedef/shape/dtype check, no provenance match, no
    plan-sharding placement, no atomicity. The hot-swap contract
    (docs/robustness.md, serving resilience) holds only while
    ``swap_weights`` is the single writer."""
    if not any(ctx.rel.startswith(p + os.sep) or ctx.rel == p
               for p in DTT011_SCOPED):
        return
    allowed = DTT011_ALLOWED.get(ctx.rel, set())

    def _enclosing_fn(node):
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        else:
            continue
        for tgt in targets:
            if not (isinstance(tgt, ast.Attribute)
                    and tgt.attr == "params"):
                continue
            fn = _enclosing_fn(node)
            if fn is not None and fn.name in allowed:
                continue
            where = f"`{fn.name}`" if fn is not None else \
                "module scope"
            yield (node.lineno,
                   f"`.params` rebound in {where} — weights change "
                   "ONLY through Engine.swap_weights (validated, "
                   "plan-sharded, atomic); a bare rebinding skips "
                   "every swap gate")
