"""Compile-time SPMD auditor: static findings over the compiled step.

For each named target (``targets.py``) this lowers + compiles the real
jitted train step abstractly on a simulated mesh (``compile.py``),
captures XLA's SPMD-partitioner diagnostics from the stderr fd, and
derives three finding classes from artifacts the run itself produces:

- **SPMD001 involuntary reshard**: the partitioner's "Involuntary full
  rematerialization" warning — to move a tensor between two shardings
  it replicates the FULL tensor on every device and re-partitions.
  Silent at small scale, a cliff at pod scale (traffic scales with the
  tensor, not the shard). Parsed by
  ``telemetry/collectives.py::parse_reshard_warnings`` — the same
  parser the trainer's ``collectives`` event uses, so the gate and the
  ledger can never disagree about the count.
- **SPMD002 unattributed collective**: a collective whose
  ``replica_groups`` match no grouping any combination of declared
  mesh axes can produce (``mesh_axis_groupings``). Either the layout
  sprouted communication nobody designed, or the mesh declaration no
  longer describes the program — both are findings.
- **SPMD003 replicated large parameter**: under a model-sharded
  strategy (fsdp/tp > 1), a parameter above the size floor whose
  sharding spec references no mesh axis — it costs full-size HBM on
  every device (cross-checked against ``utils/memory.py``'s exact
  per-device residency accounting).

Findings carry stable fingerprints; ``baseline.py`` ratchets them so
CI fails only on NEW findings while the committed known set burns
down. Everything here is static — no training state is ever
materialized, no accelerator is needed.
"""

from __future__ import annotations

from distributed_training_tpu.analysis import baseline as baseline_lib
from distributed_training_tpu.analysis import targets as targets_lib
from distributed_training_tpu.analysis.compile import (
    build_abstract_trainer)

SCHEMA = 1

CODES = {
    "SPMD001": "involuntary full rematerialization (reshard cliff)",
    "SPMD002": "collective matches no declared mesh-axis grouping",
    "SPMD003": "large parameter fully replicated under a sharded "
               "strategy",
}


def _finding(code: str, target: str, fingerprint: str, message: str,
             **detail) -> dict:
    return {"code": code, "target": target,
            "fingerprint": fingerprint, "message": message,
            "detail": detail}


def _reshard_findings(target, warnings: list[dict]) -> list[dict]:
    out, seen = [], set()
    for w in warnings:
        fp = (f"SPMD001:{target.name}:{w['op']}:"
              f"{w['dtype']}[{w['shape']}]:"
              f"{w['from_sharding']}->{w['to_sharding']}")
        if fp in seen:
            continue
        seen.add(fp)
        out.append(_finding(
            "SPMD001", target.name, fp,
            f"involuntary full rematerialization at %{w['op']} "
            f"{w['dtype']}[{w['shape']}] "
            f"({w['from_sharding'] or '?'} -> "
            f"{w['to_sharding'] or '?'})",
            op=w["op"], dtype=w["dtype"], shape=w["shape"],
            from_sharding=w["from_sharding"],
            to_sharding=w["to_sharding"]))
    return out


def _unattributed_findings(target, coll_report: dict) -> list[dict]:
    """SPMD002 rows: collectives whose replica groups matched no
    mesh-axis grouping. Fingerprinted by kind+type (not count): the
    ratchet catches new SHAPES of unattributed traffic; magnitude
    drift is the comms-roofline telemetry's job."""
    rows = [r for r in coll_report.get("rows", [])
            if r.get("axes") == "unknown"]
    by_fp: dict[str, dict] = {}
    for r in rows:
        fp = (f"SPMD002:{target.name}:{r['kind']}:"
              f"{r['dtype']}[{r['shape']}]")
        if fp in by_fp:
            by_fp[fp]["detail"]["count"] += 1
            continue
        by_fp[fp] = _finding(
            "SPMD002", target.name, fp,
            f"{r['kind']} {r['dtype']}[{r['shape']}] communicates "
            "over replica groups matching no declared mesh-axis "
            "grouping",
            kind=r["kind"], dtype=r["dtype"], shape=r["shape"],
            bytes=r["bytes"], count=1)
    return list(by_fp.values())


def _replicated_param_findings(target, trainer,
                               min_bytes: int) -> list[dict]:
    import jax
    import numpy as np

    from distributed_training_tpu.utils.memory import (
        state_bytes_per_device)

    sizes = trainer.rt.spec.as_dict()
    model_shards = sizes.get("fsdp", 1) * sizes.get("tp", 1)
    if model_shards <= 1:
        return []  # nothing claims to shard the model; rule is moot
    param_shapes = jax.eval_shape(trainer.model.init, trainer.init_rng)
    shardings = trainer.state_shardings["params"]
    per_device = max(1, state_bytes_per_device(param_shapes, shardings))
    out: list[dict] = []

    def leaf(path, shape, sh):
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        nbytes = int(np.prod(shape.shape)) * \
            np.dtype(shape.dtype).itemsize
        spec = getattr(sh, "spec", None)
        sharded = spec is not None and any(p is not None for p in spec)
        if sharded or nbytes < min_bytes:
            return
        fp = f"SPMD003:{target.name}:{name}"
        out.append(_finding(
            "SPMD003", target.name, fp,
            f"param {name} ({nbytes / 2**20:.1f} MiB) fully "
            f"replicated under a {model_shards}x model-sharded mesh "
            f"— {100 * nbytes / per_device:.0f}% of per-device param "
            "residency (utils/memory.py accounting)",
            param=name, bytes=nbytes,
            per_device_param_bytes=per_device,
            mesh={a: s for a, s in sizes.items() if s > 1}))

    jax.tree_util.tree_map_with_path(leaf, param_shapes, shardings)
    return sorted(out, key=lambda f: -f["detail"]["bytes"])


def _audit_serving_target(target) -> dict:
    """Audit record for a ``kind="serving"`` target: the engine's
    compiled program — decode or batched prefill per
    ``target.serving_objective`` — under the committed serving plan
    (serving/disagg.py lowers it — the SAME helper the planner's
    stage-2 serving verifier compiles, so the gated program is the
    consumed program). SPMD003 does not apply (no trainer state);
    SPMD001/002 come from the same parsers as the train targets."""
    from distributed_training_tpu.parallel.planner import load_plan
    from distributed_training_tpu.serving.disagg import (
        compile_serving_hlo)
    from distributed_training_tpu.telemetry import attribution
    from distributed_training_tpu.telemetry import collectives

    plan = load_plan(target.serving_plan)
    text, warnings, mesh = compile_serving_hlo(
        plan, getattr(target, "serving_objective", "decode"))
    coll = collectives.audit_hlo_text(text, mesh=mesh)
    coll["mesh"] = dict(target.mesh_axes)
    coll["spmd_reshard_warnings"] = len(warnings)
    findings = (_reshard_findings(target, warnings)
                + _unattributed_findings(target, coll))
    by_code: dict[str, int] = {}
    for f in findings:
        by_code[f["code"]] = by_code.get(f["code"], 0) + 1
    return {
        "target": target.name,
        "title": target.title,
        "devices": target.devices,
        "strategy": target.strategy,
        "mesh": dict(target.mesh_axes),
        "spmd_reshard_warnings": len(warnings),
        "findings": findings,
        "findings_by_code": by_code,
        "collectives": collectives.summary_of_event(coll),
        "overlap": attribution.overlap_summary(
            attribution.hlo_overlap_report(text)),
        "compiler_options": dict(target.compiler_options),
    }


def audit_target(target, min_replicated_bytes: int = 1 << 20) -> dict:
    """Compile one target and return its audit record (findings +
    collective summary + reshard-warning count)."""
    import jax.numpy as jnp

    from distributed_training_tpu.telemetry import attribution
    from distributed_training_tpu.telemetry import collectives

    if getattr(target, "kind", "train") == "serving":
        return _audit_serving_target(target)
    trainer, rt, batch = build_abstract_trainer(
        target.devices, target.strategy, target.model,
        dict(target.model_kwargs), target.batch_size, target.seq_len,
        mesh_axes=dict(target.mesh_axes),
        train_overrides=dict(target.train_overrides))
    # Per-target compiler options (the planned target's overlap
    # flags): the audited schedule must be the one the flagged
    # consumers execute, or the overlap ratchet scores a program
    # nobody runs.
    opts = dict(target.compiler_options) or None
    with collectives.capture_stderr_fd() as cap:
        text = trainer._step_fn.lower(
            trainer.state, batch,
            jnp.zeros((2,), jnp.uint32)).compile(
                compiler_options=opts).as_text()
    warnings = collectives.parse_reshard_warnings(cap.text)
    coll = collectives.audit_hlo_text(text, mesh=rt.mesh)
    coll["mesh"] = {a: s for a, s in rt.spec.as_dict().items()
                    if s > 1}
    coll["spmd_reshard_warnings"] = len(warnings)

    findings = (_reshard_findings(target, warnings)
                + _unattributed_findings(target, coll)
                + _replicated_param_findings(
                    target, trainer, min_replicated_bytes))
    by_code: dict[str, int] = {}
    for f in findings:
        by_code[f["code"]] = by_code.get(f["code"], 0) + 1
    return {
        "target": target.name,
        "title": target.title,
        "devices": target.devices,
        "strategy": target.strategy,
        "mesh": coll["mesh"],
        "spmd_reshard_warnings": len(warnings),
        "findings": findings,
        "findings_by_code": by_code,
        "collectives": collectives.summary_of_event(coll),
        # Static comms/compute overlap of the compiled schedule
        # (telemetry/attribution.py), from the SAME compile as the
        # findings above — ratcheted against OVERLAP_baseline.json by
        # the gate (__main__.py). Additive key; SCHEMA stays 1.
        "overlap": attribution.overlap_summary(
            attribution.hlo_overlap_report(text)),
        # Which per-compile options the schedule was audited under
        # (the planned target's plan-derived overlap flags) — so a
        # baseline score is attributable to its scheduler config.
        "compiler_options": dict(target.compiler_options),
    }


def audit_targets(names=None,
                  min_replicated_bytes: int = 1 << 20) -> dict:
    """The full ``spmd_audit.json`` document (``schema: 1``)."""
    return assemble_doc([audit_target(t, min_replicated_bytes)
                         for t in targets_lib.resolve(names)])


def assemble_doc(records: list[dict]) -> dict:
    """Wrap per-target audit records into the spmd_audit.json shape
    (split from audit_targets so callers holding records — tests,
    cached runs — assemble without recompiling)."""
    by_code: dict[str, int] = {}
    for r in records:
        for c, n in r["findings_by_code"].items():
            by_code[c] = by_code.get(c, 0) + n
    return {
        "schema": SCHEMA,
        "codes": CODES,
        "targets": records,
        "totals": {
            "targets": len(records),
            "findings": sum(len(r["findings"]) for r in records),
            "by_code": by_code,
        },
    }


def all_findings(audit_doc: dict) -> list[dict]:
    return [f for r in audit_doc["targets"] for f in r["findings"]]


def pinned_violations(audit_doc: dict) -> list[str]:
    """Violations of per-target ``pin_zero`` pins: a finding whose
    code the target pins to zero fails the gate EVEN IF its
    fingerprint is baselined — the ratchet lets known debt ride, the
    pin keeps a fixed cliff fixed (both r05 and the headline target
    pin SPMD001 after the embedding-gather fix)."""
    out: list[str] = []
    for r in audit_doc["targets"]:
        t = targets_lib.TARGETS.get(r["target"])
        pins = tuple(getattr(t, "pin_zero", ()) or ()) if t else ()
        for code in pins:
            n = r["findings_by_code"].get(code, 0)
            if n:
                out.append(
                    f"{r['target']}: {n} {code} finding(s), but this "
                    f"target pins {code} to ZERO "
                    f"({CODES.get(code, '?')})")
    return out


def render_report(audit_doc: dict, cmp: dict | None = None
                  ) -> list[str]:
    """Human report lines. With ``cmp`` (``baseline.compare`` output)
    each finding is tagged [known]/[NEW] and stale baseline entries
    are listed for burn-down."""
    from distributed_training_tpu.telemetry import collectives

    new_fps = set(f["fingerprint"] for f in cmp["new"]) if cmp else set()
    lines: list[str] = []
    for r in audit_doc["targets"]:
        mesh = ",".join(f"{a}={s}" for a, s in r["mesh"].items()) \
            or "single-device"
        lines.append(f"== {r['target']}: {r['title']}")
        lines.append(f"   devices={r['devices']} strategy="
                     f"{r['strategy']} mesh={mesh}")
        for line in collectives.render_lines(r["collectives"]):
            lines.append("   " + line)
        ov = r.get("overlap") or {}
        if ov.get("scored"):
            lines.append(
                f"   overlap: {ov['overlap_score']:.2f} of "
                f"{ov['scored']} collective(s) scheduled with "
                f"independent compute in their latency window "
                f"(mean {ov['mean_compute_between']:.1f} op(s))")
        if not r["findings"]:
            lines.append("   findings: none")
        for f in r["findings"]:
            tag = ""
            if cmp:
                tag = "[NEW] " if f["fingerprint"] in new_fps \
                    else "[known] "
            lines.append(f"   {f['code']} {tag}{f['message']}")
    if cmp:
        lines.append(
            f"baseline: {len(cmp['known'])} known, "
            f"{len(cmp['new'])} NEW, {len(cmp['stale'])} stale")
        for fp in cmp["stale"]:
            lines.append(f"   stale baseline entry (fixed? tighten "
                         f"{baseline_lib.DEFAULT_PATH}): {fp}")
    return lines
