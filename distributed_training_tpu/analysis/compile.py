"""Abstract lowering/compilation of the REAL train step, chip-free.

The one shared implementation of "build the actual Trainer against a
simulated mesh and compile its jitted step without materializing any
state" — the device-less discipline ``Trainer.collectives_report``
uses. Three consumers ride it so their trainer/batch construction can
never drift apart:

- the SPMD auditor (``analysis/audit.py``): compiles every named
  target and inspects diagnostics + HLO;
- ``benchmarks/audit_collectives.py``: the CLI wrapper (kept for its
  UX; thin re-export of these helpers);
- ``benchmarks/precompile_points.py``: warms the compile cache through
  ``lower_abstract_step``.

Simulated meshes come in two flavors: CPU fake devices
(``--xla_force_host_platform_device_count``, compiles with the CPU
partitioner) and device-less TPU topology descriptors
(``jax.experimental.topologies`` — the real libtpu pipeline, whose
passes differ: reduce-scatter-creator etc.). jax is imported inside
the functions, never at module top: callers (CLI entrypoints) must be
able to set platform env vars first.
"""

from __future__ import annotations


def build_abstract_trainer(n_devices: int, strategy: str,
                           model_name: str, model_kwargs: dict,
                           batch_size: int, seq_len: int,
                           mesh_axes: dict | None = None,
                           train_overrides: dict | None = None,
                           tpu_topology: str | None = None):
    """The REAL Trainer in abstract mode on a simulated mesh.

    Returns ``(trainer, runtime, batch)`` where ``batch`` is a
    ShapeDtypeStruct tree carrying the trainer's batch sharding —
    ready for ``trainer._step_fn.lower(trainer.state, batch, rng)``.
    Nothing is materialized: shardings, the jitted step, and the
    strategy all exist, but ``trainer.state`` is abstract, so this
    also works on meshes with no attached devices (``tpu_topology``,
    e.g. "v5e:2x2").
    """
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from distributed_training_tpu.config import Config
    from distributed_training_tpu.data import (ShardedDataLoader,
                                               SyntheticLMDataset)
    from distributed_training_tpu.models import build_model
    from distributed_training_tpu.runtime import (fake_cpu_runtime,
                                                  topology_runtime)
    from distributed_training_tpu.train.trainer import Trainer

    cfg = Config()
    cfg.train.parallel_strategy = strategy
    cfg.train.batch_size = batch_size
    cfg.train.log_every = 0
    for k, v in (train_overrides or {}).items():
        setattr(cfg.train, k, v)
    if tpu_topology:
        rt = topology_runtime(n_devices, tpu_topology,
                              **(mesh_axes or {}))
    else:
        rt = fake_cpu_runtime(n_devices, **(mesh_axes or {}))
    model = build_model(model_name, **model_kwargs)
    ds = SyntheticLMDataset(
        size=max(64, batch_size),
        seq_len=seq_len,
        vocab_size=min(model.cfg.vocab_size, 50257), seed=0)
    loader = ShardedDataLoader(ds, rt, batch_size=batch_size,
                               shuffle=False)
    trainer = Trainer(cfg, rt, model, loader, abstract=True)
    sample = ds.batch(np.arange(1))
    batch = {
        k: jax.ShapeDtypeStruct(
            (loader.global_batch,) + v.shape[1:], v.dtype,
            sharding=trainer.batch_sharding)
        for k, v in sample.items()}
    return trainer, rt, batch


def lower_abstract_step(topology: str, n_devices: int, strategy: str,
                        model_name: str, model_kwargs: dict,
                        batch_size: int, seq_len: int,
                        mesh_axes: dict | None = None,
                        train_overrides: dict | None = None):
    """Build the abstract Trainer against a DEVICE-LESS TPU topology
    and return the Lowered train step (zero materialized state)."""
    import jax.numpy as jnp

    trainer, _rt, batch = build_abstract_trainer(
        n_devices, strategy, model_name, model_kwargs, batch_size,
        seq_len, mesh_axes=mesh_axes, train_overrides=train_overrides,
        tpu_topology=topology)
    return trainer._step_fn.lower(trainer.state, batch,
                                  jnp.zeros((2,), jnp.uint32))


def compile_step_hlo(n_devices: int, strategy: str,
                     mesh_axes: dict | None = None,
                     model_kwargs: dict | None = None,
                     tpu_topology: str | None = None,
                     seq_len: int = 32) -> str:
    """Build the real Trainer on a virtual mesh and return the
    compiled (SPMD-partitioned) HLO of its jitted train step.

    ``tpu_topology`` (e.g. "v5e:2x2") compiles with the REAL TPU
    compiler against a device-less topology descriptor instead of the
    CPU backend — the partitioning passes differ (the TPU pipeline
    runs reduce-scatter-creator; CPU lowers FSDP grad sync as
    all-reduce + dynamic-slice), so contract claims about what runs
    on hardware must audit this path (VERDICT r4 item 4)."""
    import jax.numpy as jnp

    mk = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
              max_seq_len=64, dtype="float32")
    mk.update(model_kwargs or {})
    trainer, _rt, batch = build_abstract_trainer(
        n_devices, strategy, "transformer", mk,
        batch_size=2 * n_devices, seq_len=seq_len,
        mesh_axes=mesh_axes,
        train_overrides=dict(min_shard_elems=1, dtype="float32"),
        tpu_topology=tpu_topology)
    return trainer._step_fn.lower(
        trainer.state, batch,
        jnp.zeros((2,), jnp.uint32)).compile().as_text()
