"""Static analysis: compile-time SPMD auditing + JAX-pitfall linting.

Two halves, one gate (``python -m distributed_training_tpu.analysis
--check``, wired into tier-1 via tests/test_lint_local.py):

- ``audit.py`` / ``targets.py`` / ``compile.py`` / ``baseline.py``:
  lower + compile each named config × strategy abstractly on a
  simulated mesh, flag involuntary-reshard cliffs, unattributed
  collectives, and replicated large params; ratchet against the
  committed ``spmd_baseline.json`` so only NEW findings fail.
- ``pitfalls.py``: the DTT0xx AST rule registry (host syncs in the
  step loop, host-local collective guards, PRNG key reuse, undonated
  train steps, ...), shared with ``tools/lint_local.py``.

Rule catalog and workflows: docs/static-analysis.md.
"""

from distributed_training_tpu.analysis import (  # noqa: F401
    baseline,
    pitfalls,
    targets,
)
