"""Named audit targets: config × strategy pairs the SPMD auditor gates.

A target pins everything that determines the compiled program — mesh
shape, strategy, model kwargs, batch/seq — so a finding's fingerprint
is reproducible run-over-run and the committed baseline
(``spmd_baseline.json``) stays meaningful. Add a target when a new
config/strategy combination becomes a supported path; the ratchet
then freezes its current findings and fails CI on any new one.

The two seed targets mirror the repo's live evidence:

- ``multichip_r05_tp_sp_fsdp``: the exact dryrun pass-1 configuration
  from ``__graft_entry__.py`` (the one ``MULTICHIP_r05.json`` records
  with two "Involuntary full rematerialization" warnings on the
  gather/all-gather path) — the repro ROADMAP item 1's auto-planner
  must drive to zero.
- ``single_chip_headline``: the 0.4392-MFU gpt2_125m single-chip
  headline configuration (bench.py HEADLINE_MODEL_KWARGS + the gpt2
  train defaults). Audit-sized batch — findings are sharding
  properties of the compiled program, not batch-magnitude properties
  — and it must stay at ZERO findings.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class AuditTarget:
    name: str
    title: str
    devices: int
    strategy: str
    model: str
    model_kwargs: dict = field(default_factory=dict)
    batch_size: int = 4
    seq_len: int = 32
    mesh_axes: dict = field(default_factory=dict)
    train_overrides: dict = field(default_factory=dict)
    note: str = ""


TARGETS: dict[str, AuditTarget] = {}


def _register(t: AuditTarget) -> AuditTarget:
    TARGETS[t.name] = t
    return t


_register(AuditTarget(
    name="multichip_r05_tp_sp_fsdp",
    title="8-device tp+sp+fsdp dryrun (windowed GQA ring attention)",
    devices=8,
    strategy="tp",
    model="transformer",
    model_kwargs=dict(vocab_size=256, d_model=64, n_heads=4,
                      dtype="float32", max_seq_len=32, n_layers=2,
                      n_kv_heads=2, attention_impl="ring",
                      attention_window=24),
    batch_size=2,
    seq_len=32,
    mesh_axes=dict(fsdp=2, sp=2, tp=2),
    train_overrides=dict(min_shard_elems=1, dtype="float32",
                         optimizer="adamw"),
    note="__graft_entry__.py dryrun pass 1 — the MULTICHIP_r05.json "
         "configuration whose SPMD log shows involuntary full "
         "rematerialization on the gather/all-gather path. Known "
         "findings are baselined; ROADMAP item 1's planner drives "
         "them to zero.",
))

_register(AuditTarget(
    name="single_chip_headline",
    title="gpt2_125m single-chip headline (0.4392 MFU config)",
    devices=1,
    strategy="ddp",
    model="gpt2_125m",
    model_kwargs=dict(remat=True, remat_policy="mlp",
                      dtype="bfloat16"),
    batch_size=4,
    seq_len=1024,
    mesh_axes={},
    train_overrides=dict(dtype="bfloat16", optimizer="adamw"),
    note="bench.py headline configuration (HEADLINE_MODEL_KWARGS, "
         "seq 1024, adamw bf16). Single chip: zero collectives, zero "
         "reshard warnings — any finding here is a regression.",
))


def resolve(names=None) -> list[AuditTarget]:
    """Targets by name (all when ``names`` is falsy); unknown names
    raise with the available set spelled out."""
    if not names:
        return list(TARGETS.values())
    out = []
    for n in names:
        if n not in TARGETS:
            raise KeyError(
                f"unknown audit target '{n}'; available: "
                f"{sorted(TARGETS)}")
        out.append(TARGETS[n])
    return out
