"""Named audit targets: config × strategy pairs the SPMD auditor gates.

A target pins everything that determines the compiled program — mesh
shape, strategy, model kwargs, batch/seq — so a finding's fingerprint
is reproducible run-over-run and the committed baseline
(``spmd_baseline.json``) stays meaningful. Add a target when a new
config/strategy combination becomes a supported path; the ratchet
then freezes its current findings and fails CI on any new one.

The three targets mirror the repo's live evidence:

- ``multichip_r05_tp_sp_fsdp``: the exact dryrun pass-1 configuration
  from ``__graft_entry__.py`` — the one whose ``MULTICHIP_r05.json``
  log recorded two "Involuntary full rematerialization" warnings on
  the gather/all-gather path. The embedding-table gather-for-compute
  constraint (models/transformer.py ``_gathered_table``) fixed the
  cliff; the target is now PINNED to zero SPMD001 findings
  (``pin_zero``) so the fix can never silently regress, baselined or
  not.
- ``single_chip_headline``: the 0.4392-MFU gpt2_125m single-chip
  headline configuration (bench.py HEADLINE_MODEL_KWARGS + the gpt2
  train defaults). Audit-sized batch — findings are sharding
  properties of the compiled program, not batch-magnitude properties
  — and it must stay at ZERO findings.
- ``multichip_r06_planned``: the committed auto-parallelism plan
  (``conf/plans/multichip_8dev.json`` — parallel/planner.py) compiled
  through the SAME trainer path ``benchmarks/bench_multichip.py``
  measures, with the plan pinned via ``train.sharding_plan``. This is
  the "zero involuntary-reshard warnings on the chosen plan" gate:
  the planner's own ``--check`` verifies the plan is still the
  search's winner; THIS target re-proves it compiles clean on the
  current XLA.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass(frozen=True)
class AuditTarget:
    name: str
    title: str
    devices: int
    strategy: str
    model: str
    model_kwargs: dict = field(default_factory=dict)
    batch_size: int = 4
    seq_len: int = 32
    mesh_axes: dict = field(default_factory=dict)
    train_overrides: dict = field(default_factory=dict)
    # Finding codes this target pins to ZERO: unlike the baseline
    # ratchet (which lets KNOWN findings ride), a pinned code fails
    # --check even if its fingerprints are baselined — the mechanism
    # that keeps a FIXED cliff fixed.
    pin_zero: tuple = ()
    # Static comms/compute overlap floor (telemetry/attribution.py
    # score): a compiled schedule scoring below this fails the gate
    # even if OVERLAP_baseline.json was rewritten lower — the
    # pin-outranks-baseline rule, overlap edition. None = ratchet
    # against the committed baseline only.
    min_overlap: float | None = None
    # Per-compile XLA options (jax ``compile(compiler_options=...)``)
    # this target's program is audited under. The planned target
    # carries its plan's overlap flags (``parallel/overlap.py``) so
    # the ratchet scores the latency-hiding schedule the training
    # consumers (cli/launch/bench) actually run — the audit and the
    # run must compile the same program.
    compiler_options: dict = field(default_factory=dict)
    # Which program this target audits. "train" (default): the
    # jitted train step via the abstract Trainer. "serving": the
    # serving engine's compiled program under the committed plan
    # named by ``serving_plan`` (serving/disagg.py lowers it) —
    # ``serving_objective`` picks which engine program: "decode"
    # (the whole-table one-token program), "prefill" (the batched
    # multi-sequence lane program, SERVING_r03) or "resident" (the
    # device-resident K-step while_loop decode program,
    # SERVING_r04). A KV-layout regression then goes tier-1 red
    # with no accelerator, exactly like a train-step reshard.
    kind: str = "train"
    serving_plan: str = ""
    serving_objective: str = "decode"
    note: str = ""


TARGETS: dict[str, AuditTarget] = {}


def _register(t: AuditTarget) -> AuditTarget:
    TARGETS[t.name] = t
    return t


_register(AuditTarget(
    name="multichip_r05_tp_sp_fsdp",
    title="8-device tp+sp+fsdp dryrun (windowed GQA ring attention)",
    devices=8,
    strategy="tp",
    model="transformer",
    model_kwargs=dict(vocab_size=256, d_model=64, n_heads=4,
                      dtype="float32", max_seq_len=32, n_layers=2,
                      n_kv_heads=2, attention_impl="ring",
                      attention_window=24),
    batch_size=2,
    seq_len=32,
    mesh_axes=dict(fsdp=2, sp=2, tp=2),
    train_overrides=dict(min_shard_elems=1, dtype="float32",
                         optimizer="adamw"),
    pin_zero=("SPMD001",),
    note="__graft_entry__.py dryrun pass 1 — the MULTICHIP_r05.json "
         "configuration whose SPMD log used to show involuntary full "
         "rematerialization on the gather/all-gather path (the token-"
         "embedding lookup). Fixed by the embedding-table gather-for-"
         "compute constraint; SPMD001 is pinned to zero so the cliff "
         "cannot return. The ring's collective-permutes stay "
         "baselined as SPMD002 (src->tgt pairs match no axis "
         "grouping by construction).",
))

_register(AuditTarget(
    name="single_chip_headline",
    title="gpt2_125m single-chip headline (0.4392 MFU config)",
    devices=1,
    strategy="ddp",
    model="gpt2_125m",
    model_kwargs=dict(remat=True, remat_policy="mlp",
                      dtype="bfloat16"),
    batch_size=4,
    seq_len=1024,
    mesh_axes={},
    train_overrides=dict(dtype="bfloat16", optimizer="adamw"),
    pin_zero=("SPMD001",),
    note="bench.py headline configuration (HEADLINE_MODEL_KWARGS, "
         "seq 1024, adamw bf16). Single chip: zero collectives, zero "
         "reshard warnings — any finding here is a regression.",
))


def _overlap_options(plan_doc: dict) -> dict:
    """The plan's overlap flags as per-compile options for the (CPU)
    audit backend — ``parallel/overlap.py``'s derivation over the raw
    plan JSON, matching what cli/launch/bench apply via XLA_FLAGS."""
    from distributed_training_tpu.parallel import overlap
    return overlap.flags_for_plan_doc(plan_doc, "cpu")


def _register_planned_target() -> None:
    """The committed plan as an audit target: read the raw plan JSON
    (no planner import — the plan doc is consumed as data) and pin
    its exact configuration, including the overlap compiler options
    the plan derives. Skipped silently if the plan file is absent (a
    fresh checkout mid-replan); the planner --check gate fails loudly
    in that case."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "conf", "plans",
        "multichip_8dev.json")
    if not os.path.exists(path):
        return
    try:
        with open(path, encoding="utf-8") as f:
            plan = json.load(f)
    except (OSError, ValueError):
        # A corrupt/unreadable committed plan must not kill every
        # analysis import — the planner --check gate names the
        # problem loudly; this registration just goes without its
        # target until the plan is regenerated.
        return
    mk = dict(plan["inputs"]["model_kwargs"])
    if plan["remat"] == "none":
        mk["remat"] = False
    else:
        mk.update(remat=True, remat_policy=plan["remat"])
    _register(AuditTarget(
        name="multichip_r06_planned",
        title=f"8-device auto-planned config "
              f"(plan {plan['name']}@{plan['fingerprint']})",
        devices=plan["devices"],
        strategy=plan["base_strategy"],
        model="transformer",
        model_kwargs=mk,
        batch_size=plan["batch_per_shard"],
        seq_len=plan["seq_len"],
        mesh_axes={a: s for a, s in plan["mesh"].items() if s > 1},
        train_overrides=dict(
            sharding_plan=plan["name"],
            min_shard_elems=plan["inputs"]["min_shard_elems"],
            dtype=mk.get("dtype", "float32"),
            optimizer=plan["inputs"]["optimizer"]),
        pin_zero=("SPMD001",),
        # Floor under the measured 0.92 (CPU backend with the plan's
        # latency-hiding flags — the concurrency-optimized scheduler
        # lifted this target from 0.32 unscheduled): a plan/model/
        # flag change that destroys overlap scheduling fails even
        # through --write-baseline. The ratchet
        # (OVERLAP_baseline.json) holds the exact score.
        min_overlap=0.85,
        # The audit compiles the same scheduled program the flagged
        # consumers run (module field docs).
        compiler_options=_overlap_options(plan),
        note="The committed auto-parallelism plan (conf/plans/) "
             "compiled through the trainer's PlannedStrategy path — "
             "the configuration benchmarks/bench_multichip.py "
             "measures for MULTICHIP_r06.json. Zero SPMD001 pinned: "
             "the planner must never ship a resharding layout.",
    ))


def _register_serving_target(plan_file: str, name: str,
                             objective: str, title: str,
                             note: str) -> None:
    """A committed serving plan's engine program as an audit target
    (objective "decode": the paged-KV whole-batch decode step;
    "prefill": the SERVING_r03 batched multi-sequence lane program —
    dp-dealt lanes, per-lane page rows and masks). SPMD001 pinned to
    zero — a paged-pool gather/scatter or lane-table scatter that
    starts replicating is the serving reshard cliff, and it must
    fail tier-1 without a chip. Same consume-the-plan-as-data
    discipline as the planned train target."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "conf", "plans", plan_file)
    if not os.path.exists(path):
        return
    try:
        with open(path, encoding="utf-8") as f:
            plan = json.load(f)
    except (OSError, ValueError):
        # Same contract as the planned train target: a corrupt plan
        # file must not kill the analysis import; the planner --check
        # gate reports it loudly.
        return
    _register(AuditTarget(
        name=name,
        title=f"{title} (plan {plan['name']}@{plan['fingerprint']})",
        devices=plan["devices"],
        strategy=plan["base_strategy"],
        model="transformer",
        model_kwargs=dict(plan["inputs"]["model_kwargs"]),
        batch_size=plan["batch_per_shard"],
        seq_len=plan["seq_len"],
        mesh_axes={a: s for a, s in plan["mesh"].items() if s > 1},
        pin_zero=("SPMD001",),
        kind="serving",
        serving_plan=plan["name"],
        serving_objective=objective,
        note=note,
    ))


_register_planned_target()
_register_serving_target(
    "serving_8dev_cpu_decode.json", "serving_decode_planned",
    "decode", "serving paged-KV decode step",
    note="The committed serving decode plan "
         "(conf/plans/serving_8dev_cpu_decode.json) compiled "
         "through the engine's real decode program "
         "(serving/engine.py via serving/disagg.py) — "
         "benchmarks/bench_serving.py measures this exact "
         "layout. Zero SPMD001 pinned: the paged KV pool must "
         "never compile into a replicating layout.",
)
_register_serving_target(
    "serving_4dev_cpu_prefill.json", "serving_prefill_planned",
    "prefill", "serving batched multi-sequence prefill",
    note="The committed serving prefill plan "
         "(conf/plans/serving_4dev_cpu_prefill.json) compiled "
         "through the engine's real batched prefill program "
         "(serving/engine.py build_prefill_batch_fn via "
         "serving/disagg.py) — the program "
         "benchmarks/bench_serving.py measures for SERVING_r03. "
         "Zero SPMD001 pinned: the batched lane table must "
         "never compile into a replicating layout.",
)
_register_serving_target(
    "serving_8dev_cpu_decode.json", "serving_resident_planned",
    "resident", "serving device-resident K-step decode loop",
    note="The committed serving decode plan compiled through the "
         "engine's DEVICE-RESIDENT decode program (serving/engine.py "
         "build_resident_decode_fn via serving/disagg.py) — the "
         "lax.while_loop of speculative chunk steps "
         "benchmarks/bench_serving.py measures for SERVING_r04. "
         "Zero SPMD001 pinned: an in-loop page scatter or history "
         "gather that starts replicating would multiply the reshard "
         "cliff by K — it must fail tier-1 without a chip.",
)


def resolve(names=None) -> list[AuditTarget]:
    """Targets by name (all when ``names`` is falsy); unknown names
    raise with the available set spelled out."""
    if not names:
        return list(TARGETS.values())
    out = []
    for n in names:
        if n not in TARGETS:
            raise KeyError(
                f"unknown audit target '{n}'; available: "
                f"{sorted(TARGETS)}")
        out.append(TARGETS[n])
    return out
