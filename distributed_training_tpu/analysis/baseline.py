"""Baseline ratchet for SPMD audit findings.

The committed ``spmd_baseline.json`` freezes the KNOWN findings (by
fingerprint) so the check gate fails only on new ones: the tp+sp+fsdp
dryrun's involuntary-reshard warnings are real, documented, and owned
by ROADMAP item 1 — they must not make every CI run red, but nothing
NEW may hide behind them. Stale entries (baselined fingerprints no
run reproduces anymore) are reported for burn-down, never failed on:
a fixed finding should shrink the baseline at the author's next
``--write-baseline``, not break the build for being an improvement.

No jax imports here — the ratchet arithmetic is unit-tested without a
compile in sight.
"""

from __future__ import annotations

import json
import os

SCHEMA = 1

DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "spmd_baseline.json")


def load(path: str | None = None) -> dict:
    """The baseline doc ({"schema": 1, "fingerprints": [...]});
    a missing file is an EMPTY baseline — every finding is new."""
    path = path or DEFAULT_PATH
    if not os.path.exists(path):
        return {"schema": SCHEMA, "fingerprints": []}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA:
        raise ValueError(
            f"baseline {path} has schema {doc.get('schema')!r}, "
            f"expected {SCHEMA} — regenerate with --write-baseline")
    return doc


def compare(findings: list[dict], baseline_doc: dict,
            targets: list[str] | None = None) -> dict:
    """Split current findings against the baseline.

    Returns ``{"new": [finding, ...], "known": [finding, ...],
    "stale": [fingerprint, ...]}`` — ``new`` is what fails the gate,
    ``stale`` is baseline debt that no longer reproduces. With
    ``targets`` (a subset audit run), baseline entries for OTHER
    targets are ignored: they were not re-audited, so calling them
    stale would misread "not checked" as "fixed"."""
    base = set(baseline_doc.get("fingerprints", ()))
    if targets is not None:
        tset = set(targets)
        # Fingerprint format: "CODE:<target>:<detail...>".
        base = {fp for fp in base
                if len(fp.split(":", 2)) == 3
                and fp.split(":", 2)[1] in tset}
    seen = {f["fingerprint"] for f in findings}
    return {
        "new": [f for f in findings if f["fingerprint"] not in base],
        "known": [f for f in findings if f["fingerprint"] in base],
        "stale": sorted(base - seen),
    }


def write(findings: list[dict], path: str | None = None,
          note: str = "") -> str:
    """Freeze the given findings as the new baseline (sorted, deduped,
    with messages alongside for the reviewer — only ``fingerprints``
    is load-bearing)."""
    path = path or DEFAULT_PATH
    fps = sorted({f["fingerprint"] for f in findings})
    doc = {
        "schema": SCHEMA,
        "note": note or (
            "Known SPMD audit findings, frozen so CI fails only on "
            "NEW ones. Regenerate: python -m "
            "distributed_training_tpu.analysis --write-baseline"),
        "fingerprints": fps,
        "messages": {
            f["fingerprint"]: f["message"]
            for f in sorted(findings, key=lambda x: x["fingerprint"])},
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path
