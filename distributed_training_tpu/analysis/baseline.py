"""Baseline ratchet for SPMD audit findings.

The committed ``spmd_baseline.json`` freezes the KNOWN findings (by
fingerprint) so the check gate fails only on new ones: the tp+sp+fsdp
dryrun's involuntary-reshard warnings are real, documented, and owned
by ROADMAP item 1 — they must not make every CI run red, but nothing
NEW may hide behind them. Stale entries (baselined fingerprints no
run reproduces anymore) are reported for burn-down, never failed on:
a fixed finding should shrink the baseline at the author's next
``--write-baseline``, not break the build for being an improvement.

No jax imports here — the ratchet arithmetic is unit-tested without a
compile in sight.
"""

from __future__ import annotations

import json
import os

SCHEMA = 1

DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "spmd_baseline.json")

# Static comms/compute overlap ratchet (telemetry/attribution.py
# scores, per audit target). Unlike the findings ratchet — which lets
# KNOWN debt ride — this one pins a FLOOR: the committed score is the
# worst the gate accepts, improvements raise it at the next
# --write-baseline, regressions fail. A target's ``min_overlap`` pin
# outranks the baseline AND --write-baseline (the pin_zero rule):
# a regressed score below the pin cannot be laundered into a new
# baseline.
OVERLAP_SCHEMA = 1
OVERLAP_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "OVERLAP_baseline.json")


def load(path: str | None = None) -> dict:
    """The baseline doc ({"schema": 1, "fingerprints": [...]});
    a missing file is an EMPTY baseline — every finding is new."""
    path = path or DEFAULT_PATH
    if not os.path.exists(path):
        return {"schema": SCHEMA, "fingerprints": []}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA:
        raise ValueError(
            f"baseline {path} has schema {doc.get('schema')!r}, "
            f"expected {SCHEMA} — regenerate with --write-baseline")
    return doc


def compare(findings: list[dict], baseline_doc: dict,
            targets: list[str] | None = None) -> dict:
    """Split current findings against the baseline.

    Returns ``{"new": [finding, ...], "known": [finding, ...],
    "stale": [fingerprint, ...]}`` — ``new`` is what fails the gate,
    ``stale`` is baseline debt that no longer reproduces. With
    ``targets`` (a subset audit run), baseline entries for OTHER
    targets are ignored: they were not re-audited, so calling them
    stale would misread "not checked" as "fixed"."""
    base = set(baseline_doc.get("fingerprints", ()))
    if targets is not None:
        tset = set(targets)
        # Fingerprint format: "CODE:<target>:<detail...>".
        base = {fp for fp in base
                if len(fp.split(":", 2)) == 3
                and fp.split(":", 2)[1] in tset}
    seen = {f["fingerprint"] for f in findings}
    return {
        "new": [f for f in findings if f["fingerprint"] not in base],
        "known": [f for f in findings if f["fingerprint"] in base],
        "stale": sorted(base - seen),
    }


def write(findings: list[dict], path: str | None = None,
          note: str = "") -> str:
    """Freeze the given findings as the new baseline (sorted, deduped,
    with messages alongside for the reviewer — only ``fingerprints``
    is load-bearing)."""
    path = path or DEFAULT_PATH
    fps = sorted({f["fingerprint"] for f in findings})
    doc = {
        "schema": SCHEMA,
        "note": note or (
            "Known SPMD audit findings, frozen so CI fails only on "
            "NEW ones. Regenerate: python -m "
            "distributed_training_tpu.analysis --write-baseline"),
        "fingerprints": fps,
        "messages": {
            f["fingerprint"]: f["message"]
            for f in sorted(findings, key=lambda x: x["fingerprint"])},
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# overlap ratchet
# ---------------------------------------------------------------------------


def load_overlap(path: str | None = None) -> dict:
    """The overlap baseline ({"schema": 1, "targets": {name:
    {"overlap_score": x, "scored": n}}}); missing file = empty —
    nothing is gated until a baseline is written."""
    path = path or OVERLAP_PATH
    if not os.path.exists(path):
        return {"schema": OVERLAP_SCHEMA, "targets": {}}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != OVERLAP_SCHEMA:
        raise ValueError(
            f"overlap baseline {path} has schema "
            f"{doc.get('schema')!r}, expected {OVERLAP_SCHEMA} — "
            "regenerate with --write-baseline")
    return doc


def _overlap_rows(audit_doc: dict) -> dict[str, dict]:
    return {r["target"]: (r.get("overlap") or {})
            for r in audit_doc.get("targets", [])}


def compare_overlap(audit_doc: dict, baseline_doc: dict,
                    min_overlap: dict[str, float] | None = None
                    ) -> list[str]:
    """Ratchet check: one problem string per regression. A target's
    current score must be >= its baselined score, and >= its
    ``min_overlap`` pin regardless of what the baseline says. A
    target whose collectives all vanished from scoring (score None)
    against a numeric baseline is a regression too — the overlap
    evidence disappeared, which is exactly what a schedule-destroying
    change looks like."""
    min_overlap = min_overlap or {}
    base = baseline_doc.get("targets", {})
    problems: list[str] = []
    for name, ov in _overlap_rows(audit_doc).items():
        cur = ov.get("overlap_score")
        pin = min_overlap.get(name)
        if pin is not None and (cur is None or cur < pin):
            problems.append(
                f"{name}: overlap score "
                f"{'none' if cur is None else f'{cur:.3f}'} is below "
                f"this target's min_overlap pin {pin:.3f} (pins "
                "outrank the baseline — a destroyed schedule cannot "
                "be baselined in)")
            continue
        b = base.get(name, {}).get("overlap_score")
        if b is None:
            continue  # not gated until baselined
        if cur is None or cur < b:
            problems.append(
                f"{name}: overlap score "
                f"{'none' if cur is None else f'{cur:.3f}'} regressed "
                f"below the OVERLAP_baseline.json floor {b:.3f} "
                f"({ov.get('scored', 0)} collective(s) scored)")
    return problems


def write_overlap(audit_doc: dict, path: str | None = None,
                  min_overlap: dict[str, float] | None = None,
                  allow_lower: bool = False) -> str:
    """Freeze current per-target overlap scores as the new floor.

    Refuses to freeze a score below a target's ``min_overlap`` pin
    (--write-baseline must not launder a destroyed schedule), and —
    unless ``allow_lower`` — refuses to LOWER a previously raised
    floor: the ratchet only tightens by default, so a regression
    can't ride a routine baseline regen into the committed file. An
    intentional slackening (a known schedule trade-off) passes
    ``allow_lower`` explicitly (CLI: ``--lower-overlap-floor``) and
    still cannot cross a pin."""
    min_overlap = min_overlap or {}
    prior = load_overlap(path).get("targets", {})
    targets: dict[str, dict] = {}
    for name, ov in _overlap_rows(audit_doc).items():
        cur = ov.get("overlap_score")
        pin = min_overlap.get(name)
        if pin is not None and (cur is None or cur < pin):
            raise ValueError(
                f"refusing to baseline {name} at overlap score "
                f"{'none' if cur is None else f'{cur:.3f}'}: below "
                f"its min_overlap pin {pin:.3f}")
        floor = prior.get(name, {}).get("overlap_score")
        if (not allow_lower and floor is not None
                and (cur is None or cur < floor)):
            raise ValueError(
                f"refusing to LOWER {name}'s overlap floor from "
                f"{floor:.3f} to "
                f"{'none' if cur is None else f'{cur:.3f}'}: the "
                "ratchet only tightens — pass --lower-overlap-floor "
                "for an intentional slackening")
        targets[name] = {"overlap_score": cur,
                         "scored": ov.get("scored", 0)}
    if not allow_lower:
        # A target VANISHING from the audit (plan file absent mid-
        # replan, target deregistered) must not silently erase its
        # raised floor — dropping a baselined row is a lowering too.
        dropped = [n for n, row in prior.items()
                   if n not in targets
                   and row.get("overlap_score") is not None]
        if dropped:
            raise ValueError(
                f"refusing to DROP baselined overlap floor(s) for "
                f"{sorted(dropped)}: the target(s) were not audited "
                "this run — audit them, or pass "
                "--lower-overlap-floor to remove them deliberately")
    path = path or OVERLAP_PATH
    doc = {
        "schema": OVERLAP_SCHEMA,
        "note": "Per-target static comms/compute overlap floors "
                "(telemetry/attribution.py hlo_overlap_report). The "
                "gate fails when a target's score drops below its "
                "floor. Regenerate: python -m "
                "distributed_training_tpu.analysis --write-baseline",
        "targets": targets,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path
