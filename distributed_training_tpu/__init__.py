"""distributed_training_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
repo ``erfanMhi/distributed_training`` (a Hydra-driven torch DDP/FSDP
trainer; see SURVEY.md): config-driven training with pluggable parallelism
strategies, deterministic per-process data sharding, checkpoint/resume,
a pedagogical "DDP from collective primitives" playground, and pod-level
launch automation — expressed TPU-first:

- one jitted train step whose parallelism is a *sharding layout* over a
  ``jax.sharding.Mesh`` (axes ``dp``/``fsdp``/``tp``/``sp``/``ep``), with
  XLA-compiled collectives over ICI/DCN replacing NCCL/Gloo
  (reference: src/distributed_trainer.py:61, src/dist_strategy/*),
- ``jax.distributed`` rendezvous replacing torchrun
  (reference: infrastructure/nebius/cluster/scripts/cloud-init.tftpl:61-77),
- Orbax sharded checkpointing replacing ``torch.save`` snapshots
  (reference: src/dist_strategy/{ddp,fsdp}_strategy.py),
- Pallas kernels (flash attention) + ring-attention sequence parallelism
  for the long-context path the transformer targets require.
"""

__version__ = "0.1.0"

from distributed_training_tpu.config import (  # noqa: F401
    Config,
    load_config,
)
from distributed_training_tpu.runtime import (  # noqa: F401
    MeshSpec,
    Runtime,
    initialize_runtime,
)
