"""Local multi-process launcher.

Spawns ``num_processes`` OS processes on this machine, each a full
"host" in a ``jax.distributed`` cluster rendezvousing at a local TCP
coordinator — the counterpart of ``mp.spawn(train, nprocs=ws)`` +
``MASTER_ADDR=localhost:12355`` in the reference playground
(src/playground/ddp_script.py:39-48,254-256) and of torchrun's local
mode. Each child gets ``DTT_COORDINATOR`` / ``DTT_NUM_PROCESSES`` /
``DTT_PROCESS_ID``, which ``runtime._maybe_init_distributed`` consumes.

Children default to the CPU platform with a configurable number of fake
devices per process, so an 8-"chip" 2-host pod is simulated as
``launch_local(["-m", "distributed_training_tpu.train"], 2,
devices_per_process=4)`` on any machine.
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass

from distributed_training_tpu.resilience.elastic import GroupReport

logger = logging.getLogger(__name__)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Exported per spawn attempt (see ``run_group``): which port-retry
# attempt a child belongs to. Production children ignore it; tests use
# it to script a first-attempt bind failure.
ENV_PORT_ATTEMPT = "DTT_PORT_ATTEMPT"

# What a jax coordinator whose TCP port was stolen between our
# ``_free_port`` probe and its own bind prints before dying — the
# TOCTOU race ``run_group`` retries with a fresh port. Both the errno
# string (grpc/absl) and the grpc status text, either casing.
_BIND_FAILURE_MARKERS = ("Address already in use",
                         "ADDRESS_IN_USE",
                         "Failed to bind to address")


def _free_port(attempts: int = 8) -> int:
    """Pick a free TCP port (bounded retry).

    The bind-then-close probe is inherently TOCTOU — another process
    can take the port between our close and the coordinator child's
    bind seconds later. The retry here only covers probe-time failures
    (ephemeral-range exhaustion); the coordinator-side half of the
    race is handled by ``run_group``, which relaunches the group on a
    fresh port when the coordinator's log shows a bind failure."""
    last: OSError | None = None
    for attempt in range(attempts):
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]
        except OSError as e:  # ephemeral ports exhausted: back off
            last = e
            time.sleep(0.05 * (attempt + 1))
    raise RuntimeError(
        f"could not acquire a coordinator port after {attempts} "
        f"attempts: {last}")


@dataclass
class LocalProcess:
    process_id: int
    proc: subprocess.Popen
    log_path: str | None


def launch_local(
    argv: list[str],
    num_processes: int,
    devices_per_process: int = 1,
    log_dir: str | None = None,
    env: dict[str, str] | None = None,
    coordinator_port: int | None = None,
) -> list[LocalProcess]:
    """Spawn the local process group; returns handles (non-blocking).

    ``argv`` is everything after ``python`` (e.g. ``["-m",
    "distributed_training_tpu.train", "train.total_epochs=2"]``).
    Per-process logs go to ``log_dir/proc_<i>.log`` when given —
    mirroring the reference playground's per-rank log files
    (ddp_script.py:74).
    """
    port = coordinator_port or _free_port()
    procs: list[LocalProcess] = []
    for pid in range(num_processes):
        child_env = dict(os.environ)
        child_env.update(env or {})
        platform = (env or {}).get("JAX_PLATFORMS", "cpu")
        child_env.update({
            "DTT_COORDINATOR": f"127.0.0.1:{port}",
            "DTT_NUM_PROCESSES": str(num_processes),
            "DTT_PROCESS_ID": str(pid),
            "JAX_PLATFORMS": platform,
            "XLA_FLAGS": (
                child_env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count="
                  f"{devices_per_process}").strip(),
        })
        if platform == "cpu":
            # Hardware plugins registered by site customizations at
            # interpreter startup would steal the platform from the
            # simulated hosts; make sure children stay on CPU.
            for var in ("PALLAS_AXON_POOL_IPS", "TPU_SKIP_MDS_QUERY"):
                child_env.pop(var, None)
        log_path = None
        stdout = None
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, f"proc_{pid}.log")
            stdout = open(log_path, "w")
        try:
            proc = subprocess.Popen(
                [sys.executable, *argv], env=child_env,
                stdout=stdout,
                stderr=subprocess.STDOUT if stdout else None)
        finally:
            if stdout is not None:
                stdout.close()  # child holds its own descriptor
        procs.append(LocalProcess(pid, proc, log_path))
    return procs


# Set by _forward_signals' handler: the LAUNCHER itself was told to
# stop. The supervisor checks it so a preempted launcher tears down
# (clean child saves, then exit) instead of restarting the job the
# infrastructure just asked it to release.
_launcher_signaled: bool = False


@contextlib.contextmanager
def _forward_signals(procs: list[LocalProcess],
                     signums=(signal.SIGTERM, signal.SIGINT)):
    """While waiting, forward SIGTERM/SIGINT to the children instead
    of dying around them: when the LAUNCHER is preempted, the workers'
    ``PreemptionGuard`` must still fire (clean final save) — without
    the forward, the launcher exits and the orphaned workers never see
    the signal. The handler only forwards; teardown happens naturally
    when the (now cleanly exiting) children are reaped. No-op when not
    on the main thread (signal.signal would raise there)."""
    def handler(signum, frame):
        del frame
        global _launcher_signaled
        _launcher_signaled = True
        logger.warning("launcher got %s — forwarding to %d child "
                       "process(es)", signal.Signals(signum).name,
                       len(procs))
        for lp in procs:
            if lp.proc.poll() is None:
                try:
                    lp.proc.send_signal(signum)
                except (ProcessLookupError, OSError):
                    continue  # already reaped/exiting

    prev: dict[int, object] = {}
    try:
        for s in signums:
            prev[s] = signal.signal(s, handler)
    except ValueError:  # not the main thread: nothing to forward
        yield
        return
    try:
        yield
    finally:
        for s, p in prev.items():
            signal.signal(s, p)


def wait(procs: list[LocalProcess], timeout: float | None = None) -> int:
    """Wait for all processes; kill the group on first failure (the
    fail-fast behavior torchrun provides). Returns max exit code.
    SIGTERM/SIGINT delivered to the launcher while waiting are
    forwarded to the children first (see ``_forward_signals``)."""
    return wait_report(procs, timeout).returncode


def wait_report(procs: list[LocalProcess],
                timeout: float | None = None) -> GroupReport:
    """Like ``wait`` but returns the full ``GroupReport``: which
    processes failed on their own vs. were killed in the fail-fast
    sweep. The distinction is what lets the elastic supervisor tell
    "host 2 died under the others" (shrink and continue) from
    "everything crashed" (retry)."""
    with _forward_signals(procs):
        return _wait_inner(procs, timeout)


def _wait_inner(procs: list[LocalProcess],
                timeout: float | None = None) -> GroupReport:
    deadline = None if timeout is None else time.monotonic() + timeout
    pending = list(procs)
    worst = 0
    killed_ids: set[int] = set()
    self_failed: list[int] = []
    killed: list[int] = []
    completed: list[int] = []
    while pending:
        for lp in list(pending):
            budget = None
            if deadline is not None:
                budget = max(0.0, deadline - time.monotonic())
            try:
                code = lp.proc.wait(timeout=0.2 if budget is None
                                    else min(0.2, budget or 0.01))
            except subprocess.TimeoutExpired:
                if deadline is not None and time.monotonic() >= deadline:
                    for other in pending:
                        other.proc.kill()
                    raise TimeoutError(
                        f"local launch timed out after {timeout}s; "
                        f"pending={[p.process_id for p in pending]}")
                continue
            pending.remove(lp)
            if code == 0:
                completed.append(lp.process_id)
                continue
            if lp.process_id in killed_ids:
                # Died because WE killed it in the fail-fast sweep —
                # a consequence of the first failure, not a cause.
                killed.append(lp.process_id)
                continue
            self_failed.append(lp.process_id)
            if worst == 0:
                # Signal deaths are negative Popen returncodes; report
                # them as failures, not max(0, -11) == 0.
                worst = code if code > 0 else 128 - code
            logger.error(
                "process %d exited %d%s — killing group",
                lp.process_id, code,
                f" (log: {lp.log_path})" if lp.log_path else "")
            for other in pending:
                # Only count a process as launcher-killed if it was
                # still ALIVE at sweep time: in a whole-group crash
                # (every host hits the same fault) the siblings are
                # already dead with their own exit codes when the
                # first reap triggers the sweep, and marking them
                # "killed" would make the group read as a strict-
                # subset host loss — the elastic policy would shrink
                # around a crash that must burn retry budget.
                if other.proc.poll() is None:
                    killed_ids.add(other.process_id)
                    other.proc.kill()
    return GroupReport(returncode=worst, world_size=len(procs),
                       self_failed=tuple(sorted(self_failed)),
                       killed=tuple(sorted(killed)),
                       completed=tuple(sorted(completed)))


def coordinator_bind_failed(procs: list[LocalProcess]) -> bool:
    """Did this (failed) group die because the coordinator lost the
    ``_free_port`` TOCTOU race? Only readable when the group ran with
    a log_dir (the launcher paths all do). Scoped to PROCESS 0's log —
    the coordinator is the process that binds the port; a generic
    "address in use" string in some other child's crash traceback
    (e.g. an unrelated service port) must not be misread as the race
    and burn relaunch attempts on a deterministic crash."""
    lp = next((p for p in procs if p.process_id == 0), None)
    if lp is None or lp.log_path is None:
        return False
    try:
        with open(lp.log_path, errors="replace") as f:
            # A bind failure happens at STARTUP — the marker is in
            # the first lines; never slurp a long run's whole log.
            text = f.read(65536)
    except OSError:
        return False
    return any(m in text for m in _BIND_FAILURE_MARKERS)


def run_group(argv: list[str], num_processes: int,
              devices_per_process: int = 1,
              log_dir: str | None = None,
              env: dict[str, str] | None = None,
              timeout: float | None = None,
              port_attempts: int = 3,
              on_procs=None) -> GroupReport:
    """Launch + wait, retrying the whole group on a fresh port when
    the coordinator's bind lost the ``_free_port`` TOCTOU race —
    bounded, so a genuinely unbindable environment still fails. Every
    attempt exports ``DTT_PORT_ATTEMPT`` so a retry is observable (and
    scriptable by tests). ``on_procs`` (procs -> optional cleanup
    callable) lets a caller attach a watcher to the live group —
    the elastic grow watcher rides this."""
    report = GroupReport(returncode=1, world_size=num_processes)
    for attempt in range(max(1, port_attempts)):
        attempt_env = dict(env or {})
        attempt_env[ENV_PORT_ATTEMPT] = str(attempt)
        procs = launch_local(argv, num_processes, devices_per_process,
                             log_dir=log_dir, env=attempt_env)
        cleanup = on_procs(procs) if on_procs is not None else None
        try:
            report = wait_report(procs, timeout)
        finally:
            if cleanup is not None:
                cleanup()
        if report.returncode == 0:
            return report
        if (attempt + 1 >= max(1, port_attempts)
                or not coordinator_bind_failed(procs)):
            return report
        logger.warning(
            "coordinator port bind failed (TOCTOU race); retrying "
            "the group on a fresh port (attempt %d/%d)",
            attempt + 2, port_attempts)
    return report


def apply_overlap_flags_from_cmd(cmd: list[str],
                                 platform: str = "cpu") -> list[str]:
    """Scheduled comms/compute overlap for launched children: when
    the train command pins a sharding plan
    (``train.sharding_plan=<name|path>``), derive the plan's XLA
    latency-hiding flags (``parallel/overlap.py``) and append them to
    this process's ``XLA_FLAGS`` — ``launch_local`` builds every
    child's env from it, so the whole simulated pod compiles the
    scheduled program. Raw-JSON read, no planner import: a bad plan
    stays the CHILD CLI's loud failure, not a launcher crash. Returns
    the applied flag names (empty when no plan is pinned, the command
    disables ``train.xla_overlap_flags``, or everything was already
    set)."""
    import yaml
    plan_ref = None
    enabled = True
    for arg in cmd:
        if arg.startswith("train.sharding_plan="):
            plan_ref = arg.split("=", 1)[1]
        elif arg.startswith("train.xla_overlap_flags="):
            # Parse the override exactly as the child's config layer
            # will (yaml.safe_load — 'off'/'False'/'no' are False,
            # '0' is a falsy int the bool field keeps), and with the
            # same LAST-WINS semantics over repeated overrides: the
            # launcher must reach the same verdict the child's
            # resolved config does.
            try:
                enabled = bool(yaml.safe_load(arg.split("=", 1)[1]))
            except yaml.YAMLError:
                pass  # the child CLI owns the loud parse failure
    if not plan_ref or not enabled:
        return []
    path = plan_ref if os.path.exists(plan_ref) else os.path.join(
        _REPO, "conf", "plans", f"{plan_ref}.json")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []  # child CLI owns the loud plan-load failure
    from distributed_training_tpu.parallel import overlap
    applied = overlap.apply_to_env(
        overlap.flags_for_plan_doc(doc, platform))
    if applied:
        logger.info("comms/compute overlap: applied XLA flags %s "
                    "for plan %s", applied, doc.get("name", path))
    return applied


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="dtt-launch-local",
        description="Simulate a multi-host TPU pod with local processes")
    p.add_argument("--nproc", type=int, default=2)
    p.add_argument("--devices-per-proc", type=int, default=4)
    p.add_argument("--log-dir", default="outputs/local_launch")
    p.add_argument("--summarize", default=None, metavar="RUN_DIR",
                   help="after a clean exit, render the run dir's "
                        "merged cross-host telemetry report (each "
                        "simulated host writes host_<i>/events.jsonl; "
                        "see docs/observability.md)")
    p.add_argument("--supervise", action="store_true",
                   help="restart dead training processes with backoff "
                        "(resilience/supervisor.py): exits are "
                        "classified (completed/preempted/watchdog-"
                        "abort/crash) and a restart that advances the "
                        "checkpoint refunds the retry budget, so a "
                        "crash-loop gives up fast — docs/robustness.md")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="retry budget between checkpoint advances")
    p.add_argument("--backoff-base-s", type=float, default=1.0,
                   help="first restart delay; doubles per consecutive "
                        "non-advancing failure (jittered, capped)")
    p.add_argument("--ckpt-dir", default=None, metavar="DIR",
                   help="checkpoint dir to watch for progress-based "
                        "budget refunds (pass the run's "
                        "train.snapshot_path; without it every "
                        "failure burns budget)")
    p.add_argument("--elastic", action="store_true",
                   help="with --supervise: on a lost or evicted host, "
                        "re-form the job at the surviving world size "
                        "(resharded restore + rescaled per-host batch "
                        "via train.global_batch_size) instead of "
                        "retrying at full size, then grow back at a "
                        "checkpoint boundary — docs/robustness.md "
                        "'Elastic runs'")
    p.add_argument("--elastic-min-world", type=int, default=1,
                   help="never shrink below this many processes")
    p.add_argument("--elastic-grow-after-ckpts", type=int, default=1,
                   help="checkpoints a shrunken world must commit "
                        "before growing back (doubles per flap)")
    p.add_argument("--elastic-no-grow", action="store_true",
                   help="stay at the shrunken size for the rest of "
                        "the run")
    p.add_argument("--no-overlap-flags", action="store_true",
                   help="do not derive XLA latency-hiding-scheduler "
                        "flags from a train.sharding_plan= override "
                        "in the command (docs/performance.md "
                        "'Scheduled comms/compute overlap')")
    p.add_argument("--metrics-port", type=int, default=0,
                   metavar="PORT",
                   help="serve the coordinator's live Prometheus "
                        "endpoint (/metrics, /healthz) on this port "
                        "— appends train.metrics_port=PORT to the "
                        "train command (coordinator-gated there; see "
                        "docs/observability.md)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- followed by the python argv to run")
    args = p.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]
    if not cmd:
        cmd = ["-m", "distributed_training_tpu.train"]
    if args.metrics_port:
        cmd = cmd + [f"train.metrics_port={args.metrics_port}"]
    if args.elastic and not args.supervise:
        p.error("--elastic requires --supervise")
    if not args.no_overlap_flags:
        # Children default to the CPU platform (launch_local) unless
        # the caller's env says otherwise.
        from distributed_training_tpu.parallel import overlap
        apply_overlap_flags_from_cmd(
            cmd, platform=overlap.platform_from_env("cpu"))
    if args.supervise:
        rc = _supervised_main(args, cmd)
    else:
        rc = run_group(cmd, args.nproc, args.devices_per_proc,
                       log_dir=args.log_dir).returncode
    if rc == 0 and args.summarize:
        from distributed_training_tpu.telemetry import summarize
        summarize.main([args.summarize])
    return rc


class _GrowWatcher:
    """Signals a SHRUNKEN incarnation down at a checkpoint boundary so
    the supervisor can re-form at full size — the grow-back half of
    elastic training. Polls the checkpoint dir; once ``needed`` NEW
    steps have been committed since the incarnation started (the
    hysteresis dwell the supervisor computed), delivers SIGTERM to the
    group: the PreemptionGuard clean-save path runs, the incarnation
    exits ``preempted``, and the relaunch at base size restores the
    just-saved checkpoint. Never an in-band kill."""

    def __init__(self, procs: list[LocalProcess], ckpt_dir: str,
                 needed: int, poll_s: float = 0.3):
        from distributed_training_tpu.resilience.integrity import (
            checkpoint_steps_on_disk)
        self._scan = checkpoint_steps_on_disk
        self.procs = procs
        self.ckpt_dir = ckpt_dir
        self.needed = max(1, needed)
        self.poll_s = poll_s
        self.triggered = False
        self._baseline = set(self._scan(ckpt_dir))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="elastic-grow",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            new = set(self._scan(self.ckpt_dir)) - self._baseline
            if len(new) >= self.needed:
                if any(lp.proc.poll() is not None
                       for lp in self.procs):
                    # The group is already exiting (the dwell was met
                    # by the run's FINAL checkpoint, or a failure is
                    # mid-teardown): signaling now would relabel a
                    # completed run as preempted and waste a grow
                    # incarnation — the supervisor handles whatever
                    # boundary this turns out to be.
                    return
                self.triggered = True
                logger.warning(
                    "elastic: capacity available and %d new "
                    "checkpoint(s) committed at reduced size — "
                    "signaling the group down for grow-back",
                    len(new))
                for lp in self.procs:
                    if lp.proc.poll() is None:
                        try:
                            lp.proc.send_signal(signal.SIGTERM)
                        except (ProcessLookupError, OSError):
                            continue
                return
            self._stop.wait(self.poll_s)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def _supervised_main(args, cmd: list[str]) -> int:
    """``--supervise``: run incarnations of the local process group
    under the restart supervisor. Supervisor state (exit sentinels,
    its own event stream) lives under ``<log_dir>/supervisor/``; each
    incarnation's per-process logs go to ``<log_dir>/attempt_<i>/``,
    next to a ``summary.json`` recording its outcome and topology
    (world size, evicted hosts) for postmortems."""
    from distributed_training_tpu.resilience import elastic as elastic_mod
    from distributed_training_tpu.resilience import supervisor as sup
    from distributed_training_tpu.telemetry import Telemetry
    state_dir = os.path.join(args.log_dir, "supervisor")
    tel = Telemetry(
        events_jsonl=os.path.join(state_dir, "events.jsonl"),
        fresh=False)
    elastic_policy = None
    if args.elastic:
        elastic_policy = elastic_mod.ElasticPolicy(
            base_world=args.nproc,
            min_world=args.elastic_min_world,
            grow=not args.elastic_no_grow,
            grow_after_ckpts=args.elastic_grow_after_ckpts)

    def run_incarnation(extra_env: dict[str, str]):
        attempt = extra_env.get(sup.ENV_RESTART_COUNT, "0")
        nproc = int(extra_env.get(elastic_mod.ENV_WORLD)
                    or args.nproc)
        grow_after = extra_env.get(elastic_mod.ENV_GROW_AFTER_CKPTS)
        watchers: list[_GrowWatcher] = []

        def on_procs(procs):
            if grow_after is None or not args.ckpt_dir:
                return None
            w = _GrowWatcher(procs, args.ckpt_dir, int(grow_after))
            watchers.append(w)
            return w.stop

        report = run_group(
            cmd, nproc, args.devices_per_proc,
            log_dir=os.path.join(args.log_dir, f"attempt_{attempt}"),
            env=extra_env, on_procs=on_procs)
        if any(w.triggered for w in watchers):
            report = dataclasses.replace(report, grow_requested=True)
        return report

    def on_incident(incident: sup.Incident) -> None:
        # Per-attempt summary next to its process logs: outcome +
        # resolved topology, so a postmortem can read the world-size
        # history straight off the attempt dirs.
        d = os.path.join(args.log_dir,
                         f"attempt_{incident.incarnation}")
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, "summary.json.tmp")
        with open(tmp, "w") as f:
            json.dump(dataclasses.asdict(incident), f, indent=1)
        os.replace(tmp, os.path.join(d, "summary.json"))

    try:
        result = sup.supervise(
            run_incarnation,
            policy=sup.RestartPolicy(
                max_restarts=args.max_restarts,
                backoff_base_s=args.backoff_base_s),
            state_dir=state_dir,
            ckpt_dir=args.ckpt_dir,
            telemetry=tel,
            should_stop=lambda: _launcher_signaled,
            elastic=elastic_policy,
            on_incident=on_incident)
    finally:
        tel.close()
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
