"""Local multi-process launcher.

Spawns ``num_processes`` OS processes on this machine, each a full
"host" in a ``jax.distributed`` cluster rendezvousing at a local TCP
coordinator — the counterpart of ``mp.spawn(train, nprocs=ws)`` +
``MASTER_ADDR=localhost:12355`` in the reference playground
(src/playground/ddp_script.py:39-48,254-256) and of torchrun's local
mode. Each child gets ``DTT_COORDINATOR`` / ``DTT_NUM_PROCESSES`` /
``DTT_PROCESS_ID``, which ``runtime._maybe_init_distributed`` consumes.

Children default to the CPU platform with a configurable number of fake
devices per process, so an 8-"chip" 2-host pod is simulated as
``launch_local(["-m", "distributed_training_tpu.train"], 2,
devices_per_process=4)`` on any machine.
"""

from __future__ import annotations

import argparse
import contextlib
import logging
import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass

logger = logging.getLogger(__name__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class LocalProcess:
    process_id: int
    proc: subprocess.Popen
    log_path: str | None


def launch_local(
    argv: list[str],
    num_processes: int,
    devices_per_process: int = 1,
    log_dir: str | None = None,
    env: dict[str, str] | None = None,
    coordinator_port: int | None = None,
) -> list[LocalProcess]:
    """Spawn the local process group; returns handles (non-blocking).

    ``argv`` is everything after ``python`` (e.g. ``["-m",
    "distributed_training_tpu.train", "train.total_epochs=2"]``).
    Per-process logs go to ``log_dir/proc_<i>.log`` when given —
    mirroring the reference playground's per-rank log files
    (ddp_script.py:74).
    """
    port = coordinator_port or _free_port()
    procs: list[LocalProcess] = []
    for pid in range(num_processes):
        child_env = dict(os.environ)
        child_env.update(env or {})
        platform = (env or {}).get("JAX_PLATFORMS", "cpu")
        child_env.update({
            "DTT_COORDINATOR": f"127.0.0.1:{port}",
            "DTT_NUM_PROCESSES": str(num_processes),
            "DTT_PROCESS_ID": str(pid),
            "JAX_PLATFORMS": platform,
            "XLA_FLAGS": (
                child_env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count="
                  f"{devices_per_process}").strip(),
        })
        if platform == "cpu":
            # Hardware plugins registered by site customizations at
            # interpreter startup would steal the platform from the
            # simulated hosts; make sure children stay on CPU.
            for var in ("PALLAS_AXON_POOL_IPS", "TPU_SKIP_MDS_QUERY"):
                child_env.pop(var, None)
        log_path = None
        stdout = None
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, f"proc_{pid}.log")
            stdout = open(log_path, "w")
        try:
            proc = subprocess.Popen(
                [sys.executable, *argv], env=child_env,
                stdout=stdout,
                stderr=subprocess.STDOUT if stdout else None)
        finally:
            if stdout is not None:
                stdout.close()  # child holds its own descriptor
        procs.append(LocalProcess(pid, proc, log_path))
    return procs


# Set by _forward_signals' handler: the LAUNCHER itself was told to
# stop. The supervisor checks it so a preempted launcher tears down
# (clean child saves, then exit) instead of restarting the job the
# infrastructure just asked it to release.
_launcher_signaled: bool = False


@contextlib.contextmanager
def _forward_signals(procs: list[LocalProcess],
                     signums=(signal.SIGTERM, signal.SIGINT)):
    """While waiting, forward SIGTERM/SIGINT to the children instead
    of dying around them: when the LAUNCHER is preempted, the workers'
    ``PreemptionGuard`` must still fire (clean final save) — without
    the forward, the launcher exits and the orphaned workers never see
    the signal. The handler only forwards; teardown happens naturally
    when the (now cleanly exiting) children are reaped. No-op when not
    on the main thread (signal.signal would raise there)."""
    def handler(signum, frame):
        del frame
        global _launcher_signaled
        _launcher_signaled = True
        logger.warning("launcher got %s — forwarding to %d child "
                       "process(es)", signal.Signals(signum).name,
                       len(procs))
        for lp in procs:
            if lp.proc.poll() is None:
                try:
                    lp.proc.send_signal(signum)
                except (ProcessLookupError, OSError):
                    continue  # already reaped/exiting

    prev: dict[int, object] = {}
    try:
        for s in signums:
            prev[s] = signal.signal(s, handler)
    except ValueError:  # not the main thread: nothing to forward
        yield
        return
    try:
        yield
    finally:
        for s, p in prev.items():
            signal.signal(s, p)


def wait(procs: list[LocalProcess], timeout: float | None = None) -> int:
    """Wait for all processes; kill the group on first failure (the
    fail-fast behavior torchrun provides). Returns max exit code.
    SIGTERM/SIGINT delivered to the launcher while waiting are
    forwarded to the children first (see ``_forward_signals``)."""
    with _forward_signals(procs):
        return _wait_inner(procs, timeout)


def _wait_inner(procs: list[LocalProcess],
                timeout: float | None = None) -> int:
    deadline = None if timeout is None else time.monotonic() + timeout
    pending = list(procs)
    worst = 0
    while pending:
        for lp in list(pending):
            budget = None
            if deadline is not None:
                budget = max(0.0, deadline - time.monotonic())
            try:
                code = lp.proc.wait(timeout=0.2 if budget is None
                                    else min(0.2, budget or 0.01))
            except subprocess.TimeoutExpired:
                if deadline is not None and time.monotonic() >= deadline:
                    for other in pending:
                        other.proc.kill()
                    raise TimeoutError(
                        f"local launch timed out after {timeout}s; "
                        f"pending={[p.process_id for p in pending]}")
                continue
            pending.remove(lp)
            if code != 0 and worst == 0:
                # Signal deaths are negative Popen returncodes; report
                # them as failures, not max(0, -11) == 0.
                worst = code if code > 0 else 128 - code
            if code != 0:
                logger.error(
                    "process %d exited %d%s — killing group",
                    lp.process_id, code,
                    f" (log: {lp.log_path})" if lp.log_path else "")
                for other in pending:
                    other.proc.kill()
    return worst


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="dtt-launch-local",
        description="Simulate a multi-host TPU pod with local processes")
    p.add_argument("--nproc", type=int, default=2)
    p.add_argument("--devices-per-proc", type=int, default=4)
    p.add_argument("--log-dir", default="outputs/local_launch")
    p.add_argument("--summarize", default=None, metavar="RUN_DIR",
                   help="after a clean exit, render the run dir's "
                        "merged cross-host telemetry report (each "
                        "simulated host writes host_<i>/events.jsonl; "
                        "see docs/observability.md)")
    p.add_argument("--supervise", action="store_true",
                   help="restart dead training processes with backoff "
                        "(resilience/supervisor.py): exits are "
                        "classified (completed/preempted/watchdog-"
                        "abort/crash) and a restart that advances the "
                        "checkpoint refunds the retry budget, so a "
                        "crash-loop gives up fast — docs/robustness.md")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="retry budget between checkpoint advances")
    p.add_argument("--backoff-base-s", type=float, default=1.0,
                   help="first restart delay; doubles per consecutive "
                        "non-advancing failure (jittered, capped)")
    p.add_argument("--ckpt-dir", default=None, metavar="DIR",
                   help="checkpoint dir to watch for progress-based "
                        "budget refunds (pass the run's "
                        "train.snapshot_path; without it every "
                        "failure burns budget)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- followed by the python argv to run")
    args = p.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]
    if not cmd:
        cmd = ["-m", "distributed_training_tpu.train"]
    if args.supervise:
        rc = _supervised_main(args, cmd)
    else:
        procs = launch_local(cmd, args.nproc, args.devices_per_proc,
                             log_dir=args.log_dir)
        rc = wait(procs)
    if rc == 0 and args.summarize:
        from distributed_training_tpu.telemetry import summarize
        summarize.main([args.summarize])
    return rc


def _supervised_main(args, cmd: list[str]) -> int:
    """``--supervise``: run incarnations of the local process group
    under the restart supervisor. Supervisor state (exit sentinels,
    its own event stream) lives under ``<log_dir>/supervisor/``; each
    incarnation's per-process logs go to ``<log_dir>/attempt_<i>/``."""
    from distributed_training_tpu.resilience import supervisor as sup
    from distributed_training_tpu.telemetry import Telemetry
    state_dir = os.path.join(args.log_dir, "supervisor")
    tel = Telemetry(
        events_jsonl=os.path.join(state_dir, "events.jsonl"),
        fresh=False)

    def run_incarnation(extra_env: dict[str, str]) -> int:
        attempt = extra_env.get(sup.ENV_RESTART_COUNT, "0")
        procs = launch_local(
            cmd, args.nproc, args.devices_per_proc,
            log_dir=os.path.join(args.log_dir, f"attempt_{attempt}"),
            env=extra_env)
        return wait(procs)

    try:
        result = sup.supervise(
            run_incarnation,
            policy=sup.RestartPolicy(
                max_restarts=args.max_restarts,
                backoff_base_s=args.backoff_base_s),
            state_dir=state_dir,
            ckpt_dir=args.ckpt_dir,
            telemetry=tel,
            should_stop=lambda: _launcher_signaled)
    finally:
        tel.close()
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
