"""Launch layer (L4): process fan-out + rendezvous wiring.

TPU-native replacement for the reference's torchrun/mp.spawn launch
path (reference: cloud-init.tftpl:59-78 computes per-node torchrun
invocations; src/playground/ddp_script.py:254-256 uses ``mp.spawn``).
On a TPU pod nothing here is needed — every host runs the same binary
and ``jax.distributed.initialize`` self-organises — so this module's
job is the *local simulation* path: spawning N host-processes on one
machine with an explicit coordinator, the framework's analogue of the
reference's Gloo/CPU cluster simulation (SURVEY.md §4.1).
"""

from distributed_training_tpu.launch.local import (  # noqa: F401
    LocalProcess, launch_local, main,
)
