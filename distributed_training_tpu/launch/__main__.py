from distributed_training_tpu.launch.local import main

raise SystemExit(main())
