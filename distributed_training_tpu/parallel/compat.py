"""Cross-version jax shims for the parallel kernels.

The parallel layer targets the newest jax API surface, but the repo
must stay importable (and compilable — the static SPMD auditor in
``analysis/`` lowers the ring/pipeline paths on every run) on the
container's pinned jaxlib. Each shim resolves the modern name when it
exists and otherwise maps onto the older spelling of the same
primitive — never a behavioral emulation, only a rename bridge.
"""

from __future__ import annotations

import jax


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis inside a traced context
    (shard_map/pmap body). ``jax.lax.axis_size`` exists from
    jax 0.4.38; older releases expose the same number through the axis
    environment (``jax.core.axis_frame``, which returns either a frame
    object carrying ``.size`` or, on some releases, the size itself).
    """
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    import jax.core as jcore

    frame = jcore.axis_frame(axis_name)
    return int(getattr(frame, "size", frame))
