"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context training shards the *sequence* dimension across devices;
attention then needs every query shard to see every KV shard. Ring
attention does this with O(S/sp) *attention-matrix* memory per device
(never materializing S×S scores; backward residuals are O(S_local) —
see the reverse-ring VJP below) and bandwidth-optimal neighbor
exchanges: KV blocks rotate around the ``sp`` ring via
``jax.lax.ppermute`` (XLA lowers it to ICI collective-permute) while each
device folds the incoming block into its queries' running online-softmax
state — the distributed generalization of the flash-attention recurrence
(Liu et al., Ring Attention with Blockwise Transformers, 2023).

Causality with a sequence sharded contiguously: ring step ``t`` delivers
the KV block of device ``(i - t) mod sp`` to device ``i``; that block is

- entirely in the past  (src < i)  → unmasked block attention,
- the diagonal          (src == i) → causal block attention,
- entirely in the future (src > i) → skipped (zero contribution).

The rotation runs a full cycle regardless (uniform collective schedule
on every device — no data-dependent communication), so causal skipping
saves FLOPs, not bandwidth.

Backward is a REVERSE-RING custom VJP, not autodiff: autodiff through
the scan would save each step's rotated KV carries (O(S_global) per
device — the memory scaling ring attention exists to avoid). Instead
the backward pass re-rotates the *original* KV blocks around the ring a
second time, recomputing each step's normalized softmax from the saved
per-row logsumexp (``p = exp(s - lse)``, the FlashAttention-2 trick)
while dk/dv partial sums travel WITH their KV block — after the full
cycle each block's gradient arrives back at its home device. Residuals
per device: q, k, v, out, lse — all O(S_local).

The reference repo has nothing like this (no attention at all,
SURVEY.md §5.7); it exists because long-context is first-class here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from distributed_training_tpu.runtime import AXIS_SP, BATCH_AXES


def _block_attn_with_lse(q, k, v, mode: str):
    """Blockwise attention returning (out_unnorm, m, l) online-softmax
    state. q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D); fp32 statistics."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    qg = q.reshape(B, Sq, Hkv, group, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    if mode == "causal":
        Sk = k.shape[1]
        mask = (jnp.arange(Sk)[None, :]
                <= (jnp.arange(Sq)[:, None] + (Sk - Sq)))
        s = jnp.where(mask[None, None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                          # (B,Hkv,g,Sq)
    m = jnp.maximum(m, -1e30)  # all-masked rows
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                          # (B,Hkv,g,Sq)
    o = jnp.einsum("bhgqk,bkhd->bhgqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)  # unnormalized
    return o, m, l


def _merge(o_a, m_a, l_a, o_b, m_b, l_b):
    """Merge two online-softmax partial states."""
    m = jnp.maximum(m_a, m_b)
    wa = jnp.exp(m_a - m)
    wb = jnp.exp(m_b - m)
    return (o_a * wa[..., None] + o_b * wb[..., None],
            m, l_a * wa + l_b * wb)


def _ring_perm(sp: int):
    """Rotate right: device i sends to i+1, so at step t device i holds
    the block originating at (i - t) mod sp."""
    return [(i, (i + 1) % sp) for i in range(sp)]


def _ring_fwd_scan(q, k, v, axis_name: str, causal: bool):
    """Full ring cycle of online-softmax accumulation. Returns the
    normalized output (B, S, H, D) and per-row logsumexp
    (B, Hkv, g, S) fp32."""
    sp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    perm = _ring_perm(sp)

    o0 = jnp.zeros((B, Hkv, group, S, D), jnp.float32)
    m0 = jnp.full((B, Hkv, group, S), -1e30, jnp.float32)
    l0 = jnp.zeros((B, Hkv, group, S), jnp.float32)

    def step(carry, t):
        k_cur, v_cur, o_acc, m_acc, l_acc = carry
        src = (idx - t) % sp

        def full_block(kv):
            return _block_attn_with_lse(q, kv[0], kv[1], "full")

        def diag_block(kv):
            return _block_attn_with_lse(q, kv[0], kv[1], "causal")

        def skip_block(kv):
            del kv  # future block: zero contribution, no FLOPs
            return (jnp.zeros_like(o0), jnp.full_like(m0, -1e30),
                    jnp.zeros_like(l0))

        if causal:
            # 0: past (full), 1: diagonal (causal), 2: future (skip);
            # lax.switch keeps only one branch's FLOPs per step.
            branch = jnp.where(src == idx, 1,
                               jnp.where(src < idx, 0, 2))
            o_t, m_t, l_t = jax.lax.switch(
                branch, (full_block, diag_block, skip_block),
                (k_cur, v_cur))
        else:
            o_t, m_t, l_t = full_block((k_cur, v_cur))

        o_acc, m_acc, l_acc = _merge(o_acc, m_acc, l_acc, o_t, m_t, l_t)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o_acc, m_acc, l_acc), None

    (k_f, v_f, o_acc, m_acc, l_acc), _ = jax.lax.scan(
        step, (k, v, o0, m0, l0), jnp.arange(sp))
    del k_f, v_f

    l_safe = jnp.maximum(l_acc, 1e-30)
    out = o_acc / l_safe[..., None]
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, D) \
        .astype(q.dtype)
    lse = m_acc + jnp.log(l_safe)                 # (B, Hkv, g, S)
    return out, lse


def _block_grads(q, k, v, do_g, lse, delta, mode: str):
    """Gradients of one KV block against the local queries, with the
    softmax recomputed from the saved logsumexp (``p = exp(s - lse)`` is
    the *normalized* softmax — no second normalizer pass needed).

    q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D); do_g: (B, Hkv, g, Sq, D)
    fp32; lse/delta: (B, Hkv, g, Sq) fp32. Returns (dq (B,Sq,H,D) f32,
    dk (B,Sk,Hkv,D) f32, dv likewise)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    Hkv = k.shape[2]
    group = H // Hkv
    scale = D ** -0.5
    qg = q.reshape(B, Sq, Hkv, group, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32) * scale
    if mode == "causal":
        mask = (jnp.arange(Sk)[None, :]
                <= (jnp.arange(Sq)[:, None] + (Sk - Sq)))
        s = jnp.where(mask[None, None, None], s, -jnp.inf)
    p = jnp.exp(s - lse[..., None])                  # (B,Hkv,g,Sq,Sk)
    dv = jnp.einsum("bhgqk,bhgqd->bkhd", p, do_g,
                    preferred_element_type=jnp.float32)
    dp = jnp.einsum("bhgqd,bkhd->bhgqk", do_g, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhgqk,bkhd->bqhgd", ds, k.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    dk = jnp.einsum("bhgqk,bqhgd->bkhd", ds, qg.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    return dq.reshape(B, Sq, H, D), dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_core(q, k, v, axis_name, causal):
    out, _ = _ring_fwd_scan(q, k, v, axis_name, causal)
    return out


def _ring_core_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_fwd_scan(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_core_bwd(axis_name, causal, res, do):
    """Reverse ring: KV blocks make a second full rotation; each step
    recomputes that block's softmax and adds its dk/dv contribution into
    accumulators that TRAVEL WITH the block — after sp rotations the
    block (and its finished gradient) is back on its home device. dq
    accumulates locally. Residuals were O(S_local); so are the carries.
    """
    q, k, v, out, lse = res
    sp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    perm = _ring_perm(sp)

    do_g = do.astype(jnp.float32) \
        .reshape(B, S, Hkv, group, D).transpose(0, 2, 3, 1, 4)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                        # (B, S, H)
    delta = delta.reshape(B, S, Hkv, group).transpose(0, 2, 3, 1)

    dq0 = jnp.zeros((B, S, H, D), jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)

    def step(carry, t):
        k_cur, v_cur, dq_acc, dk_acc, dv_acc = carry
        src = (idx - t) % sp

        def full_block(kv):
            return _block_grads(q, kv[0], kv[1], do_g, lse, delta,
                                "full")

        def diag_block(kv):
            return _block_grads(q, kv[0], kv[1], do_g, lse, delta,
                                "causal")

        def skip_block(kv):
            del kv
            return dq0, dk0, dv0

        if causal:
            branch = jnp.where(src == idx, 1,
                               jnp.where(src < idx, 0, 2))
            dq_t, dk_t, dv_t = jax.lax.switch(
                branch, (full_block, diag_block, skip_block),
                (k_cur, v_cur))
        else:
            dq_t, dk_t, dv_t = full_block((k_cur, v_cur))

        dq_acc = dq_acc + dq_t
        dk_acc = dk_acc + dk_t
        dv_acc = dv_acc + dv_t
        # Rotate the KV block together with its gradient accumulators.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_acc, axis_name, perm)
        return (k_nxt, v_nxt, dq_acc, dk_nxt, dv_nxt), None

    (k_f, v_f, dq, dk, dv), _ = jax.lax.scan(
        step, (k, v, dq0, dk0, dv0), jnp.arange(sp))
    del k_f, v_f
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = AXIS_SP,
                   causal: bool = True) -> jax.Array:
    """Sequence-parallel attention; call INSIDE shard_map.

    Shapes are per-device shards: q/k/v (B, S_local, H|Hkv, D) where the
    global sequence is the concatenation of shards in ``axis_name``
    order. Output matches q's shape/dtype.
    """
    sp = jax.lax.axis_size(axis_name)
    B, S, H, D = q.shape

    if sp == 1:
        o, m, l = _block_attn_with_lse(q, k, v,
                                       "causal" if causal else "full")
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, D) \
            .astype(q.dtype)

    return _ring_core(q, k, v, axis_name, causal)


def make_ring_attention(mesh: Mesh, causal: bool = True,
                        batch_axes=BATCH_AXES,
                        head_axis: str | None = None):
    """Build the shard_map'd ring-attention fn over global (B, S, H, D)
    arrays: batch over ``batch_axes``, sequence over ``sp``, heads over
    ``head_axis`` (pass ``tp`` to compose SP with tensor parallelism).
    The single construction point for every caller (models, tests)."""
    spec = P(tuple(batch_axes) or None, AXIS_SP, head_axis, None)
    return shard_map(
        functools.partial(ring_attention, axis_name=AXIS_SP,
                          causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )


def ring_attention_global(q: jax.Array, k: jax.Array, v: jax.Array,
                          mesh: Mesh, causal: bool = True,
                          batch_axes=BATCH_AXES) -> jax.Array:
    """Convenience entry for tests/eager use. Batch axes that don't
    divide B are dropped (replicated batch)."""
    import math
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    usable = tuple(a for a in batch_axes if sizes.get(a, 1) > 1)
    if usable and q.shape[0] % math.prod(sizes[a] for a in usable):
        usable = ()
    fn = make_ring_attention(mesh, causal=causal, batch_axes=usable)
    return jax.jit(fn)(q, k, v)
