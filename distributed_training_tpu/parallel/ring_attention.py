"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context training shards the *sequence* dimension across devices;
attention then needs every query shard to see every KV shard. Ring
attention does this with O(S/sp) *attention-matrix* memory per device
(never materializing S×S scores; KV-block residuals for backward are
O(S) like the inputs — see the remat note at the scan) and
bandwidth-optimal neighbor exchanges: KV blocks rotate around the ``sp`` ring via
``jax.lax.ppermute`` (XLA lowers it to ICI collective-permute) while each
device folds the incoming block into its queries' running online-softmax
state — the distributed generalization of the flash-attention recurrence
(Liu et al., Ring Attention with Blockwise Transformers, 2023).

Causality with a sequence sharded contiguously: ring step ``t`` delivers
the KV block of device ``(i - t) mod sp`` to device ``i``; that block is

- entirely in the past  (src < i)  → unmasked block attention,
- the diagonal          (src == i) → causal block attention,
- entirely in the future (src > i) → skipped (zero contribution).

The rotation runs a full cycle regardless (uniform collective schedule
on every device — no data-dependent communication), so causal skipping
saves FLOPs, not bandwidth. Backward is plain autodiff through the
``lax.scan``: ``ppermute``'s transpose is the inverse permute, giving
the reverse KV/gradient ring for free.

The reference repo has nothing like this (no attention at all,
SURVEY.md §5.7); it exists because long-context is first-class here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from distributed_training_tpu.runtime import AXIS_SP, BATCH_AXES


def _block_attn_with_lse(q, k, v, mode: str):
    """Blockwise attention returning (out_unnorm, m, l) online-softmax
    state. q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D); fp32 statistics."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    qg = q.reshape(B, Sq, Hkv, group, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    if mode == "causal":
        Sk = k.shape[1]
        mask = (jnp.arange(Sk)[None, :]
                <= (jnp.arange(Sq)[:, None] + (Sk - Sq)))
        s = jnp.where(mask[None, None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                          # (B,Hkv,g,Sq)
    m = jnp.maximum(m, -1e30)  # all-masked rows
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                          # (B,Hkv,g,Sq)
    o = jnp.einsum("bhgqk,bkhd->bhgqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)  # unnormalized
    return o, m, l


def _merge(o_a, m_a, l_a, o_b, m_b, l_b):
    """Merge two online-softmax partial states."""
    m = jnp.maximum(m_a, m_b)
    wa = jnp.exp(m_a - m)
    wb = jnp.exp(m_b - m)
    return (o_a * wa[..., None] + o_b * wb[..., None],
            m, l_a * wa + l_b * wb)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = AXIS_SP,
                   causal: bool = True) -> jax.Array:
    """Sequence-parallel attention; call INSIDE shard_map.

    Shapes are per-device shards: q/k/v (B, S_local, H|Hkv, D) where the
    global sequence is the concatenation of shards in ``axis_name``
    order. Output matches q's shape/dtype.
    """
    sp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv

    if sp == 1:
        o, m, l = _block_attn_with_lse(q, k, v,
                                       "causal" if causal else "full")
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, D) \
            .astype(q.dtype)

    # rotate right: device i sends its block to i+1, so at step t we
    # hold the block originating at (idx - t) mod sp.
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    o0 = jnp.zeros((B, Hkv, group, S, D), jnp.float32)
    m0 = jnp.full((B, Hkv, group, S), -1e30, jnp.float32)
    l0 = jnp.zeros((B, Hkv, group, S), jnp.float32)

    def step(carry, t):
        k_cur, v_cur, o_acc, m_acc, l_acc = carry
        src = (idx - t) % sp

        def full_block(kv):
            return _block_attn_with_lse(q, kv[0], kv[1], "full")

        def diag_block(kv):
            return _block_attn_with_lse(q, kv[0], kv[1], "causal")

        def skip_block(kv):
            del kv  # future block: zero contribution, no FLOPs
            return (jnp.zeros_like(o0), jnp.full_like(m0, -1e30),
                    jnp.zeros_like(l0))

        if causal:
            # 0: past (full), 1: diagonal (causal), 2: future (skip);
            # lax.switch keeps only one branch's FLOPs per step.
            branch = jnp.where(src == idx, 1,
                               jnp.where(src < idx, 0, 2))
            o_t, m_t, l_t = jax.lax.switch(
                branch, (full_block, diag_block, skip_block),
                (k_cur, v_cur))
        else:
            o_t, m_t, l_t = full_block((k_cur, v_cur))

        o_acc, m_acc, l_acc = _merge(o_acc, m_acc, l_acc, o_t, m_t, l_t)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o_acc, m_acc, l_acc), None

    # Remat the step: without it, autodiff saves each step's (Sq × Sk)
    # softmax intermediates — the quadratic-memory term ring attention
    # exists to avoid. With remat, backward residuals are the per-step
    # carries (the rotated KV blocks): O(S_global) per device, like the
    # inputs themselves. A custom reverse-ring VJP that re-rotates KV
    # instead of saving it (true O(S_local)) is the known upgrade path.
    (k_f, v_f, o_acc, m_acc, l_acc), _ = jax.lax.scan(
        jax.checkpoint(step, prevent_cse=False), (k, v, o0, m0, l0),
        jnp.arange(sp))
    del k_f, v_f

    out = o_acc / jnp.maximum(l_acc, 1e-30)[..., None]
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, D)
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, causal: bool = True,
                        batch_axes=BATCH_AXES,
                        head_axis: str | None = None):
    """Build the shard_map'd ring-attention fn over global (B, S, H, D)
    arrays: batch over ``batch_axes``, sequence over ``sp``, heads over
    ``head_axis`` (pass ``tp`` to compose SP with tensor parallelism).
    The single construction point for every caller (models, tests)."""
    spec = P(tuple(batch_axes) or None, AXIS_SP, head_axis, None)
    return shard_map(
        functools.partial(ring_attention, axis_name=AXIS_SP,
                          causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )


def ring_attention_global(q: jax.Array, k: jax.Array, v: jax.Array,
                          mesh: Mesh, causal: bool = True,
                          batch_axes=BATCH_AXES) -> jax.Array:
    """Convenience entry for tests/eager use. Batch axes that don't
    divide B are dropped (replicated batch)."""
    import math
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    usable = tuple(a for a in batch_axes if sizes.get(a, 1) > 1)
    if usable and q.shape[0] % math.prod(sizes[a] for a in usable):
        usable = ()
    fn = make_ring_attention(mesh, causal=causal, batch_axes=usable)
    return jax.jit(fn)(q, k, v)
