"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context training shards the *sequence* dimension across devices;
attention then needs every query shard to see every KV shard. Ring
attention does this with O(S/sp) *attention-matrix* memory per device
(never materializing S×S scores; backward residuals are O(S_local) —
see the reverse-ring VJP below) and bandwidth-optimal neighbor
exchanges: KV blocks rotate around the ``sp`` ring via
``jax.lax.ppermute`` (XLA lowers it to ICI collective-permute) while each
device folds the incoming block into its queries' running online-softmax
state — the distributed generalization of the flash-attention recurrence
(Liu et al., Ring Attention with Blockwise Transformers, 2023).

Causality with a sequence sharded contiguously: ring step ``t`` delivers
the KV block of device ``(i - t) mod sp`` to device ``i``; that block is

- entirely in the past  (src < i)  → unmasked block attention,
- the diagonal          (src == i) → causal block attention,
- entirely in the future (src > i) → skipped (zero contribution).

The rotation runs a full cycle regardless (uniform collective schedule
on every device — no data-dependent communication), so causal skipping
saves FLOPs, not bandwidth.

Per-block attention dispatches to the Pallas flash kernels when the
local shard is tile-friendly (``block_impl="auto"``): each ring step is
then MXU-tiled with O(tile) score memory — the blockwise-transformer
composition the ring paper assumes — falling back to the fused-einsum
reference otherwise. The merge works on (normalized out, logsumexp)
pairs, which both block implementations produce.

Backward is a REVERSE-RING custom VJP, not autodiff: autodiff through
the scan would save each step's rotated KV carries (O(S_global) per
device — the memory scaling ring attention exists to avoid). Instead
the backward pass re-rotates the *original* KV blocks around the ring a
second time, recomputing each step's normalized softmax from the saved
per-row logsumexp (``p = exp(s - lse)``, the FlashAttention-2 trick)
while dk/dv partial sums travel WITH their KV block — after the full
cycle each block's gradient arrives back at its home device. Residuals
per device: q, k, v, out, lse — all O(S_local).

The reference repo has nothing like this (no attention at all,
SURVEY.md §5.7); it exists because long-context is first-class here.
"""

from __future__ import annotations

import functools

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_training_tpu.parallel.compat import axis_size
from distributed_training_tpu.runtime import AXIS_SP, BATCH_AXES


NEG_INF = -1e30


def _block_mask(Sq: int, Sk: int, mode: str, offset, window: int):
    """Visibility mask (Sq, Sk) for one ring block pair.

    ``offset`` = absolute query-start − absolute key-start (0 on the
    diagonal, t·S_local for a block t ring steps in the past; may be a
    traced scalar). Query row r sits at absolute position r + offset
    relative to key column c: causal keeps ``c <= r + offset``, a
    sliding window additionally needs ``c >= r + offset − (window−1)``.
    Returns None when nothing is masked (pure-past block, no window).
    """
    rows = jnp.arange(Sq)[:, None] + offset
    cols = jnp.arange(Sk)[None, :]
    mask = None
    if mode == "causal":
        mask = cols <= rows
    if window:
        lower = cols >= rows - (window - 1)
        mask = lower if mask is None else jnp.logical_and(mask, lower)
    return mask


def _block_attn_naive(q, k, v, mode: str, offset=None, window: int = 0):
    """XLA-einsum block attention → (out_norm (B,Sq,H,D) f32,
    lse (B,H,Sq) f32). The numerics reference for the flash block.

    ``offset``/``window``: ring-block geometry (see _block_mask);
    ``offset=None`` keeps the historical single-pair alignment
    ``Sk − Sq`` (queries end where keys end)."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    qg = q.reshape(B, Sq, Hkv, group, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    Sk = k.shape[1]
    if offset is None:
        offset = Sk - Sq
    mask = _block_mask(Sq, Sk, mode, offset, window)
    if mask is not None:
        s = jnp.where(mask[None, None, None], s, -jnp.inf)
    m = jnp.maximum(jnp.max(s, axis=-1), NEG_INF)    # (B,Hkv,g,Sq)
    p = jnp.exp(s - m[..., None])
    lsum = jnp.maximum(jnp.sum(p, axis=-1), 1e-30)
    o = jnp.einsum("bhgqk,bkhd->bhgqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32) / lsum[..., None]
    out = o.transpose(0, 3, 1, 2, 4).reshape(B, Sq, H, D)
    lse = (m + jnp.log(lsum)).reshape(B, Hkv * group, Sq)
    return out, lse


def _validate_tile_overrides(q, k, block_q: int, block_k: int) -> None:
    """Raise-don't-ignore: an explicit flash tile override that does
    not divide the local shard would otherwise be silently dropped —
    how sweeps misattribute their own measurements."""
    S, Sk = q.shape[1], k.shape[1]
    if (block_q and S % min(block_q, S)) or (
        block_k and Sk % min(block_k, Sk)
    ):
        raise ValueError(
            f"flash tile overrides ({block_q}, {block_k}) do not "
            f"divide the local shard lengths ({S}, {Sk})")


def _flash_block_ok(q, k, block_impl: str, block_q: int = 0,
                    block_k: int = 0) -> bool:
    """Route this block through the Pallas flash kernel? Static
    decision (shapes are static under jit/shard_map). Forcing
    ``"flash"`` with non-tile-friendly shards raises: the kernel grid
    would silently leave output rows unwritten (partial tiles), and
    garbage propagated through the ring merge is far worse than a
    trace-time error. Explicit tile overrides that don't divide the
    shard raise for the same reason — a silently ignored override is
    how sweeps misattribute their own measurements."""
    from distributed_training_tpu.ops import flash_attention as fa
    _validate_tile_overrides(q, k, block_q, block_k)
    S, Sk = q.shape[1], k.shape[1]
    if block_impl == "naive":
        return False
    if block_impl == "flash":
        bq, bk = fa._resolve_blocks(block_q, block_k, S, Sk,
                                    q.shape[3])
        if not bq or not bk or S % bq or Sk % bk:
            raise ValueError(
                f"block_impl='flash' forced but local shard lengths "
                f"({S}, {Sk}) admit no dividing kernel tile "
                f"(resolved ({bq}, {bk}), 0 = none fits VMEM); pad "
                f"the sequence or use 'auto'")
        if q.shape[2] % k.shape[2]:
            # A non-dividing group would make the kernel's h // reps
            # KV index map read out-of-range blocks (Pallas clamps —
            # silently wrong heads, no error).
            raise ValueError(
                f"block_impl='flash': n_heads {q.shape[2]} not "
                f"divisible by n_kv_heads {k.shape[2]}")
        if q.dtype not in (jnp.float32, jnp.bfloat16):
            raise ValueError(
                f"block_impl='flash': unsupported dtype {q.dtype} "
                "(float32/bfloat16 only)")
        return True
    # auto: same tile-friendliness rules as single-device dispatch
    # (incl. Sq == Sk, which ring blocks always satisfy), checked
    # against the EFFECTIVE tiles — an override must not demote the
    # ring to the naive path against the default tiles.
    return fa.supported(q, k, k, block_q=block_q, block_k=block_k)


def _bhsd(x):
    return jnp.transpose(x, (0, 2, 1, 3))


def _flash_blocks(qt, block_q: int = 0, block_k: int = 0):
    """Tile sizes for a (B,H,S,D)-layout ring block (0 → the measured
    seq-aware kernel defaults, clamped to the local shard length)."""
    from distributed_training_tpu.ops import flash_attention as fa
    return fa._resolve_blocks(block_q, block_k, qt.shape[2],
                              qt.shape[2], qt.shape[3])


def _block_attn_flash(qt, k, v, mode: str, block_q: int = 0,
                      block_k: int = 0, window: int = 0):
    """One ring block via the Pallas flash kernel (MXU-tiled, O(tile)
    scores memory). ``qt`` is the loop-invariant (B,H,S,D) transpose of
    the local queries — hoisted out of the ring scan by the caller
    (k/v rotate, so their transposes legitimately live in the step).
    ``window``: legal only for the DIAGONAL block (offset 0 — the
    aligned geometry the kernel's band support models)."""
    from distributed_training_tpu.ops import flash_attention as fa
    bq, bk = _flash_blocks(qt, block_q, block_k)
    # f32 out: per-block partials must not round to the input dtype
    # before the cross-block merge (the naive path is f32 throughout;
    # single-device flash rounds exactly once, at the very end).
    out, lse = fa._flash_fwd(qt, _bhsd(k), _bhsd(v),
                             causal=(mode == "causal"),
                             block_q=bq, block_k=bk,
                             out_dtype=jnp.float32, window=window)
    return _bhsd(out), lse[..., 0]


def _merge(out_a, lse_a, out_b, lse_b):
    """Merge two normalized partial attentions with their logsumexps:
    softmax over the union = lse-weighted convex combination."""
    lse = jnp.logaddexp(lse_a, lse_b)                  # (B,H,S)
    wa = jnp.exp(lse_a - lse)
    wb = jnp.exp(lse_b - lse)
    # (B,H,S) weights onto (B,S,H,D) outputs
    wa = jnp.transpose(wa, (0, 2, 1))[..., None]
    wb = jnp.transpose(wb, (0, 2, 1))[..., None]
    return out_a * wa + out_b * wb, lse


def _ring_perm(sp: int):
    """Rotate right: device i sends to i+1, so at step t device i holds
    the block originating at (i - t) mod sp."""
    return [(i, (i + 1) % sp) for i in range(sp)]


def _ring_branch(src, idx, t, S: int, window: int):
    """Ring-step branch id: 0 = past block, 1 = diagonal, 2 = skip.

    Blocks ahead of the queries are always skipped (causality). Under a
    sliding window, a past block t steps back is additionally skipped
    when even its NEWEST key (gap to the OLDEST local query:
    (t−1)·S + 1 positions) falls outside the window — the FLOPs term
    that makes windowed ring attention O(S·W/sp) per device instead of
    O(S²/sp²)·sp."""
    past = jnp.where(src < idx, 0, 2)
    if window:
        past = jnp.where((t - 1) * S + 1 <= window - 1, past, 2)
    return jnp.where(src == idx, 1, past)


def _ring_fwd_scan(q, k, v, axis_name: str, causal: bool,
                   block_impl: str, block_q: int = 0,
                   block_k: int = 0, window: int = 0):
    """Full ring cycle of online-softmax accumulation. Returns the
    normalized output (B, S, H, D) in q.dtype and per-row logsumexp
    (B, H, S) fp32."""
    sp = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    perm = _ring_perm(sp)

    # The Pallas kernel models the band only in the ALIGNED geometry
    # (offset 0), so under a window it serves the diagonal block — the
    # dominant computed block once out-of-window blocks are skipped —
    # while offset (past/boundary) blocks run the einsum reference.
    # Without a window every block is flash-eligible.
    use_flash = _flash_block_ok(q, k, block_impl, block_q, block_k)
    # Loop-invariant: hoisted here because XLA's while-loop LICM does
    # not lift computations out of lax.switch branch computations.
    qt = _bhsd(q) if use_flash else None

    def block(kv, mode, offset):
        if use_flash and (not window or mode == "causal"):
            return _block_attn_flash(qt, kv[0], kv[1], mode,
                                     block_q, block_k, window=window)
        return _block_attn_naive(q, kv[0], kv[1], mode,
                                 offset=offset, window=window)

    out0 = jnp.zeros((B, S, H, D), jnp.float32)
    lse0 = jnp.full((B, H, S), NEG_INF, jnp.float32)

    def step(carry, t):
        k_cur, v_cur, out_acc, lse_acc = carry
        src = (idx - t) % sp
        # Non-future blocks sit exactly t ring steps in the past, so
        # the absolute query-start − key-start offset is t·S.
        offset = t * S

        def full_block(kv):
            return block(kv, "full", offset)

        def diag_block(kv):
            return block(kv, "causal", 0)

        def skip_block(kv):
            del kv  # out-of-view block: zero contribution, no FLOPs
            return jnp.zeros_like(out0), jnp.full_like(lse0, NEG_INF)

        if causal:
            # lax.switch keeps only one branch's FLOPs per step.
            branch = _ring_branch(src, idx, t, S, window)
            out_t, lse_t = jax.lax.switch(
                branch, (full_block, diag_block, skip_block),
                (k_cur, v_cur))
        else:
            out_t, lse_t = full_block((k_cur, v_cur))

        out_acc, lse_acc = _merge(out_acc, lse_acc, out_t, lse_t)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, out_acc, lse_acc), None

    (k_f, v_f, out_acc, lse_acc), _ = jax.lax.scan(
        step, (k, v, out0, lse0), jnp.arange(sp))
    del k_f, v_f
    return out_acc.astype(q.dtype), lse_acc


def _block_grads_naive(q, k, v, do_g, lse, delta, mode: str,
                       offset=None, window: int = 0):
    """Einsum gradients of one KV block against the local queries, with
    the softmax recomputed from the saved FINAL logsumexp
    (``p = exp(s - lse)`` is the globally-normalized softmax — the
    FlashAttention-2 decomposition, so per-block grads sum to the
    exact total).

    q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D); do_g: (B, Hkv, g, Sq, D)
    fp32; lse/delta: (B, H, Sq) fp32. Returns (dq (B,Sq,H,D) f32,
    dk (B,Sk,Hkv,D) f32, dv likewise)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    Hkv = k.shape[2]
    group = H // Hkv
    scale = D ** -0.5
    qg = q.reshape(B, Sq, Hkv, group, D)
    lse_g = lse.reshape(B, Hkv, group, Sq)
    delta_g = delta.reshape(B, Hkv, group, Sq)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32) * scale
    if offset is None:
        offset = Sk - Sq
    mask = _block_mask(Sq, Sk, mode, offset, window)
    if mask is not None:
        s = jnp.where(mask[None, None, None], s, -jnp.inf)
    p = jnp.exp(s - lse_g[..., None])                # (B,Hkv,g,Sq,Sk)
    dv = jnp.einsum("bhgqk,bhgqd->bkhd", p, do_g,
                    preferred_element_type=jnp.float32)
    dp = jnp.einsum("bhgqd,bkhd->bhgqk", do_g, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    ds = p * (dp - delta_g[..., None]) * scale
    dq = jnp.einsum("bhgqk,bkhd->bqhgd", ds, k.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    dk = jnp.einsum("bhgqk,bqhgd->bkhd", ds, qg.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    return dq.reshape(B, Sq, H, D), dk, dv


def _block_grads_flash(qt, dot, k, v, lse, delta, mode: str,
                       block_q: int = 0, block_k: int = 0,
                       window: int = 0):
    """Per-block gradients via the Pallas flash backward kernels. Feeds
    the FINAL (lse, delta) — the FA2 trick makes per-block kernels
    compose into the ring total without any per-block statistics.
    ``qt``/``dot`` are the loop-invariant (B,H,S,D) transposes of the
    local queries / upstream grads, hoisted out of the ring scan.
    ``window``: diagonal block only (aligned geometry)."""
    from distributed_training_tpu.ops import flash_attention as fa
    bq, bk = _flash_blocks(qt, block_q, block_k)
    dq, dk, dv = fa._flash_bwd(
        qt, _bhsd(k), _bhsd(v), None, lse[..., None], dot,
        causal=(mode == "causal"), block_q=bq, block_k=bk,
        delta=delta[..., None], grads_dtype=jnp.float32,
        window=window)
    return _bhsd(dq), _bhsd(dk), _bhsd(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_core(q, k, v, axis_name, causal, block_impl,
               block_q=0, block_k=0, window=0):
    out, _ = _ring_fwd_scan(q, k, v, axis_name, causal, block_impl,
                            block_q, block_k, window)
    return out


def _ring_core_fwd(q, k, v, axis_name, causal, block_impl,
                   block_q=0, block_k=0, window=0):
    out, lse = _ring_fwd_scan(q, k, v, axis_name, causal, block_impl,
                              block_q, block_k, window)
    # Checkpoint-name the residuals the reverse ring consumes (same
    # discipline as ops/flash_attention._flash_bhsd_fwd): un-named
    # custom-VJP residuals are dropped by save_only_these_names remat
    # policies, and the "recompute" here is the ENTIRE forward ring —
    # sp ppermute rotations riding ICI — not just a local kernel.
    # The model's policy allow-lists carry these names
    # (models/transformer.FLASH_RESIDUAL_NAMES). Primal and residual
    # share the named value — see the note in
    # ops/flash_attention._flash_bhsd_fwd.
    name = jax.ad_checkpoint.checkpoint_name
    out = name(out, "flash_out")
    return out, (q, k, v, out, name(lse, "flash_lse"))


def _ring_core_bwd(axis_name, causal, block_impl, block_q, block_k,
                   window, res, do):
    """Reverse ring: KV blocks make a second full rotation; each step
    recomputes that block's softmax and adds its dk/dv contribution into
    accumulators that TRAVEL WITH the block — after sp rotations the
    block (and its finished gradient) is back on its home device. dq
    accumulates locally. Residuals were O(S_local); so are the carries.
    """
    q, k, v, out, lse = res
    sp = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    perm = _ring_perm(sp)

    do_f = do.astype(jnp.float32)
    delta = jnp.sum(do_f * out.astype(jnp.float32), axis=-1)  # (B,S,H)
    delta = jnp.transpose(delta, (0, 2, 1))                   # (B,H,S)

    # Loop-invariant per-path precomputes, hoisted out of the scan
    # (XLA's while-loop LICM does not lift out of switch branches):
    # flash wants (B,H,S,D) q/dO; the einsum path wants grouped dO.
    use_flash = _flash_block_ok(q, k, block_impl, block_q, block_k)
    if use_flash:
        qt, dot = _bhsd(q), _bhsd(do)
    else:
        qt = dot = None
    if not use_flash or window:
        # The einsum path serves every block when flash is off, and
        # the offset (past/boundary) blocks under a window.
        do_g = do_f.reshape(B, S, Hkv, group, D).transpose(
            0, 2, 3, 1, 4
        )
    else:
        do_g = None

    def block_grads(kv, mode, offset):
        if use_flash and (not window or mode == "causal"):
            return _block_grads_flash(qt, dot, kv[0], kv[1], lse,
                                      delta, mode, block_q, block_k,
                                      window=window)
        return _block_grads_naive(q, kv[0], kv[1], do_g, lse, delta,
                                  mode, offset=offset, window=window)

    dq0 = jnp.zeros((B, S, H, D), jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)

    def step(carry, t):
        k_cur, v_cur, dq_acc, dk_acc, dv_acc = carry
        src = (idx - t) % sp
        offset = t * S

        def full_block(kv):
            return block_grads(kv, "full", offset)

        def diag_block(kv):
            return block_grads(kv, "causal", 0)

        def skip_block(kv):
            del kv
            return dq0, dk0, dv0

        if causal:
            branch = _ring_branch(src, idx, t, S, window)
            dq_t, dk_t, dv_t = jax.lax.switch(
                branch, (full_block, diag_block, skip_block),
                (k_cur, v_cur))
        else:
            dq_t, dk_t, dv_t = full_block((k_cur, v_cur))

        dq_acc = dq_acc + dq_t
        dk_acc = dk_acc + dk_t
        dv_acc = dv_acc + dv_t
        # Rotate the KV block together with its gradient accumulators.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_acc, axis_name, perm)
        return (k_nxt, v_nxt, dq_acc, dk_nxt, dv_nxt), None

    (k_f, v_f, dq, dk, dv), _ = jax.lax.scan(
        step, (k, v, dq0, dk0, dv0), jnp.arange(sp))
    del k_f, v_f
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = AXIS_SP,
                   causal: bool = True,
                   block_impl: str = "auto",
                   block_q: int = 0, block_k: int = 0,
                   window: int = 0) -> jax.Array:
    """Sequence-parallel attention; call INSIDE shard_map.

    Shapes are per-device shards: q/k/v (B, S_local, H|Hkv, D) where the
    global sequence is the concatenation of shards in ``axis_name``
    order. Output matches q's shape/dtype. ``block_impl``: per-block
    attention kernel — "auto" uses the Pallas flash kernel when the
    local shard is tile-friendly (fwd AND reverse-ring bwd), else the
    einsum reference; "naive"/"flash" force a path. ``block_q``/
    ``block_k`` override the flash tiles (0 → module defaults; must
    divide the local shard — raises rather than silently ignore).

    ``window > 0``: sliding-window (Mistral-style) attention in GLOBAL
    positions — query i attends keys [i − window + 1, i] across shard
    boundaries. Ring blocks entirely behind the window are skipped
    (work per device is O(S_local · window), not O(S_local · S)); the
    diagonal block runs the flash kernel with its aligned band mask
    when tile-friendly, while offset (past/boundary) blocks run the
    einsum path (the kernels don't model the offset band). Requires
    ``causal=True``.
    """
    if window and not causal:
        raise ValueError("window > 0 requires causal=True")
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window:
        # Under a window only the DIAGONAL block can use the flash
        # kernel (aligned band); offset blocks run the einsum path.
        # Forcing 'flash' would therefore be partially ignored — the
        # raise-don't-ignore contract on explicit kernel config makes
        # that loud (a silently demoted sweep misattributes its own
        # measurements).
        if block_impl == "flash":
            raise ValueError(
                "block_impl='flash' is unsupported with window > 0 "
                "(the per-block flash kernels don't model the offset "
                "band mask); use block_impl='auto' or 'naive'")
        _validate_tile_overrides(q, k, block_q, block_k)
    sp = axis_size(axis_name)

    if sp == 1:
        # Degenerate ring: plain block attention under autodiff (the
        # naive block — the Pallas fwd kernel alone has no vjp outside
        # the ring's custom VJP). The raise-don't-ignore contract on
        # tile overrides still applies.
        _validate_tile_overrides(q, k, block_q, block_k)
        out, _ = _block_attn_naive(q, k, v,
                                   "causal" if causal else "full",
                                   window=window)
        return out.astype(q.dtype)

    return _ring_core(q, k, v, axis_name, causal, block_impl,
                      block_q, block_k, window)


def make_ring_attention(mesh: Mesh, causal: bool = True,
                        batch_axes=BATCH_AXES,
                        head_axis: str | None = None,
                        block_impl: str = "auto",
                        block_q: int = 0, block_k: int = 0,
                        window: int = 0):
    """Build the shard_map'd ring-attention fn over global (B, S, H, D)
    arrays: batch over ``batch_axes``, sequence over ``sp``, heads over
    ``head_axis`` (pass ``tp`` to compose SP with tensor parallelism).
    The single construction point for every caller (models, tests)."""
    spec = P(tuple(batch_axes) or None, AXIS_SP, head_axis, None)
    return shard_map(
        functools.partial(ring_attention, axis_name=AXIS_SP,
                          causal=causal, block_impl=block_impl,
                          block_q=block_q, block_k=block_k,
                          window=window),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )


def usable_batch_axes(mesh: Mesh, batch: int,
                      batch_axes=BATCH_AXES) -> tuple:
    """Mesh batch axes a global batch of ``batch`` rows can actually be
    sharded over; axes that don't divide are dropped (replicated).
    Shared by the eager/test entry points of every sequence-parallel
    attention (ring, ulysses)."""
    import math
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    usable = tuple(a for a in batch_axes if sizes.get(a, 1) > 1)
    if usable and batch % math.prod(sizes[a] for a in usable):
        return ()
    return usable


def ring_attention_global(q: jax.Array, k: jax.Array, v: jax.Array,
                          mesh: Mesh, causal: bool = True,
                          batch_axes=BATCH_AXES,
                          window: int = 0) -> jax.Array:
    """Convenience entry for tests/eager use."""
    fn = make_ring_attention(
        mesh, causal=causal,
        batch_axes=usable_batch_axes(mesh, q.shape[0], batch_axes),
        window=window)
    return jax.jit(fn)(q, k, v)
