"""XLA comms/compute-overlap flags, derived from a sharding plan.

SimpleFSDP's lesson (PAPERS.md, arXiv 2411.00284) is that FSDP's
all-gather/reduce-scatter latency is hidden by COMPILER scheduling,
not hand-written pipelining; TorchTitan ships that as a composable
knob of the stack. The JAX equivalent is XLA's latency-hiding
scheduler family, enabled per backend by flags. This module is the
one place those flags are derived — from the plan, because the plan
knows whether there is anything to hide (an unsharded mesh has no
collectives) and how much per-step traffic the combiner should batch
(its compile evidence records the measured collective bytes).

Consumers: ``Plan.xla_overlap_flags()`` (the API surface),
``train/cli.py`` and ``launch/local.py`` (apply to ``XLA_FLAGS``
before backend init), ``benchmarks/bench_multichip.py`` (apply +
record in MULTICHIP provenance), and the SPMD-audit targets
(``analysis/targets.py`` passes them as per-compile
``compiler_options`` so the overlap ratchet scores the schedule a
flagged run executes).

Per-platform sets:

- ``tpu``: the latency-hiding scheduler + async-collective-fusion
  set that public TPU training stacks (MaxText et al.) run with.
- ``gpu``: the GPU latency-hiding scheduler plus collective-combiner
  thresholds sized from the plan's measured per-step collective
  bytes — combine everything a step moves, capped so the combiner
  cannot create a multi-hundred-MB fusion bubble.
- ``cpu``: the concurrency-optimized module scheduler — the CPU
  backend's analogue (measured on the repo's fake-device meshes:
  the r06 planned target's static overlap score rises 0.32 -> 0.92,
  see ``analysis/OVERLAP_baseline.json``).

The module itself depends on nothing but the stdlib — the derivation
is pure data over plan JSON, and it never initializes a backend
(importing it does execute the package ``__init__``s, which import
the jax MODULE like every module in this repo; no device or compiler
state is touched).
"""

from __future__ import annotations

import os
import re

# Flag VALUES are python types; ``render_xla_flags`` lowercases bools
# for the env form, compiler_options passes them through (jax accepts
# python bools/ints per-compile).
TPU_OVERLAP_FLAGS = {
    "xla_tpu_enable_latency_hiding_scheduler": True,
    "xla_enable_async_all_gather": True,
    "xla_enable_async_collective_permute": True,
    "xla_tpu_enable_async_collective_fusion": True,
    "xla_tpu_enable_async_collective_fusion_fuse_all_gather": True,
    "xla_tpu_enable_async_collective_fusion_multiple_steps": True,
    "xla_tpu_overlap_compute_collective_tc": True,
}

CPU_OVERLAP_FLAGS = {
    "xla_cpu_enable_concurrency_optimized_scheduler": True,
}

GPU_OVERLAP_FLAGS = {
    "xla_gpu_enable_latency_hiding_scheduler": True,
}

# Combiner-threshold clamp: at least 1 MiB (below that the combiner
# is latency noise), at most 64 MiB (past that the combined
# collective's memory spike outweighs the launch savings).
_COMBINE_MIN = 1 << 20
_COMBINE_MAX = 1 << 26


def combine_threshold_bytes(collective_bytes_per_step) -> int:
    """Combiner threshold from the plan's measured per-step
    collective traffic: the next power of two at or above it, so one
    step's collectives of a kind can combine into one launch,
    clamped to [1 MiB, 64 MiB]."""
    try:
        nbytes = int(collective_bytes_per_step)
    except (TypeError, ValueError):
        nbytes = 0
    thr = _COMBINE_MIN
    while thr < nbytes and thr < _COMBINE_MAX:
        thr <<= 1
    return min(thr, _COMBINE_MAX)


def platform_from_env(default: str = "", env=None) -> str:
    """The platform a process WILL initialize, readable before the
    backend exists: the first ``JAX_PLATFORMS`` entry, else
    ``default``. The one shared resolution for every flag consumer
    (cli / launcher / bench) — three hand-rolled copies would drift.
    An empty result means "unknown": callers must derive NO flags
    rather than guess a backend and trip an unknown-flag abort."""
    env = os.environ if env is None else env
    p = env.get("JAX_PLATFORMS", "").split(",")[0].strip()
    return p or default


def flags_for(platform: str, mesh: dict | None = None,
              collective_bytes_per_step=None) -> dict:
    """The overlap flag set for ``platform`` (``cpu``/``gpu``/``tpu``;
    anything else — or an unsharded mesh, which compiles zero
    collectives — gets ``{}``)."""
    if mesh is not None and not any(
            int(s) > 1 for s in mesh.values()):
        return {}
    p = (platform or "").lower()
    if p == "tpu":
        return dict(TPU_OVERLAP_FLAGS)
    if p == "gpu":
        flags = dict(GPU_OVERLAP_FLAGS)
        thr = combine_threshold_bytes(collective_bytes_per_step)
        for k in ("xla_gpu_all_gather_combine_threshold_bytes",
                  "xla_gpu_reduce_scatter_combine_threshold_bytes",
                  "xla_gpu_all_reduce_combine_threshold_bytes"):
            flags[k] = thr
        return flags
    if p == "cpu":
        return dict(CPU_OVERLAP_FLAGS)
    return {}


def flags_for_plan_doc(doc: dict, platform: str) -> dict:
    """Flags from a RAW plan document (stdlib callers: the launcher
    parent, the targets registry). The consuming half of
    ``Plan.xla_overlap_flags`` — same derivation, no jax import."""
    ev = (doc.get("provenance") or {}).get("compile_evidence") or {}
    return flags_for(
        platform, mesh=doc.get("mesh"),
        collective_bytes_per_step=ev.get("collective_bytes_per_step"))


def render_xla_flags(flags: dict) -> str:
    """``--name=value`` space-joined, bools lowercased — the
    ``XLA_FLAGS`` env form."""
    def val(v):
        return str(v).lower() if isinstance(v, bool) else str(v)
    return " ".join(f"--{k}={val(v)}" for k, v in sorted(flags.items()))


def _flag_names(xla_flags: str) -> set[str]:
    """Flag NAMES present in an ``XLA_FLAGS`` string, tokenized — a
    raw substring test would let a longer-named flag
    (``..._fusion_fuse_all_gather``) shadow a shorter one
    (``..._fusion``)."""
    return set(re.findall(r"--([A-Za-z0-9_]+)(?==|\s|$)", xla_flags))


def apply_to_env(flags: dict, env=None) -> list[str]:
    """Append ``flags`` to ``env['XLA_FLAGS']`` and return the names
    actually applied. A flag whose NAME is already set in the
    existing value is left alone — an operator's explicit setting
    (including an explicit ``...=false``) outranks the plan's
    derivation. Must run before the backend initializes; callers own
    that ordering (the planner-CLI / bench_multichip env discipline).
    """
    env = os.environ if env is None else env
    existing = env.get("XLA_FLAGS", "")
    names = _flag_names(existing)
    fresh = {k: v for k, v in flags.items() if k not in names}
    if not fresh:
        return []
    env["XLA_FLAGS"] = (existing + " "
                        + render_xla_flags(fresh)).strip()
    return sorted(fresh)


def active_in_env(flags: dict, env=None) -> dict:
    """Which of ``flags`` are present (by exact name) in
    ``env['XLA_FLAGS']`` — provenance for ledger entries and
    telemetry events. Values are read from the ENV string (the
    operator may have set a flag to a different value than the plan
    derives; provenance must report what actually ran)."""
    env = os.environ if env is None else env
    existing = env.get("XLA_FLAGS", "")
    out = {}
    for k in flags:
        # LAST occurrence wins — XLA honors the final repetition of
        # a flag, and provenance must report what actually ran.
        ms = re.findall(r"--" + re.escape(k) + r"(?:=(\S+))?(?=\s|$)",
                        existing)
        if not ms:
            continue
        val = ms[-1] or None
        if val is None:
            out[k] = True
        elif val.lower() in ("true", "false"):
            out[k] = val.lower() == "true"
        else:
            try:
                out[k] = int(val)
            except ValueError:
                out[k] = val
    return out
