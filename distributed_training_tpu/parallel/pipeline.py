"""Pipeline parallelism over the ``pp`` mesh axis: GPipe + interleaved.

The transformer's decoder stack is already a *stacked-layer* pytree
(leaves shaped ``(L, ...)``, models/transformer.py), which makes pipeline
parallelism a sharding statement plus a schedule:

- **layout**: shard the stacked-layer leading dim over ``pp`` — stage
  ``i`` physically holds a slice of the layers. This is the partition
  jit cannot exploit on its own (layers execute sequentially), hence the
  explicit schedule.
- **GPipe schedule**: split the batch into ``M`` microbatches and run
  the classic wavefront for ``M + pp - 1`` ticks inside ``shard_map``:
  stage 0 injects microbatch ``t``; every stage applies its local layers
  to its buffer; buffers rotate to the next stage via ``ppermute``
  (XLA collective-permute on ICI); the last stage banks finished
  microbatches. Bubble fraction ``(pp-1)/(M+pp-1)``.
- **Interleaved schedule** (Megatron-style virtual stages): each device
  owns ``v`` *non-contiguous* layer chunks, so the ring has ``v·pp``
  virtual stages of ``L/(v·pp)`` layers and a tick is one chunk. The
  pipeline fills in ``pp - 1`` chunk-ticks instead of ``pp - 1``
  full-stage ticks — idle device-ticks shrink ``v``-fold (see
  ``schedule_stats``; asserted in tests/test_pipeline.py).
- **backward**: plain autodiff. ``ppermute`` transposes to the reverse
  permute, so the same schedule runs backwards (activations
  rematerialize per-stage via the remat'd tick).
- **dropout**: the stage body receives each layer's *global* id and the
  microbatch index of the tick, so per-(layer, microbatch) rngs are
  derived identically on every schedule — pipelined dropout draws the
  same masks regardless of pp (models/transformer.py threads them).

All devices execute the same program every tick (SPMD — no
data-dependent communication); stage roles differ only by masking on
``axis_index``. The reference repo has no pipeline (SURVEY.md §2.3);
this exists so deep models scale past one chip's HBM along depth as
well as width.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_tpu.parallel.compat import axis_size
from distributed_training_tpu.runtime import AXIS_PP

SCHEDULES = ("gpipe", "interleaved")


def pipeline_spec(leaf_ndim: int) -> P:
    """Spec for a stacked-layer param leaf inside the pipeline
    shard_map: leading (layer) dim over pp, rest replicated."""
    return P(AXIS_PP, *([None] * (leaf_ndim - 1)))


def schedule_stats(pp: int, num_microbatches: int, schedule: str,
                   virtual_stages: int = 2) -> dict:
    """Static schedule accounting in *chunk-tick* units (a chunk is
    ``L/(v·pp)`` layers; a GPipe tick costs ``v`` chunk-ticks so both
    schedules are measured in the same currency).

    Returns ticks, total device-slots, useful slots, and idle slots.
    """
    m = num_microbatches
    if schedule == "gpipe":
        ticks = (m + pp - 1) * virtual_stages
    elif schedule == "interleaved":
        # last microbatch enters at (g·v·pp + r) and takes v·pp ticks
        # (same arithmetic as _interleave_tables).
        g, r = divmod(m - 1, pp)
        ticks = g * virtual_stages * pp + r + virtual_stages * pp
    else:
        raise ValueError(f"unknown schedule '{schedule}'")
    slots = ticks * pp
    useful = m * virtual_stages * pp
    return {"ticks": ticks, "slots": slots, "useful": useful,
            "idle": slots - useful}


def _interleave_tables(pp: int, M: int, v: int):
    """Static (T, pp) tables for the interleaved schedule: microbatch
    index (−1 = idle), virtual stage (−1 = idle) per (tick, device).

    Microbatch ``m`` (group ``g = m // pp``, slot ``r = m % pp``) enters
    virtual stage 0 at tick ``g·v·pp + r`` and advances one virtual
    stage per tick; virtual stage ``s`` lives on device ``s % pp``. The
    group spacing guarantees at most one live buffer per device per
    tick (device d, tick t holds the unique in-flight m with
    ``t − e_m ≡ d (mod pp)``)."""
    S = v * pp
    entry = [(m // pp) * S + (m % pp) for m in range(M)]
    T = entry[-1] + S
    mb = -np.ones((T, pp), dtype=np.int32)
    vs = -np.ones((T, pp), dtype=np.int32)
    for m in range(M):
        for s in range(S):
            t = entry[m] + s
            d = s % pp
            assert mb[t, d] < 0, "schedule collision"
            mb[t, d] = m
            vs[t, d] = s
    return jnp.asarray(mb), jnp.asarray(vs)


def _gpipe(stage_params, layer_ids, x_mb, aux0, *, body_fn,
           num_microbatches, axis_name):
    """GPipe wavefront inside shard_map. stage_params leaves:
    (L/pp, ...) local shard; layer_ids: (L/pp,) global layer ids;
    x_mb: (M, B_mb, S_local, D) microbatched activations — replicated
    across pp; S_local = S/sp when ``pipeline_apply`` got a
    ``seq_axis`` (the stage body then holds only its sequence slice).
    Returns processed (M, B_mb, S_local, D) + summed aux."""
    pp = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    M = num_microbatches
    T = M + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    buf = jnp.zeros_like(x_mb[0])
    out = jnp.zeros_like(x_mb)
    aux_acc = aux0

    def tick(carry, t):
        buf, out, aux_acc = carry
        # stage idx processes microbatch t - idx while 0 <= t - idx < M
        mb_idx = jnp.clip(t - idx, 0, M - 1)
        # stage 0 injects microbatch t while t < M
        inject = x_mb[jnp.clip(t, 0, M - 1)]
        is_stage0 = (idx == 0)
        take = jnp.logical_and(is_stage0, t < M)
        buf = jnp.where(take, inject, buf)

        buf, aux = body_fn(stage_params, layer_ids, buf, mb_idx)
        # only count aux for ticks where this stage held real data:
        # stage i is busy for t in [i, i + M)
        busy = jnp.logical_and(t >= idx, t < idx + M)
        aux_acc = aux_acc + jnp.where(busy, aux, 0.0)

        # last stage banks microbatch t - (pp - 1)
        done_t = t - (pp - 1)
        is_last = (idx == pp - 1)
        bank = jnp.logical_and(is_last,
                               jnp.logical_and(done_t >= 0, done_t < M))
        out = jax.lax.dynamic_update_index_in_dim(
            out,
            jnp.where(bank, buf, out[jnp.clip(done_t, 0, M - 1)]),
            jnp.clip(done_t, 0, M - 1), axis=0)

        buf = jax.lax.ppermute(buf, axis_name, perm)
        return (buf, out, aux_acc), None

    (buf, out, aux_acc), _ = jax.lax.scan(
        jax.checkpoint(tick, prevent_cse=False), (buf, out, aux_acc),
        jnp.arange(T))
    del buf

    # results live on the last stage; broadcast to all stages so the
    # (replicated-over-pp) head/loss sees them: mask + psum.
    keep = (idx == pp - 1).astype(out.dtype)
    out = jax.lax.psum(out * keep, axis_name)
    # aux was accumulated per-stage over its own layers: sum of stages.
    aux_acc = jax.lax.psum(aux_acc, axis_name)
    return out, aux_acc


def _interleaved(stage_params, layer_ids, x_mb, aux0, *, body_fn,
                 num_microbatches, virtual_stages, axis_name):
    """Interleaved virtual-stage schedule inside shard_map.

    stage_params leaves: (L/pp, ...) — the local slice holds this
    device's ``v`` chunks back to back (chunk c = local layers
    [c·Lc, (c+1)·Lc), pre-permuted by the caller so chunk c is virtual
    stage ``c·pp + d``). Each tick applies ONE chunk, selected by
    ``lax.switch`` on the static schedule table, so a tick costs
    1/v of a GPipe tick and the fill bubble shrinks v-fold.
    x_mb's sequence dim is local (S/sp) when ``pipeline_apply`` got a
    ``seq_axis`` — same contract as ``_gpipe``."""
    pp = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    M = num_microbatches
    v = virtual_stages
    S = v * pp
    mb_tbl, vs_tbl = _interleave_tables(pp, M, v)
    T = mb_tbl.shape[0]
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    L_local = jax.tree.leaves(stage_params)[0].shape[0]
    Lc = L_local // v

    def chunk_body(c, buf, mb_idx):
        p_c = jax.tree.map(
            lambda leaf: jax.lax.dynamic_slice_in_dim(
                leaf, c * Lc, Lc, axis=0), stage_params)
        ids_c = jax.lax.dynamic_slice_in_dim(layer_ids, c * Lc, Lc)
        return body_fn(p_c, ids_c, buf, mb_idx)

    buf = jnp.zeros_like(x_mb[0])
    out = jnp.zeros_like(x_mb)
    aux_acc = aux0

    def tick(carry, t):
        buf, out, aux_acc = carry
        m_here = mb_tbl[t, idx]            # -1 when idle
        s_here = vs_tbl[t, idx]
        busy = m_here >= 0
        mb_idx = jnp.clip(m_here, 0, M - 1)
        chunk = jnp.clip(s_here // pp, 0, v - 1)

        inject = jnp.logical_and(busy, s_here == 0)
        buf = jnp.where(inject, x_mb[mb_idx], buf)

        branches = [functools.partial(chunk_body, c) for c in range(v)]
        new_buf, aux = jax.lax.switch(chunk, branches, buf, mb_idx)
        buf = jnp.where(busy, new_buf, buf)
        aux_acc = aux_acc + jnp.where(busy, aux, 0.0)

        bank = jnp.logical_and(busy, s_here == S - 1)
        out = jax.lax.dynamic_update_index_in_dim(
            out, jnp.where(bank, buf, out[mb_idx]), mb_idx, axis=0)

        buf = jax.lax.ppermute(buf, axis_name, perm)
        return (buf, out, aux_acc), None

    (buf, out, aux_acc), _ = jax.lax.scan(
        jax.checkpoint(tick, prevent_cse=False), (buf, out, aux_acc),
        jnp.arange(T))
    del buf

    # finished microbatches were banked on device pp-1 (virtual stage
    # S-1 lives there); broadcast like the GPipe path.
    keep = (idx == pp - 1).astype(out.dtype)
    out = jax.lax.psum(out * keep, axis_name)
    aux_acc = jax.lax.psum(aux_acc, axis_name)
    return out, aux_acc


def interleave_layer_order(L: int, pp: int, v: int) -> np.ndarray:
    """Permutation placing global layer order into interleaved device
    storage: device d's local slice holds chunks (0·pp+d, 1·pp+d, ...)
    back to back. Entry j of the result is the global layer stored at
    stacked position j."""
    Lc = L // (v * pp)
    order = []
    for d in range(pp):
        for c in range(v):
            s = c * pp + d
            order.extend(range(s * Lc, (s + 1) * Lc))
    return np.asarray(order, dtype=np.int32)


def pipeline_apply(body_fn: Callable, stacked_params, x: jax.Array,
                   mesh: Mesh, num_microbatches: int,
                   batch_axes=(), axis_name: str = AXIS_PP,
                   schedule: str = "gpipe", virtual_stages: int = 2,
                   seq_axis=None):
    """Apply ``body_fn`` (one stage-chunk's layers over one microbatch:
    ``(stage_params, layer_ids, x, mb_idx) -> (x, aux)``) as a pipeline.

    ``x``: (B, S, D) activations; B must divide into ``num_microbatches``.
    ``stacked_params``: pytree with leading layer dim on every leaf.
    ``layer_ids`` gives the stage body each layer's *global* index (for
    per-layer dropout rngs that are schedule-invariant); ``mb_idx`` the
    microbatch being processed this tick.
    ``schedule``: "gpipe", or "interleaved" with ``virtual_stages``
    chunks per device (requires L % (v·pp) == 0; costs one stacked-param
    gather per step to place chunks into device storage order).
    ``seq_axis``: mesh axis sharding the sequence dim of ``x`` (sp, for
    Ulysses attention inside the stage body); activations stay
    sequence-sharded as they rotate through stages — the pp ppermute
    moves each (pp, sp) shard to its pp-neighbor with the same sp index.
    Returns ``(x_out, aux_sum)`` with x_out shaped like x.
    """
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown schedule '{schedule}' (expected {SCHEDULES})")
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    pp = sizes.get(axis_name, 1)
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % pp:
        raise ValueError(f"{L} layers not divisible by {pp} stages")

    layer_ids = jnp.arange(L, dtype=jnp.int32)
    if schedule == "interleaved":
        if L % (virtual_stages * pp):
            raise ValueError(
                f"{L} layers not divisible by virtual_stages*pp="
                f"{virtual_stages * pp}")
        order = jnp.asarray(
            interleave_layer_order(L, pp, virtual_stages))
        stacked_params = jax.tree.map(
            lambda p: jnp.take(p, order, axis=0), stacked_params)
        layer_ids = jnp.take(layer_ids, order)

    # STRIDED microbatch split (microbatch m = rows m, m+M, m+2M, ...),
    # not contiguous chunks: each device's contiguous batch shard then
    # contributes the same dim-1 slot to every microbatch, so rows never
    # leave their home device. A contiguous (M, B/M, ...) reshape of the
    # (dp, fsdp)-sharded batch dim is a physical relayout, which GSPMD
    # resolves with an involuntary full rematerialization at the
    # shard_map boundary (replicate + repartition, every step). The
    # explicit constraints pin the boundary layout to the in/out specs
    # so the compiler can't shard the microbatch dim over pp either.
    x_mb = jnp.swapaxes(
        x.reshape(B // M, M, *x.shape[1:]), 0, 1)

    param_specs = jax.tree.map(
        lambda leaf: pipeline_spec(leaf.ndim), stacked_params)
    xspec = P(None, tuple(batch_axes) or None, seq_axis, None)
    x_mb = jax.lax.with_sharding_constraint(
        x_mb, NamedSharding(mesh, xspec))

    if schedule == "interleaved":
        inner = functools.partial(
            _interleaved, body_fn=body_fn, num_microbatches=M,
            virtual_stages=virtual_stages, axis_name=axis_name)
    else:
        inner = functools.partial(
            _gpipe, body_fn=body_fn, num_microbatches=M,
            axis_name=axis_name)

    fn = shard_map(
        inner,
        mesh=mesh,
        in_specs=(param_specs, P(AXIS_PP), xspec, P(None)),
        out_specs=(xspec, P(None)),
        check_rep=False,
    )
    # The aux accumulator crosses the shard_map boundary as shape (1,)
    # rather than a scalar: when the aux actually carries gradient
    # (MoE load-balancing loss), shard_map's partial-eval stages a
    # scalar residual whose out-names check fails (_SpecError) on this
    # jax — a rank-1 carry sidesteps it, and the squeeze below keeps
    # the external contract (scalar aux) unchanged.
    out_mb, aux = fn(stacked_params, layer_ids, x_mb,
                     jnp.zeros((1,), jnp.float32))
    out_mb = jax.lax.with_sharding_constraint(
        out_mb, NamedSharding(mesh, xspec))
    out = jnp.swapaxes(out_mb, 0, 1).reshape(B, *x.shape[1:])
    return out, aux[0]
