"""Pipeline parallelism (GPipe-style) over the ``pp`` mesh axis.

The transformer's decoder stack is already a *stacked-layer* pytree
(leaves shaped ``(L, ...)``, models/transformer.py), which makes pipeline
parallelism a sharding statement plus a schedule:

- **layout**: shard the stacked-layer leading dim over ``pp`` — stage
  ``i`` physically holds layers ``[i*L/pp, (i+1)*L/pp)``. This is the
  partition jit cannot exploit on its own (layers execute sequentially),
  hence the explicit schedule.
- **schedule**: split the batch into ``M`` microbatches and run the
  classic GPipe wavefront for ``M + pp - 1`` ticks inside ``shard_map``:
  stage 0 injects microbatch ``t``; every stage applies its local layers
  to its buffer; buffers rotate to the next stage via ``ppermute``
  (XLA collective-permute on ICI); the last stage banks finished
  microbatches. Bubble fraction is ``(pp-1)/(M+pp-1)`` — pick M ≫ pp.
- **backward**: plain autodiff. ``ppermute`` transposes to the reverse
  permute, so the same schedule runs backwards (activations rematerialize
  per-stage via the remat'd tick).

All devices execute the same program every tick (SPMD — no
data-dependent communication); stage roles differ only by masking on
``axis_index``. The reference repo has no pipeline (SURVEY.md §2.3);
this exists so deep models scale past one chip's HBM along depth as
well as width.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from distributed_training_tpu.runtime import AXIS_PP


def pipeline_spec(leaf_ndim: int) -> P:
    """Spec for a stacked-layer param leaf inside the pipeline
    shard_map: leading (layer) dim over pp, rest replicated."""
    return P(AXIS_PP, *([None] * (leaf_ndim - 1)))


def _pipelined(stage_params, x_mb, aux0, *, body_fn, num_microbatches,
               axis_name):
    """Runs inside shard_map. stage_params leaves: (L/pp, ...) local
    shard; x_mb: (M, B_mb, S, D) microbatched activations (replicated
    across pp); returns processed (M, B_mb, S, D) + summed aux."""
    pp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    M = num_microbatches
    T = M + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    buf = jnp.zeros_like(x_mb[0])
    out = jnp.zeros_like(x_mb)
    aux_acc = aux0

    def tick(carry, t):
        buf, out, aux_acc = carry
        # stage 0 injects microbatch t while t < M
        inject = x_mb[jnp.clip(t, 0, M - 1)]
        is_stage0 = (idx == 0)
        take = jnp.logical_and(is_stage0, t < M)
        buf = jnp.where(take, inject, buf)

        buf, aux = body_fn(stage_params, buf)
        # only count aux for ticks where this stage held real data:
        # stage i is busy for t in [i, i + M)
        busy = jnp.logical_and(t >= idx, t < idx + M)
        aux_acc = aux_acc + jnp.where(busy, aux, 0.0)

        # last stage banks microbatch t - (pp - 1)
        done_t = t - (pp - 1)
        is_last = (idx == pp - 1)
        bank = jnp.logical_and(is_last,
                               jnp.logical_and(done_t >= 0, done_t < M))
        out = jax.lax.dynamic_update_index_in_dim(
            out,
            jnp.where(bank, buf, out[jnp.clip(done_t, 0, M - 1)]),
            jnp.clip(done_t, 0, M - 1), axis=0)

        buf = jax.lax.ppermute(buf, axis_name, perm)
        return (buf, out, aux_acc), None

    (buf, out, aux_acc), _ = jax.lax.scan(
        jax.checkpoint(tick, prevent_cse=False), (buf, out, aux_acc),
        jnp.arange(T))
    del buf

    # results live on the last stage; broadcast to all stages so the
    # (replicated-over-pp) head/loss sees them: mask + psum.
    keep = (idx == pp - 1).astype(out.dtype)
    out = jax.lax.psum(out * keep, axis_name)
    # aux was accumulated per-stage over its own layers: sum of stages.
    aux_acc = jax.lax.psum(aux_acc, axis_name)
    return out, aux_acc


def pipeline_apply(body_fn: Callable, stacked_params, x: jax.Array,
                   mesh: Mesh, num_microbatches: int,
                   batch_axes=(), axis_name: str = AXIS_PP):
    """Apply ``body_fn`` (one stage's layers over one microbatch:
    ``(stage_params, x) -> (x, aux)``) as a GPipe pipeline.

    ``x``: (B, S, D) activations; B must divide into ``num_microbatches``.
    ``stacked_params``: pytree with leading layer dim on every leaf.
    Returns ``(x_out, aux_sum)`` with x_out shaped like x.
    """
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    pp = sizes.get(axis_name, 1)
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % pp:
        raise ValueError(f"{L} layers not divisible by {pp} stages")

    # STRIDED microbatch split (microbatch m = rows m, m+M, m+2M, ...),
    # not contiguous chunks: each device's contiguous batch shard then
    # contributes the same dim-1 slot to every microbatch, so rows never
    # leave their home device. A contiguous (M, B/M, ...) reshape of the
    # (dp, fsdp)-sharded batch dim is a physical relayout, which GSPMD
    # resolves with an involuntary full rematerialization at the
    # shard_map boundary (replicate + repartition, every step). The
    # explicit constraints pin the boundary layout to the in/out specs
    # so the compiler can't shard the microbatch dim over pp either.
    from jax.sharding import NamedSharding
    x_mb = jnp.swapaxes(
        x.reshape(B // M, M, *x.shape[1:]), 0, 1)

    param_specs = jax.tree.map(
        lambda leaf: pipeline_spec(leaf.ndim), stacked_params)
    xspec = P(None, tuple(batch_axes) or None, None, None)
    x_mb = jax.lax.with_sharding_constraint(
        x_mb, NamedSharding(mesh, xspec))

    fn = shard_map(
        functools.partial(_pipelined, body_fn=body_fn,
                          num_microbatches=M, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_specs, xspec, P()),
        out_specs=(xspec, P()),
        check_rep=False,
    )
    out_mb, aux = fn(stacked_params, x_mb, jnp.zeros((), jnp.float32))
    out_mb = jax.lax.with_sharding_constraint(
        out_mb, NamedSharding(mesh, xspec))
    out = jnp.swapaxes(out_mb, 0, 1).reshape(B, *x.shape[1:])
    return out, aux
