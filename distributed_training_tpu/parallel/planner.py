"""Auto-parallelism planner: search mesh × remat × batch, emit a plan.

ROADMAP item 1's answer to hand-picked parallelism: instead of each
strategy scattering its own ``PartitionSpec``s (the ad-hoc layout that
produced MULTICHIP_r05's involuntary-reshard cliff), the planner
SEARCHES the layout space for a model config and device count and
emits one resolved, serializable **sharding plan** — a
sharding-map-by-name (SNIPPETS [1]/[3] pattern; veScale's "one
consistent SPMD spec source") that the trainer, ``__graft_entry__``,
and ``benchmarks/bench_multichip.py`` all compile against.

Search space (``enumerate_candidates``):
- mesh shape: every ``pp/dp/fsdp/sp/tp`` factorization of the device
  count that the model admits (sp needs a sequence-parallel attention
  impl and ``seq % sp == 0``; tp needs head/kv/ff divisibility; pp is
  gated behind ``allow_pp`` — stage-local layouts are owned by the
  pipeline's shard_map, not the SPMD map this planner resolves);
- remat policy: ``none`` / ``mlp_pre`` / ``mlp`` (the measured ladder
  from the single-chip headline work);
- per-shard batch: the target's candidate set.

Cost model (``score_candidate``), composed from existing subsystems so
there is exactly one of each:
- HBM fit: ``utils/memory.py::estimate_transformer_memory`` (the same
  calibrated model ``benchmarks/plan_memory.py`` prints — that script
  is now a thin wrapper over ``hbm_plan_record`` here). Over-budget
  candidates are rejected outright.
- throughput proxy: a compute/comms roofline — compute seconds from
  the model's FLOPs accounting × a remat recompute factor, comms
  seconds from an analytic per-step collective-bytes model (grad
  sync over data axes, tp activation all-reduces, sp ring rotations).
  Both halves are CALIBRATED when a committed measurement exists
  (``conf/calibration/<chip>.json`` — benchmarks/calibrate.py): the
  comms half prices each collective KIND's bytes on the measured
  piecewise latency/bandwidth curve, the compute half uses the
  measured achievable-FLOPs curve instead of the spec-sheet peak.
  Without a matching table each kind falls back to the per-chip
  NOMINAL constants (``NOMINAL_ICI_BYTES_PER_S`` — per device kind,
  so a v4 and a v5e rank differently where their interconnects
  would). Which source scored a plan is recorded in provenance
  (``calibration``) and verified by ``--check`` — re-calibrating the
  chip fails every plan scored from the older table until it is
  re-planned. Step time = max(compute, comms) × a pipeline-bubble
  factor. Score = tokens/step ÷ step seconds.
- reshard cleanliness: the top-ranked candidates are compiled
  abstractly (``analysis/compile.py`` — the REAL trainer, chip-free)
  and any ``SPMD001`` involuntary-reshard warning **disqualifies the
  candidate outright** (``telemetry/collectives.py`` parses the same
  stderr the audit ratchet gates on). The measured collective bytes
  of the winner are recorded as provenance.

Everything is deterministic: pure enumeration, stable sort keys, no
clocks, no randomness — the same target always resolves to the same
plan and fingerprint, which is what ``--check`` (ratchet style, wired
into the tier-1 gate) verifies against the committed plans in
``conf/plans/``. ``--check`` re-runs the cheap stages (enumeration,
scoring, sharding-map resolution, fingerprint) and trusts the
committed plan's recorded compile evidence; the SPMD audit gate
(``python -m distributed_training_tpu.analysis --check``) owns the
recompile that proves the plan is STILL reshard-clean on this XLA.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable

PLAN_SCHEMA = 1

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PLANS_DIR = os.path.join(REPO, "conf", "plans")

# Remat recompute multiplier on total step FLOPs (fwd+bwd ≈ 3x fwd):
# "mlp" recomputes the two F-wide MLP matmul/gelu tensors (~+11% of
# forward ≈ +4% of total); "mlp_pre" saves the pre-gelu tensor and
# recomputes only the elementwise gelu (~+2%). Constants, not
# measurements — they only need to rank policies correctly (none
# fastest when it fits), and docs/performance.md documents them.
REMAT_POLICIES = ("none", "mlp_pre", "mlp")
REMAT_RECOMPUTE = {"none": 1.0, "mlp_pre": 1.02, "mlp": 1.04}

# Nominal fallback ICI bandwidth for the comms half of the roofline
# when no calibration table matches the target chip. PER DEVICE KIND
# (spec-sheet interconnect numbers: v4 2.4 Tb/s, v5e 1.6, v5p 4.8,
# v6e ~3.6; "cpu" keeps the historical ranking constant): absolute
# step times are not the claim, but relative compute-vs-comms
# pressure differs per chip, and pretending every kind has v5e's
# wires mis-ranks candidates near the roofline crossover. Keyed by
# the calibration layer's canonical chip slug so "v5 lite",
# "v5litepod", and "v5e" all resolve to ONE row — nominal fallback
# and measured-table lookup share a single normalization
# (calibration/table.py::chip_slug).
ICI_BYTES_PER_S = 1.0e11  # unknown-kind fallback (historical value)
NOMINAL_ICI_BYTES_PER_S = {
    "v4": 3.0e11,
    "v5e": 2.0e11,
    "v5p": 6.0e11,
    "v6e": 4.48e11,
    "cpu": 1.0e11,
}


def nominal_ici_bytes_per_s(chip: str) -> float:
    """Per-kind nominal ICI bandwidth (same chip normalization as
    the measured-table lookup; unknown kinds get the historical
    one-size constant)."""
    from distributed_training_tpu.calibration import chip_slug
    return NOMINAL_ICI_BYTES_PER_S.get(chip_slug(chip),
                                       ICI_BYTES_PER_S)

MESH_AXES = ("pp", "dp", "fsdp", "sp", "tp")


class PlanError(ValueError):
    pass


def _canon(obj):
    """JSON-canonical form (tuples become lists) so in-memory targets
    compare equal to their round-tripped committed form."""
    return json.loads(json.dumps(obj, sort_keys=True))


def _doc_digest(doc: dict) -> str:
    """sha256 over the canonical plan document, ``integrity`` field
    excluded (it holds this digest)."""
    body = {k: v for k, v in doc.items() if k != "integrity"}
    blob = json.dumps(_canon(body), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# Targets: named configs the repo commits plans for
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PlanTarget:
    """Everything that determines the search: model, devices, budget,
    candidate sets. A target is the unit ``--write``/``--check``
    operate on; its resolved plan is committed to ``conf/plans/``."""

    name: str
    devices: int
    model_kwargs: dict          # WITHOUT remat keys (the search owns them)
    seq_len: int
    optimizer: str = "adamw"
    chip: str = "v5e"           # HBM budget + peak-FLOPs lookup
    hbm_gib: float | None = None  # override the chip's HBM capacity
    headroom: float = 0.85      # usable fraction (XLA scratch)
    batch_candidates: tuple = (1, 2, 4, 8)
    remat_candidates: tuple = REMAT_POLICIES
    min_shard_elems: int = 1
    allow_pp: bool = False
    # Stage 2 budget: how many top-ranked candidates may be compiled
    # while hunting a reshard-clean winner before giving up.
    max_compiles: int = 4
    # What the plan optimizes. "train": step throughput under the
    # training memory model (params+grads+optimizer+activations) —
    # the historical objective. "decode": AGGREGATE serving decode
    # tokens/second with HBM-FOR-KV feasibility (params + this
    # device's pool shard must fit; the slot table batch-shards over
    # dp — serving/engine.py's shard_map — so dp divides pool bytes
    # and step latency for free while a layout that all-gathers
    # weights per token prices itself out). "prefill": forward-only
    # chunk THROUGHPUT (no grad/optimizer state, no backward
    # collectives) — the engine's prompt side. The serving
    # objectives fix remat to "none" (no backward to trade memory
    # against) and exclude sp/pp (the decode/prefill programs have
    # no sequence-parallel or pipelined form).
    objective: str = "train"
    # Weight storage the serving objectives price params at: "none"
    # (fp32) or "int8" (weight-only per-channel — serving/disagg.py
    # quantize_params_int8; ~4× fewer attention/FFN param bytes per
    # device, scales included). Feasibility-only: the compute model
    # is unchanged (dequant-at-compute runs the same einsums).
    quant: str = "none"
    note: str = ""

    def __post_init__(self):
        if self.objective not in ("train", "decode", "prefill"):
            raise PlanError(
                f"unknown plan objective '{self.objective}' "
                "(expected 'train', 'decode' or 'prefill')")
        if self.quant not in ("none", "int8"):
            raise PlanError(
                f"unknown plan quant '{self.quant}' "
                "(expected 'none' or 'int8')")
        if self.quant != "none" and self.objective == "train":
            raise PlanError(
                "quant is a serving-objective knob (weight-only "
                "int8 has no train-objective memory model)")

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if self.objective == "train":
            # Back-compat: committed train plans predate the
            # objective field; their recorded inputs must keep
            # matching this target's canonical form under --check.
            d.pop("objective")
        if self.quant == "none":
            # Same back-compat discipline for the quant field: every
            # committed fp32 plan predates it.
            d.pop("quant")
        return d


PLAN_TARGETS: dict[str, PlanTarget] = {}


def _register(t: PlanTarget) -> PlanTarget:
    PLAN_TARGETS[t.name] = t
    return t


_register(PlanTarget(
    name="multichip_8dev",
    devices=8,
    model_kwargs=dict(vocab_size=256, d_model=64, n_heads=4,
                      n_kv_heads=2, n_layers=2, max_seq_len=32,
                      attention_impl="ring", attention_window=24,
                      dtype="float32", param_dtype="float32"),
    seq_len=32,
    optimizer="adamw",
    chip="v5e",
    note="The MULTICHIP_r05 dryrun model (windowed GQA, ring-capable) "
         "promoted to a planned, measured 8-device benchmark — "
         "benchmarks/bench_multichip.py runs real steps against this "
         "plan and MULTICHIP_r06.json records the result.",
))


_register(PlanTarget(
    name="multichip_8dev_cpu",
    devices=8,
    model_kwargs=dict(vocab_size=256, d_model=64, n_heads=4,
                      n_kv_heads=2, n_layers=2, max_seq_len=32,
                      attention_impl="ring", attention_window=24,
                      dtype="float32", param_dtype="float32"),
    seq_len=32,
    optimizer="adamw",
    chip="cpu",
    hbm_gib=16.0,
    note="The multichip_8dev model scored against the MEASURED cpu "
         "calibration table (conf/calibration/cpu.json, "
         "benchmarks/calibrate.py) — the calibrated-cost-model path "
         "exercised end-to-end in CI: planner --check validates this "
         "plan's recorded calibration fingerprint against the "
         "committed table, and benchmarks/bench_multichip.py "
         "--plan multichip_8dev_cpu measures it (MULTICHIP_r07).",
))


# The serving-plan model: the byte-vocab tiny transformer the serving
# bench and tests decode (rope so position handling exercises the
# per-row decode path; no MoE — the engine rejects it). One kwargs
# dict shared by all three serving targets so prefill and decode plans
# provably describe ONE model (the disaggregation contract).
SERVING_MODEL_KWARGS = dict(vocab_size=256, d_model=64, n_heads=4,
                            n_kv_heads=2, n_layers=2, max_seq_len=64,
                            pos_encoding="rope", dtype="float32",
                            param_dtype="float32")

# The chunk width the prefill objective scores the batched lane
# program at — engine_config_for_plan's default prefill_chunk, so the
# scored program and the disagg pipeline's compiled program agree.
SERVING_PREFILL_CHUNK = 16

_register(PlanTarget(
    name="serving_8dev_cpu_decode",
    devices=8,
    model_kwargs=dict(SERVING_MODEL_KWARGS),
    seq_len=64,
    optimizer="none",
    chip="cpu",
    # HBM budget sized so the all-dp layout (dp8·tp1 — pool fully
    # batch-sharded but params REPLICATED on every device) does not
    # fit, while dp4·tp2 (params + kv heads sharded over tp, slots
    # dealt over dp) does — the decode objective's forced choice
    # since the slot table batch-shards over dp: dp is free
    # throughput, tp costs all-reduces but is the only thing that
    # shrinks resident params; the budget makes tp mandatory and dp
    # soaks up the rest (docs/serving.md works the math).
    hbm_gib=0.0005,
    batch_candidates=(32,),
    objective="decode",
    note="The serving decode plan benchmarks/bench_serving.py lays "
         "the engine out with (SERVING_r02/r03): 32 decode slots "
         "dealt over dp4 groups of 8, paged KV pool sharded dp×tp; "
         "r03's speculative multi-token decode rides the same "
         "layout (the chunk program deals lanes over dp "
         "identically). Audited reshard-clean by the "
         "serving_decode_planned analysis target.",
))

_register(PlanTarget(
    name="serving_4dev_cpu_prefill",
    devices=4,
    model_kwargs=dict(SERVING_MODEL_KWARGS),
    seq_len=64,
    optimizer="none",
    chip="cpu",
    hbm_gib=0.002,
    batch_candidates=(8,),
    objective="prefill",
    note="Prefill-slice layout for the disaggregated pipeline "
         "(serving/disagg.py): the BATCHED multi-sequence prefill "
         "program (SERVING_r03) — 8 lanes dealt over the plan's dp "
         "groups, one prompt chunk per lane per launch — scored for "
         "aggregate prompt tokens/second over half the 8-device CPU "
         "mesh; resolved against the SAME model as "
         "serving_4dev_cpu_decode — two plans, one weight store. "
         "Audited reshard-clean by the serving_prefill_planned "
         "analysis target.",
))

_register(PlanTarget(
    name="serving_4dev_cpu_decode",
    devices=4,
    model_kwargs=dict(SERVING_MODEL_KWARGS),
    seq_len=64,
    optimizer="none",
    chip="cpu",
    # Same params-force-tp squeeze as the 8-device decode target, at
    # the 4-device slice's 16 slots: dp4·tp1 (replicated params) out,
    # dp2·tp2 in.
    hbm_gib=0.0005,
    batch_candidates=(16,),
    objective="decode",
    note="Decode-slice layout for the disaggregated pipeline: the KV "
         "cache written by the prefill slice's batched lane program "
         "is handed off onto this layout (serving/disagg.py) and "
         "decode continues there (speculative multi-token capable, "
         "SERVING_r03).",
))

_register(PlanTarget(
    name="serving_8dev_cpu_decode_int8",
    devices=8,
    model_kwargs=dict(SERVING_MODEL_KWARGS),
    seq_len=64,
    optimizer="none",
    chip="cpu",
    # SAME budget as the fp32 decode target — the squeeze that made
    # tp mandatory there. Weight-only int8 shrinks resident params
    # ~4× (serving/disagg.py), so layouts fp32 priced out re-enter:
    # the planner may now spend the freed bytes on dp instead of tp
    # (dp is free aggregate throughput, tp pays all-reduces) — the
    # int8 HBM credit changing the CHOSEN MESH is the planner-level
    # proof the quantization matters, not just a smaller number.
    hbm_gib=0.0005,
    batch_candidates=(32,),
    objective="decode",
    quant="int8",
    note="The serving_8dev_cpu_decode target served from an int8 "
         "weight-only store (checkpoint/export.py --quantize int8): "
         "same model, same budget, params priced at 1 byte/elem + "
         "per-channel scales. SERVING_r04's quantized bench lane "
         "lays the engine out with this plan.",
))


def resolve_targets(names=None) -> list[PlanTarget]:
    if not names:
        return list(PLAN_TARGETS.values())
    out = []
    for n in names:
        if n not in PLAN_TARGETS:
            raise KeyError(f"unknown plan target '{n}'; available: "
                           f"{sorted(PLAN_TARGETS)}")
        out.append(PLAN_TARGETS[n])
    return out


# ---------------------------------------------------------------------------
# The plan artifact
# ---------------------------------------------------------------------------


@dataclass
class Plan:
    """A resolved parallelism decision: mesh shape, remat policy,
    per-shard batch, and the full sharding-map-by-name. Serializable
    (JSON, ``schema`` 1) and fingerprinted so consumers can assert
    they run exactly what the planner chose."""

    name: str
    devices: int
    mesh: dict                  # all five axes, all >= 1
    base_strategy: str          # spec-generator family: ddp|fsdp|tp
    remat: str                  # none|mlp_pre|mlp
    batch_per_shard: int
    seq_len: int
    batch_axes: list            # batch-dim mesh axes, e.g. ["dp","fsdp"]
    sharding_map: dict          # param path -> per-dim axis entries
    inputs: dict = field(default_factory=dict)   # the PlanTarget
    provenance: dict = field(default_factory=dict)

    @property
    def data_shards(self) -> int:
        return self.mesh["dp"] * self.mesh["fsdp"]

    @property
    def global_batch(self) -> int:
        return self.batch_per_shard * self.data_shards

    @property
    def candidate_key(self) -> str:
        """The search-candidate identity this plan resolves — MUST
        stay the single implementation ``Candidate.key`` also uses
        (the --check winner comparison matches on it)."""
        m = ".".join(f"{a}{self.mesh[a]}" for a in MESH_AXES)
        return f"{m}/{self.remat}/b{self.batch_per_shard}"

    def xla_overlap_flags(self, platform: str) -> dict:
        """The XLA latency-hiding/combiner flag set this plan wants
        on ``platform`` (``parallel/overlap.py`` — derived from the
        plan's mesh and measured collective bytes; ``{}`` when there
        is nothing to hide). Consumers: ``train/cli.py``,
        ``launch/local.py``, ``benchmarks/bench_multichip.py``, and
        the SPMD-audit targets (as per-compile compiler options)."""
        from distributed_training_tpu.parallel import overlap
        ev = (self.provenance or {}).get("compile_evidence") or {}
        return overlap.flags_for(
            platform, mesh=self.mesh,
            collective_bytes_per_step=ev.get(
                "collective_bytes_per_step"))

    def fingerprint(self) -> str:
        """Identity of the RESOLVED layout (search inputs included so
        two plans from different searches can never collide silently);
        provenance — scores, compile evidence — is derived, not
        identity, and is tamper-guarded separately by the integrity
        digest ``save_plan`` writes."""
        doc = {k: getattr(self, k) for k in (
            "name", "devices", "mesh", "base_strategy", "remat",
            "batch_per_shard", "seq_len", "batch_axes",
            "sharding_map", "inputs")}
        blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def to_doc(self) -> dict:
        doc = {
            "schema": PLAN_SCHEMA,
            "fingerprint": self.fingerprint(),
            **{k: getattr(self, k) for k in (
                "name", "devices", "mesh", "base_strategy", "remat",
                "batch_per_shard", "seq_len", "batch_axes",
                "sharding_map", "inputs", "provenance")},
        }
        # Whole-document digest: the fingerprint pins the resolved
        # IDENTITY, but --check also trusts the recorded provenance
        # (ranking, disqualifications, compile evidence) — a hand
        # edit there must refuse to load just as loudly.
        doc["integrity"] = _doc_digest(doc)
        return doc

    @staticmethod
    def from_doc(doc: dict) -> "Plan":
        if doc.get("schema") != PLAN_SCHEMA:
            raise PlanError(
                f"plan schema {doc.get('schema')!r} != {PLAN_SCHEMA} "
                "— regenerate with planner --write")
        recorded_digest = doc.get("integrity")
        if recorded_digest and recorded_digest != _doc_digest(doc):
            raise PlanError(
                f"plan '{doc.get('name')}' integrity digest mismatch "
                "— the file (provenance included) was hand-edited; "
                "regenerate with --write")
        plan = Plan(**{k: doc[k] for k in (
            "name", "devices", "mesh", "base_strategy", "remat",
            "batch_per_shard", "seq_len", "batch_axes", "sharding_map",
            "inputs", "provenance")})
        recorded = doc.get("fingerprint")
        if recorded and recorded != plan.fingerprint():
            raise PlanError(
                f"plan '{plan.name}' fingerprint mismatch: file says "
                f"{recorded}, content hashes to {plan.fingerprint()} "
                "— the file was hand-edited; regenerate with --write")
        return plan


def plan_path(name: str) -> str:
    return os.path.join(PLANS_DIR, f"{name}.json")


def load_plan(name_or_path: str) -> Plan:
    """Load a committed plan by name (``conf/plans/<name>.json``) or
    any explicit path."""
    path = name_or_path
    if not os.path.exists(path):
        path = plan_path(name_or_path)
        if not os.path.exists(path):
            raise PlanError(
                f"no plan at '{name_or_path}' and no committed plan "
                f"named '{name_or_path}' in {PLANS_DIR}")
    with open(path, encoding="utf-8") as f:
        return Plan.from_doc(json.load(f))


def save_plan(plan: Plan, path: str | None = None) -> str:
    path = path or plan_path(plan.name)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(plan.to_doc(), f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# Candidate enumeration
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Candidate:
    pp: int
    dp: int
    fsdp: int
    sp: int
    tp: int
    remat: str
    batch_per_shard: int

    @property
    def mesh(self) -> dict:
        return {a: getattr(self, a) for a in MESH_AXES}

    @property
    def key(self) -> str:
        m = ".".join(f"{a}{getattr(self, a)}" for a in MESH_AXES)
        return f"{m}/{self.remat}/b{self.batch_per_shard}"


def _factorizations(n: int, axes: int):
    """All ordered tuples of ``axes`` positive ints with product n."""
    if axes == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, axes - 1):
                yield (d,) + rest


def enumerate_candidates(target: PlanTarget) -> list[Candidate]:
    """Every (mesh, remat, batch) the model and device count admit.

    Divisibility constraints mirror what the model/attention layers
    would reject at trace time — enumeration must never emit a
    candidate that cannot compile for SHAPE reasons (reshard findings
    are stage 2's job, shape validity is stage 1's):
    - ``sp > 1`` only with a sequence-parallel attention impl, and
      ``seq % sp == 0`` (contiguous sequence shards);
    - ring attention shards kv heads over tp inside its shard_map:
      ``n_kv_heads % tp == 0`` and ``n_heads % tp == 0``;
    - ulysses trades heads for sequence: ``heads % (tp*sp) == 0``;
    - ``pp > 1`` needs ``n_layers % pp == 0`` and is gated behind
      ``allow_pp`` (the pipeline's stage-local shard_map owns its own
      layouts — out of scope for the SPMD map this planner resolves);
    - tp sharding of the MLP needs ``d_ff % tp == 0``.
    """
    mk = dict(target.model_kwargs)
    n_heads = mk.get("n_heads", 12)
    n_kv = mk.get("n_kv_heads", 0) or n_heads
    d_model = mk.get("d_model", 768)
    d_ff = mk.get("d_ff", 0) or 4 * d_model
    n_layers = mk.get("n_layers", 12)
    impl = mk.get("attention_impl", "auto")
    seq_parallel = impl in ("ring", "ulysses")

    serving = target.objective in ("decode", "prefill")
    remat_cands = (("none",) if serving
                   else tuple(target.remat_candidates))

    out: list[Candidate] = []
    for pp, dp, fsdp, sp, tp in _factorizations(target.devices, 5):
        if serving and (pp > 1 or sp > 1):
            # The serving decode/prefill programs have no pipelined
            # or sequence-parallel form (engine.py) — such a mesh
            # could not compile the program the plan is for.
            continue
        if pp > 1 and (not target.allow_pp or n_layers % pp):
            continue
        if sp > 1 and (not seq_parallel or target.seq_len % sp):
            continue
        if tp > 1 and (n_heads % tp or n_kv % tp or d_ff % tp):
            continue
        if impl == "ulysses" and sp > 1 and (
                n_heads % (tp * sp) or n_kv % (tp * sp)):
            continue
        for remat in remat_cands:
            for b in target.batch_candidates:
                out.append(Candidate(pp, dp, fsdp, sp, tp, remat, b))
    return out


# ---------------------------------------------------------------------------
# Cost model (stage 1: analytic, no compiles)
# ---------------------------------------------------------------------------


def _tf_cfg(target: PlanTarget, remat: str):
    from distributed_training_tpu.models.transformer import (
        TransformerConfig)
    mk = dict(target.model_kwargs)
    mk.pop("remat", None)
    mk.pop("remat_policy", None)
    if remat == "none":
        return TransformerConfig(remat=False, **mk)
    return TransformerConfig(remat=True, remat_policy=remat, **mk)


def _n_params(target: PlanTarget) -> int:
    import jax

    from distributed_training_tpu.models.transformer import Transformer
    from distributed_training_tpu.utils.memory import param_count
    model = Transformer(_tf_cfg(target, "none"))
    return param_count(jax.eval_shape(model.init,
                                      jax.random.PRNGKey(0)))


def hbm_budget_gib(target: PlanTarget) -> float:
    from distributed_training_tpu.utils.memory import HBM_GIB
    cap = (target.hbm_gib if target.hbm_gib is not None
           else HBM_GIB[target.chip])
    return cap * target.headroom


def resolve_calibration(target: PlanTarget):
    """The calibration feeding this target's cost model: a
    ``CalibrationLookup`` for the committed
    ``conf/calibration/<chip>.json`` matching ``target.chip``
    (``table`` is None on fallback, ``status`` says why). One
    resolution shared by ``plan_search`` and ``check_plan`` so the
    search and its verifier can never consult different tables."""
    from distributed_training_tpu.calibration import lookup_for_chip
    return lookup_for_chip(target.chip)


def calibration_provenance(target: PlanTarget, calib, note: str
                           ) -> dict:
    """The ``calibration`` block a plan's provenance records — the
    drift anchor ``check_plan`` compares against the committed table
    (source + fingerprint; ``nominal`` records the per-kind constants
    actually used, so a nominal-scored plan drifts loudly too when
    someone later lands a table for its chip)."""
    from distributed_training_tpu.utils.metrics import (
        peak_flops_per_chip)
    if calib is not None:
        return {"source": "measured", "chip": target.chip,
                "device_kind": calib.device_kind,
                "fingerprint": calib.fingerprint(), "note": note}
    return {"source": "nominal", "chip": target.chip,
            "fingerprint": None,
            "nominal_ici_bytes_per_s": nominal_ici_bytes_per_s(
                target.chip),
            "nominal_peak_flops_per_s": peak_flops_per_chip(
                target.chip),
            "note": note}


def score_candidate(target: PlanTarget, cand: Candidate,
                    n_params: int | None = None,
                    calib="auto") -> dict:
    """Analytic feasibility + throughput proxy for one candidate.

    Returns a record with ``feasible`` (False carries ``reason``),
    the per-chip HBM estimate, the compute/comms roofline seconds,
    and ``score`` (tokens per second proxy — higher is better). Pure
    function of (target, candidate, calibration table): no clocks,
    no device state. ``calib`` is a ``CalibrationTable`` (measured
    curves), ``None`` (per-kind nominal constants), or ``"auto"``
    (resolve the committed table for ``target.chip``)."""
    from distributed_training_tpu.models.transformer import Transformer
    from distributed_training_tpu.utils.memory import (
        estimate_transformer_memory)
    from distributed_training_tpu.utils.metrics import (
        peak_flops_per_chip)

    if calib == "auto":
        calib = resolve_calibration(target).table
    if target.objective in ("decode", "prefill"):
        return _score_serving(target, cand, n_params, calib)
    cfg = _tf_cfg(target, cand.remat)
    if n_params is None:
        n_params = _n_params(target)
    seq_local = target.seq_len // cand.sp
    est_cfg = (dataclasses.replace(cfg, n_layers=cfg.n_layers // cand.pp)
               if cand.pp > 1 else cfg)
    est = estimate_transformer_memory(
        est_cfg, batch_per_chip=cand.batch_per_shard,
        seq_len=seq_local, optimizer=target.optimizer,
        fsdp=cand.fsdp, tp=cand.tp)
    rec: dict = {
        "candidate": cand.key,
        "mesh": cand.mesh,
        "remat": cand.remat,
        "batch_per_shard": cand.batch_per_shard,
        "hbm_gib": round(est.total_gib, 4),
        "hbm_budget_gib": round(hbm_budget_gib(target), 4),
    }
    if est.total_gib > hbm_budget_gib(target):
        rec.update(feasible=False, reason="hbm", score=0.0)
        return rec

    # Compute roofline: model FLOPs at the candidate's global batch,
    # scaled by the remat recompute factor, over every chip's
    # ACHIEVABLE throughput — the measured matmul curve when a
    # calibration table matches the chip, the spec-sheet peak
    # otherwise.
    model = Transformer(cfg)
    global_batch = cand.batch_per_shard * cand.dp * cand.fsdp
    flops_step = (model.flops_per_token(target.seq_len) * target.seq_len
                  * global_batch * REMAT_RECOMPUTE[cand.remat])
    flops_per_dev = flops_step / target.devices
    if calib is not None:
        compute_s = flops_per_dev / calib.achievable_flops_per_s(
            flops_per_dev)
    else:
        compute_s = flops_per_dev / peak_flops_per_chip(target.chip)

    # Comms roofline: analytic per-device bytes per step, SPLIT BY
    # COLLECTIVE KIND (the granularity calibration measures). param
    # bytes use the stored dtype (grad sync moves masters),
    # activation terms the compute dtype.
    pb = {"float32": 4, "bfloat16": 2, "float16": 2}[cfg.param_dtype]
    ab = {"float32": 4, "bfloat16": 2, "float16": 2}[cfg.dtype]
    P_store = n_params * pb / cand.pp
    B, S_l, D = cand.batch_per_shard, seq_local, cfg.d_model
    kv_width = cfg.n_kv_heads * cfg.head_dim
    by_kind = {k: 0.0 for k in ("all-gather", "reduce-scatter",
                                "all-reduce", "ppermute")}
    if cand.fsdp > 1:
        # Weights all-gather for compute (compute dtype) + gradient
        # reduce-scatter (stored dtype): each ~param-scale per step.
        by_kind["all-gather"] += n_params * ab / cand.pp
        by_kind["reduce-scatter"] += P_store
    if cand.dp > 1:
        # Pure-replica gradient all-reduce over dp of each fsdp shard
        # (2x tensor bytes: the ring's RS+AG phases — the accounted-
        # bytes convention calibration/table.py measures against).
        by_kind["all-reduce"] += 2.0 * P_store / cand.fsdp
    if cand.tp > 1:
        # Activation all-reduces at the attn/mlp block boundaries,
        # forward and backward: 4 crossings per layer of a (B, S, D)
        # tensor, each at the same 2x accounted-bytes convention as
        # the dp term above (ring RS+AG phases) so one all-reduce
        # curve prices both.
        by_kind["all-reduce"] += (2.0 * 4.0 * cfg.n_layers
                                  * B * S_l * D * ab)
    if cand.sp > 1:
        # Ring rotations: K/V around the ring in forward, K/V plus
        # their gradient accumulators in backward — ~3 full cycles of
        # 2 kv-width blocks.
        by_kind["ppermute"] += (6.0 * cfg.n_layers * B * S_l
                                * kv_width * ab * (cand.sp - 1))
    comms = sum(by_kind.values())
    if calib is not None:
        comms_s = sum(calib.collective_seconds(k, b)
                      for k, b in by_kind.items() if b > 0)
    else:
        comms_s = comms / nominal_ici_bytes_per_s(target.chip)

    bubble = ((cand.pp - 1) / max(1, cfg.pp_microbatches)
              if cand.pp > 1 else 0.0)
    step_s = max(compute_s, comms_s) * (1.0 + bubble)
    tokens = global_batch * target.seq_len
    rec.update(
        feasible=True,
        reason="",
        compute_s=compute_s,
        comms_s=comms_s,
        comms_bytes=int(comms),
        comms_bytes_by_kind={k: int(b) for k, b in by_kind.items()
                             if b > 0},
        calibrated=calib is not None,
        tokens_per_step=tokens,
        score=tokens / step_s if step_s > 0 else 0.0,
    )
    return rec


def _score_serving(target: PlanTarget, cand: Candidate,
                   n_params: int | None, calib) -> dict:
    """Serving-objective scoring (objective "decode"/"prefill").

    The training objective maximizes step THROUGHPUT under the
    training memory model; serving wants something else entirely:

    - **decode**: score = AGGREGATE decode tokens/second (one token
      per sequence per step across the dealt slot table; step
      latency is the denominator, so dp's batch-parallel groups are
      credited without any new collective — decode rows are
      independent), and feasibility is HBM-FOR-KV: per-device params
      + this device's shard of the paged KV pool for
      ``batch_per_shard`` sequences of ``seq_len`` tokens must fit
      the budget. The pool shards over ``dp`` (the batch-sharded
      slot groups, serving/engine.py's shard_map) × ``tp`` (kv heads
      — serving/kv_cache.py's head axis); params shard only over
      ``tp``/``fsdp``, and ``fsdp`` pays a FULL weight all-gather
      every decode step, which the comms term prices — exactly the
      trade that forces tp in once per-device params + pool stop
      fitting replicated, while dp soaks up the remaining devices
      for free throughput.
    - **prefill**: the BATCHED multi-sequence prefill program
      (serving/engine.py ``build_prefill_batch_fn``, SERVING_r03):
      ``batch_per_shard`` is the aggregate LANE count, dealt over
      ``dp`` exactly like the decode slot table (``slots % dp``
      feasibility), each lane a ``SERVING_PREFILL_CHUNK``-token
      prompt chunk. dp divides the lane compute, the prompt-KV pool,
      and the per-group tp activation traffic with zero new
      collectives (lanes are independent); tp pays the activation
      all-reduces; fsdp pays a full weight all-gather per LAUNCH —
      score = aggregate prompt tokens/second at full chunk occupancy
      (slots × chunk per launch).

    Both use the same calibrated collective/matmul curves as the
    train objective (one cost model, three objectives).
    """
    from distributed_training_tpu.models.transformer import Transformer
    from distributed_training_tpu.utils.metrics import (
        peak_flops_per_chip)

    cfg = _tf_cfg(target, "none")
    if n_params is None:
        n_params = _n_params(target)
    pb = {"float32": 4, "bfloat16": 2, "float16": 2}[cfg.param_dtype]
    ab = {"float32": 4, "bfloat16": 2, "float16": 2}[cfg.dtype]
    S = target.seq_len
    B_shard = cand.batch_per_shard
    D = cfg.d_model
    params_dev = n_params * pb / (cand.fsdp * cand.tp)
    if target.quant == "int8":
        # Weight-only int8 credit (serving/disagg.py _QUANT_AXES):
        # the attention + FFN matmul weights store 1 byte/elem, their
        # per-output-channel scales 4 bytes each, everything else
        # (embeddings, norms, biases) stays at param-dtype bytes.
        # Feasibility-only — layouts that replicated themselves out
        # of budget at fp32 (dp-heavy, params unsharded) come back
        # in, which is the whole point of serving int8.
        hd = cfg.head_dim
        q_elems = cfg.n_layers * (
            2 * D * cfg.n_heads * hd          # wq + wo
            + 2 * D * cfg.n_kv_heads * hd     # wk + wv
            + 2 * D * cfg.d_ff)               # mlp wi + wo
        s_elems = cfg.n_layers * (
            cfg.n_heads * hd                  # wq scales
            + 2 * cfg.n_kv_heads * hd         # wk + wv scales
            + D                               # wo scales
            + cfg.d_ff + D)                   # mlp wi + wo scales
        params_dev = ((n_params - q_elems) * pb + q_elems
                      + 4 * s_elems) / (cand.fsdp * cand.tp)
    kv_tok = 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * ab
    budget = hbm_budget_gib(target) * 2**30

    rec: dict = {
        "candidate": cand.key,
        "mesh": cand.mesh,
        "remat": cand.remat,
        "batch_per_shard": B_shard,
        "hbm_budget_gib": round(hbm_budget_gib(target), 6),
    }
    if target.quant != "none":
        rec["quant"] = target.quant
    if target.objective == "decode":
        # Decode semantics (engine.py): the SLOT TABLE is BATCH-
        # SHARDED over dp — batch_per_shard is the AGGREGATE
        # concurrent-sequence count, dealt into dp groups of
        # slots/dp, each group decoding only its own slots against
        # its own KV pool shard (the shard_map program). dp therefore
        # divides the pool's HBM, the per-device compute, AND the
        # per-group tp activation traffic, and adds ZERO collectives
        # of its own (decode rows are independent); tp still shards
        # per-token compute + the pool's kv heads but pays the
        # activation all-reduces. fsdp shrinks RESIDENT params but
        # pays a full weight all-gather per token, priced below.
        slots = B_shard
        if slots % cand.dp:
            rec.update(feasible=False, reason="slots%dp", score=0.0)
            return rec
        slots_local = slots // cand.dp
        kv_dev = slots * S * kv_tok / (cand.dp * cand.tp)
        act_dev = slots_local * (4 * D + 2 * cfg.d_ff) * ab
        total = params_dev + kv_dev + act_dev
        rec["hbm_gib"] = round(total / 2**30, 6)
        rec["kv_pool_gib"] = round(kv_dev / 2**30, 6)
        rec["kv_capacity_tokens"] = int(
            max(0.0, budget - params_dev - act_dev)
            * cand.dp * cand.tp / kv_tok)
        if total > budget:
            rec.update(feasible=False, reason="hbm", score=0.0)
            return rec
        # SERVING_r05: spend the residual HBM credit — weight bytes
        # vacated by int8 plus whatever the layout leaves free — on
        # KV pages instead of leaving it idle. kv_pool_tokens is the
        # pool the ENGINE should size (serving/disagg.py
        # engine_config_for_plan consumes it); kv_pool_gib_delta
        # records the provenance of the grown pool vs the minimal
        # slots*seq_len one. Informational only: the score value is
        # untouched, so committed rankings and fingerprints of other
        # plans stay --check-clean without a rewrite.
        rec["kv_pool_tokens"] = max(slots * S,
                                    rec["kv_capacity_tokens"])
        rec["kv_pool_sized_gib"] = round(
            rec["kv_pool_tokens"] * kv_tok
            / (cand.dp * cand.tp) / 2**30, 6)
        rec["kv_pool_gib_delta"] = round(
            rec["kv_pool_sized_gib"] - rec["kv_pool_gib"], 6)
        # Forward FLOPs for one token across the aggregate batch
        # (fwd ≈ 1/3 of the fwd+bwd accounting); dp shards the rows,
        # tp the per-row math.
        model = Transformer(cfg)
        flops_step = (model.flops_per_token(S) / 3.0) * slots
        flops_per_dev = flops_step / (cand.dp * cand.tp)
        by_kind = {}
        if cand.fsdp > 1:
            by_kind["all-gather"] = n_params * ab
        if cand.tp > 1:
            by_kind["all-reduce"] = 2.0 * 2.0 * cfg.n_layers \
                * slots_local * D * ab
        tokens = slots  # one token per sequence per step
    else:  # prefill — the batched multi-sequence lane program
        slots = B_shard
        if slots % cand.dp:
            rec.update(feasible=False, reason="slots%dp", score=0.0)
            return rec
        slots_local = slots // cand.dp
        C = SERVING_PREFILL_CHUNK
        # The prefill engine writes prompt KV into its own paged
        # pool (the disagg handoff's source) — same residency model
        # as decode, at the lane table's width.
        kv_dev = slots * S * kv_tok / (cand.dp * cand.tp)
        act_dev = slots_local * C * (4 * D + 2 * cfg.d_ff) * ab
        total = params_dev + kv_dev + act_dev
        rec["hbm_gib"] = round(total / 2**30, 6)
        rec["kv_pool_gib"] = round(kv_dev / 2**30, 6)
        if total > budget:
            rec.update(feasible=False, reason="hbm", score=0.0)
            return rec
        # One launch = every lane's C-token chunk; dp deals lanes
        # (batch-parallel, zero new collectives), tp shards the
        # per-lane math. Attention cost rides flops_per_token(S) —
        # a continuation chunk attends up to S prefix positions.
        model = Transformer(cfg)
        flops_step = (model.flops_per_token(S) / 3.0) * C * slots
        flops_per_dev = flops_step / (cand.dp * cand.tp)
        by_kind = {}
        if cand.fsdp > 1:
            by_kind["all-gather"] = n_params * ab
        if cand.tp > 1:
            by_kind["all-reduce"] = 2.0 * 2.0 * cfg.n_layers \
                * slots_local * C * D * ab
        tokens = slots * C  # full chunk occupancy per launch

    if calib is not None:
        compute_s = flops_per_dev / calib.achievable_flops_per_s(
            flops_per_dev)
    else:
        compute_s = flops_per_dev / peak_flops_per_chip(target.chip)
    if calib is not None:
        comms_s = sum(calib.collective_seconds(k, b)
                      for k, b in by_kind.items() if b > 0)
    else:
        comms_s = sum(by_kind.values()) \
            / nominal_ici_bytes_per_s(target.chip)
    step_s = max(compute_s, comms_s)
    rec.update(
        feasible=True,
        reason="",
        compute_s=compute_s,
        comms_s=comms_s,
        comms_bytes=int(sum(by_kind.values())),
        comms_bytes_by_kind={k: int(b) for k, b in by_kind.items()
                             if b > 0},
        calibrated=calib is not None,
        tokens_per_step=tokens,
        # decode: AGGREGATE tokens/second — one token per sequence
        # per step across the whole dealt slot table, so dp's
        # batch-parallel groups are credited while per-token latency
        # (step_s) stays the denominator; prefill: prompt
        # tokens/second (throughput).
        score=tokens / step_s if step_s > 0 else 0.0,
    )
    return rec


def rank_candidates(target: PlanTarget, calib="auto"
                    ) -> list[tuple[Candidate, dict]]:
    """Feasible candidates best-first. Deterministic: the sort key is
    (-score, simplest-mesh-first, largest-batch-first, remat order) —
    ties between layouts with equal throughput proxies break toward
    fewer sharded axes (less to go wrong) and then lexical mesh
    order, so the same (target, calibration) can never rank two
    ways. The table is resolved ONCE for the whole ranking."""
    if calib == "auto":
        calib = resolve_calibration(target).table
    n_params = _n_params(target)
    scored = [(c, score_candidate(target, c, n_params, calib=calib))
              for c in enumerate_candidates(target)]
    feasible = [(c, s) for c, s in scored if s["feasible"]]
    remat_order = {r: i for i, r in enumerate(REMAT_POLICIES)}

    def key(cs):
        c, s = cs
        sharded_axes = sum(1 for a in MESH_AXES if getattr(c, a) > 1)
        return (-s["score"], sharded_axes, -c.batch_per_shard,
                remat_order[c.remat],
                tuple(getattr(c, a) for a in MESH_AXES))

    return sorted(feasible, key=key)


# ---------------------------------------------------------------------------
# Sharding-map resolution
# ---------------------------------------------------------------------------


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def base_strategy_for(mesh: dict) -> str:
    if mesh.get("tp", 1) > 1:
        return "tp"
    if mesh.get("fsdp", 1) > 1:
        return "fsdp"
    return "ddp"


def resolve_sharding_map(target: PlanTarget, mesh: dict) -> dict:
    """The resolved by-name map for one mesh: run the base strategy's
    spec producers (parallel/strategy.py stays the GENERATOR; the plan
    is the resolved artifact) over the model's abstract params +
    logical axes, then serialize each leaf's PartitionSpec as plain
    JSON — ``None`` replicates, a string is one mesh axis, a list is
    an axis tuple."""
    import jax

    from distributed_training_tpu.models.transformer import Transformer
    from distributed_training_tpu.parallel.strategy import get_strategy
    from distributed_training_tpu.runtime import MeshSpec

    spec = MeshSpec(**{a: mesh.get(a, 1) for a in MESH_AXES})
    strat = get_strategy(base_strategy_for(mesh), spec,
                         min_shard_elems=target.min_shard_elems)
    model = Transformer(_tf_cfg(target, "none"))
    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    logical = (model.logical_axes()
               if hasattr(model, "logical_axes") else None)
    specs = strat.specs_for_tree(shapes, logical)

    out: dict = {}

    def leaf(path, pspec):
        out[_path_str(path)] = [
            list(e) if isinstance(e, tuple) else e for e in pspec]

    from jax.sharding import PartitionSpec as P
    jax.tree_util.tree_map_with_path(
        leaf, specs, is_leaf=lambda x: isinstance(x, P))
    return out


def build_plan(target: PlanTarget, cand: Candidate,
               provenance: dict | None = None) -> Plan:
    """Materialize one candidate as a full Plan (no compile)."""
    from distributed_training_tpu.runtime import BATCH_AXES
    mesh = cand.mesh
    return Plan(
        name=target.name,
        devices=target.devices,
        mesh=mesh,
        base_strategy=base_strategy_for(mesh),
        remat=cand.remat,
        batch_per_shard=cand.batch_per_shard,
        seq_len=target.seq_len,
        batch_axes=[a for a in BATCH_AXES],
        sharding_map=resolve_sharding_map(target, mesh),
        inputs=target.as_dict(),
        provenance=provenance or {},
    )


def model_kwargs_for(plan: Plan) -> dict:
    """The model kwargs a consumer (bench, dryrun, audit target)
    builds the transformer with: the target's kwargs plus the plan's
    remat decision."""
    mk = dict(plan.inputs.get("model_kwargs", {}))
    mk.pop("remat", None)
    mk.pop("remat_policy", None)
    if plan.remat == "none":
        mk["remat"] = False
    else:
        mk.update(remat=True, remat_policy=plan.remat)
    return mk


# ---------------------------------------------------------------------------
# Stage 2: abstract-compile verification (SPMD001 disqualifies)
# ---------------------------------------------------------------------------


def model_for_plan(plan: Plan):
    """The Transformer a serving consumer builds for ``plan`` — the
    target's model kwargs with the plan's remat decision dropped
    (serving programs have no backward; remat keys would be rejected
    by TransformerConfig). One constructor for the engine builder,
    the HTTP server, the disagg pipeline, and the serving verifier."""
    from distributed_training_tpu.models.transformer import (
        Transformer, TransformerConfig)
    mk = model_kwargs_for(plan)
    mk.pop("remat", None)
    mk.pop("remat_policy", None)
    return Transformer(TransformerConfig(**mk))


def compile_verify(target: PlanTarget, plan: Plan) -> dict:
    """Compile the REAL train step against this plan on a simulated
    mesh (``analysis/compile.py``) and return the evidence: the SPMD
    reshard-warning count (any > 0 disqualifies the candidate) and
    the measured per-step collective summary. The plan is passed to
    the trainer through a temp file exactly as a run would consume
    it — the verification path IS the consumption path."""
    import tempfile

    import jax.numpy as jnp

    from distributed_training_tpu.analysis.compile import (
        build_abstract_trainer)
    from distributed_training_tpu.telemetry import collectives

    with tempfile.TemporaryDirectory() as td:
        tmp = os.path.join(td, f"{plan.name}.json")
        save_plan(plan, tmp)
        trainer, rt, batch = build_abstract_trainer(
            plan.devices, plan.base_strategy, "transformer",
            model_kwargs_for(plan), plan.batch_per_shard, plan.seq_len,
            mesh_axes={a: s for a, s in plan.mesh.items() if s > 1},
            train_overrides=dict(
                sharding_plan=tmp,
                min_shard_elems=target.min_shard_elems,
                dtype=plan.inputs.get("model_kwargs", {}).get(
                    "dtype", "float32"),
                optimizer=target.optimizer))
        # Compile with the plan's overlap flags for this (cpu)
        # backend: the verification path IS the consumption path, and
        # consumers run the latency-hiding schedule (cli/bench apply
        # the same flags via XLA_FLAGS).
        opts = plan.xla_overlap_flags("cpu") or None
        with collectives.capture_stderr_fd() as cap:
            text = trainer._step_fn.lower(
                trainer.state, batch,
                jnp.zeros((2,), jnp.uint32)).compile(
                    compiler_options=opts).as_text()
        warnings = collectives.parse_reshard_warnings(cap.text)
        coll = collectives.audit_hlo_text(text, mesh=rt.mesh)
    return {
        "spmd_reshard_warnings": len(warnings),
        "reshard_ops": sorted({w["op"] for w in warnings}),
        "collective_bytes_per_step": coll["bytes_per_step"],
        "total_collectives": coll["total_collectives"],
    }


def verify_fn_for(target: PlanTarget) -> Callable:
    """The stage-2 verifier matching the target's objective: the
    train step for "train" plans, the serving engine's compiled
    decode/prefill program for serving plans (serving/disagg.py) —
    in every case the verification path IS the consumption path."""
    if target.objective == "train":
        return compile_verify
    from distributed_training_tpu.serving.disagg import (
        compile_verify_serving)
    return compile_verify_serving


def plan_search(target: PlanTarget,
                verify_fn: Callable | None = None) -> Plan:
    """The full search: rank analytically, then walk candidates
    best-first compiling each (``verify_fn`` injectable for tests)
    until one is reshard-clean — that candidate becomes the plan,
    with the ranking, every disqualification, and the winner's
    compile evidence recorded as provenance. Raises if the compile
    budget (``target.max_compiles``) runs out with every compiled
    candidate dirty — a planner that silently shipped a resharding
    layout would defeat its own reason to exist."""
    verify = verify_fn or verify_fn_for(target)
    lookup = resolve_calibration(target)
    calib, calib_note = lookup.table, lookup.note
    if lookup.status == "unusable":
        # A TAMPERED/CORRUPT committed table is a loud event even
        # though the search proceeds on nominal constants — the
        # provenance note below records it durably, this line makes
        # it visible at plan time.
        print(f"[planner] WARNING: {calib_note}")
    ranked = rank_candidates(target, calib=calib)
    if not ranked:
        raise PlanError(
            f"target '{target.name}': no feasible candidate "
            f"(devices={target.devices}, budget "
            f"{hbm_budget_gib(target):.2f} GiB)")
    ranking = [{"candidate": c.key, "score": s["score"]}
               for c, s in ranked]
    disqualified: list[dict] = []
    for i, (cand, score) in enumerate(ranked[:target.max_compiles]):
        plan = build_plan(target, cand)
        evidence = verify(target, plan)
        if evidence["spmd_reshard_warnings"]:
            disqualified.append({
                "candidate": cand.key,
                "spmd_reshard_warnings":
                    evidence["spmd_reshard_warnings"],
                "reshard_ops": evidence.get("reshard_ops", [])})
            continue
        plan.provenance = {
            "rank": i,
            "score": score,
            "ranking": ranking,
            "disqualified": disqualified,
            "compile_evidence": evidence,
            "calibration": calibration_provenance(
                target, calib, calib_note),
        }
        return plan
    raise PlanError(
        f"target '{target.name}': every compiled candidate "
        f"(top {target.max_compiles}) has involuntary-reshard "
        f"warnings: {disqualified}")


# ---------------------------------------------------------------------------
# PlannedStrategy: the trainer-facing consumer of a plan
# ---------------------------------------------------------------------------


from distributed_training_tpu.parallel.strategy import (  # noqa: E402
    ShardingStrategy)


@dataclasses.dataclass
class PlannedStrategy(ShardingStrategy):
    """A ShardingStrategy whose layout is a resolved plan, not rules.

    ``specs_for_tree`` looks every leaf up BY PATH in the plan's
    sharding map — the veScale-style single spec source — and raises
    on a path the plan does not name (a model/plan mismatch must fail
    at construction, not compile into a silently replicated layout).
    Optimizer moments inherit the param layout (the plan's generator
    families all behave this way; ZeRO-1 is not in the planner's
    search space)."""

    plan: Plan | None = None

    def __post_init__(self) -> None:
        self.name = "planned"
        if self.plan is None:
            raise PlanError("PlannedStrategy requires a plan")

    @property
    def wants_gather_for_compute(self) -> bool:
        return self.plan.base_strategy == "fsdp"

    def param_spec(self, shape, logical):
        raise PlanError(
            "PlannedStrategy resolves specs by param PATH via "
            "specs_for_tree; a path-less spec lookup would bypass "
            "the plan's sharding map")

    def _spec_for_path(self, key: str):
        from jax.sharding import PartitionSpec as P
        try:
            entries = self.plan.sharding_map[key]
        except KeyError:
            raise PlanError(
                f"plan '{self.plan.name}' names no sharding for param "
                f"'{key}' — the plan was resolved against a different "
                "model; re-run the planner") from None
        return P(*[tuple(e) if isinstance(e, list) else e
                   for e in entries])

    def specs_for_tree(self, tree: Any, logical_tree: Any = None,
                       spec_fn: Any = None) -> Any:
        import jax
        del logical_tree, spec_fn  # the plan IS the resolved layout
        return jax.tree_util.tree_map_with_path(
            lambda path, _leaf: self._spec_for_path(_path_str(path)),
            tree)

    def batch_spec(self):
        from jax.sharding import PartitionSpec as P
        axes = tuple(self.plan.batch_axes)
        return P(axes) if axes else P()

    def describe(self) -> str:
        return (f"planned({self.plan.name}@{self.plan.fingerprint()} "
                f"mesh={ {a: s for a, s in self.plan.mesh.items() if s > 1} } "
                f"remat={self.plan.remat})")


def check_plan_runtime(plan: Plan, mesh_spec,
                       elastic: bool | None = None) -> None:
    """Fail loudly when the runtime mesh is not the plan's mesh.

    Under an elastic incarnation (``DTT_ELASTIC_WORLD`` set — PR 7's
    contract) only the ``dp`` extent may differ: the CLI applies the
    plan's model-sharding axes with ``dp`` as the wildcard, so a
    shrunken world keeps exactly the planned layout at a smaller
    data-parallel width."""
    from distributed_training_tpu.resilience import elastic as el
    if elastic is None:
        elastic = os.environ.get(el.ENV_WORLD) is not None
    have = mesh_spec.as_dict()
    for a in MESH_AXES:
        if a == "dp" and elastic:
            continue
        if have.get(a, 1) != plan.mesh.get(a, 1):
            raise PlanError(
                f"runtime mesh {have} does not match plan "
                f"'{plan.name}' mesh {plan.mesh} (axis '{a}'); pass "
                "the plan through the CLI (train.sharding_plan) so "
                "the mesh is derived from it, or re-plan for this "
                "topology")


def apply_plan_to_config(cfg) -> Plan:
    """Derive ``cfg.mesh`` (and the per-shard batch) from
    ``cfg.train.sharding_plan``: every model-sharding axis pinned to
    the plan's extent, ``dp`` left as the ``-1`` wildcard so the data
    axis absorbs the actual device count — full-size worlds resolve
    to exactly the plan's mesh, and elastic incarnations (PR 7's
    shrink/grow) re-form around the same planned layout (the MeshSpec
    dp wildcard precedent). The per-shard batch is a SEARCHED
    dimension of the plan, so it is applied too — the compiled
    program is then the one the plan's reshard-clean compile evidence
    covered — except under ``train.global_batch_size`` (the elastic
    world-size-invariant contract), where the CLI derives the
    per-shard batch from the resolved world instead. Returns the
    loaded plan."""
    plan = load_plan(cfg.train.sharding_plan)
    for a in MESH_AXES:
        setattr(cfg.mesh, a, -1 if a == "dp" else plan.mesh.get(a, 1))
    if not cfg.train.global_batch_size:
        cfg.train.batch_size = plan.batch_per_shard
    return plan


# ---------------------------------------------------------------------------
# HBM plan records (benchmarks/plan_memory.py backend)
# ---------------------------------------------------------------------------


def hbm_plan_record(name: str, preset: str, chip: str,
                    overrides: dict, layout: dict) -> dict:
    """One estimator-validated memory-plan record — the single HBM
    cost model (utils/memory.py) formatted the way the planner scores
    candidates and ``benchmarks/plan_memory.py`` prints plans. That
    script is a thin wrapper over this function (PR 6's
    audit_collectives precedent): one memory model, two consumers."""
    from distributed_training_tpu.models.transformer import (
        PRESETS, TransformerConfig)
    from distributed_training_tpu.utils.memory import (
        HBM_GIB, estimate_transformer_memory)

    cfg = TransformerConfig(dtype="bfloat16",
                            **{**PRESETS[preset], **overrides})
    est = estimate_transformer_memory(cfg, **layout)
    return {
        "plan": name,
        "preset": preset,
        "chip": chip,
        "hbm_gib": HBM_GIB[chip],
        "overrides": overrides,
        "layout": layout,
        "params_gib": round(est.params_gib, 2),
        "grads_gib": round(est.grads_gib, 2),
        "opt_gib": round(est.opt_gib, 2),
        "activations_gib": round(est.activations_gib, 2),
        "total_gib": round(est.total_gib, 2),
        "fits": est.fits(chip),
    }


# ---------------------------------------------------------------------------
# --check: the committed plan is still what the planner would choose
# ---------------------------------------------------------------------------


def check_plan(target: PlanTarget,
               compile_winner: bool = False) -> list[str]:
    """Ratchet-style verification of one committed plan. Returns
    problem strings (empty = clean):

    - the committed plan must load, be for this target's inputs, and
      carry a self-consistent fingerprint;
    - the calibration that scored the plan must still be the one the
      chip resolves to (same source, same committed-table
      fingerprint): re-measuring a chip — or landing/removing its
      table — without re-planning is silent cost-model drift;
    - the deterministic stage-1 ranking must match the one recorded
      at plan time (a cost-model or search-space change silently
      reordering candidates is exactly what must not pass CI);
    - the winner the search would pick (ranking + recorded
      disqualifications) must BE the committed candidate, and the
      re-resolved sharding map must hash to the committed
      fingerprint (catches strategy-rule drift);
    - the recorded compile evidence must say zero reshard warnings;
      with ``compile_winner`` the step is recompiled to re-prove it
      (the tier-1 analysis gate owns that compile otherwise, via the
      planned audit target).
    """
    problems: list[str] = []
    try:
        committed = load_plan(target.name)
    except (PlanError, FileNotFoundError) as e:
        return [f"{target.name}: cannot load committed plan: {e}"]
    if _canon(committed.inputs) != _canon(target.as_dict()):
        problems.append(
            f"{target.name}: committed plan was resolved for "
            "different search inputs — re-run planner --write")
        return problems
    lookup = resolve_calibration(target)
    calib, calib_note = lookup.table, lookup.note
    if lookup.status == "unusable":
        # A committed table whose own integrity check rejects it is
        # repo damage, not a fallback case: plan_search may proceed
        # on nominal constants mid-recalibration, but --check guards
        # COMMITTED state and must go red until the artifact is
        # re-measured or removed.
        problems.append(
            f"{target.name}: {calib_note}")
        return problems
    recorded_calib = committed.provenance.get("calibration", {})
    current_fp = calib.fingerprint() if calib is not None else None
    if recorded_calib.get("fingerprint") != current_fp:
        problems.append(
            f"{target.name}: calibration drift — plan was scored "
            f"from {recorded_calib.get('source', 'nominal')} "
            f"(fingerprint {recorded_calib.get('fingerprint')}), the "
            f"chip now resolves to fingerprint {current_fp} "
            f"({calib_note}) — re-run planner --write")
        return problems
    ranked = rank_candidates(target, calib=calib)
    ranking = [{"candidate": c.key, "score": s["score"]}
               for c, s in ranked]
    recorded = committed.provenance.get("ranking", [])
    if ranking != recorded:
        problems.append(
            f"{target.name}: stage-1 ranking changed (cost model or "
            "search space drift) — re-run planner --write")
        return problems
    # Winner identity: skip candidates the plan-time compile
    # disqualified, then the next must be the committed one.
    dq = {d["candidate"]
          for d in committed.provenance.get("disqualified", [])}
    expect = next((c for c, _s in ranked if c.key not in dq), None)
    committed_key = committed.candidate_key
    if expect is None or expect.key != committed_key:
        problems.append(
            f"{target.name}: search winner is "
            f"{expect.key if expect else None}, committed plan is "
            f"{committed_key} — re-run planner --write")
        return problems
    rebuilt = build_plan(target, expect,
                         provenance=committed.provenance)
    if rebuilt.fingerprint() != committed.fingerprint():
        problems.append(
            f"{target.name}: re-resolved sharding map no longer "
            f"matches the committed plan (fingerprint "
            f"{rebuilt.fingerprint()} != {committed.fingerprint()}) "
            "— strategy rules drifted; re-run planner --write")
        return problems
    ev = committed.provenance.get("compile_evidence", {})
    if ev.get("spmd_reshard_warnings", None) != 0:
        problems.append(
            f"{target.name}: committed plan carries no clean compile "
            "evidence — re-run planner --write")
    if compile_winner and not problems:
        fresh = verify_fn_for(target)(target, rebuilt)
        if fresh["spmd_reshard_warnings"]:
            problems.append(
                f"{target.name}: plan is no longer reshard-clean on "
                f"this XLA ({fresh['spmd_reshard_warnings']} "
                "warning(s)) — the layout needs re-planning")
    return problems


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m distributed_training_tpu.parallel.planner",
        description="Auto-parallelism planner: search mesh x remat x "
                    "batch, emit/verify committed sharding plans.")
    ap.add_argument("--targets", default="",
                    help="comma-separated plan target names "
                         "(default: all)")
    ap.add_argument("--write", action="store_true",
                    help="run the full search (incl. compile "
                         "verification) and write conf/plans/<name>"
                         ".json for each target")
    ap.add_argument("--check", action="store_true",
                    help="verify each committed plan is still the "
                         "deterministic search winner and carries "
                         "clean compile evidence (exit 1 otherwise)")
    ap.add_argument("--compile", action="store_true",
                    help="with --check: also recompile each winner "
                         "to re-prove reshard cleanliness (the "
                         "tier-1 analysis gate owns this otherwise)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="with --write: also dump the plan doc here")
    args = ap.parse_args(argv)

    # Device-less by design: CPU backend with enough fake devices for
    # the largest target, forced before the first backend init.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    need = max((t.devices for t in PLAN_TARGETS.values()), default=8)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={need}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    names = [n for n in args.targets.split(",") if n] or None
    targets = resolve_targets(names)
    rc = 0
    for t in targets:
        if args.write:
            plan = plan_search(t)
            path = save_plan(plan)
            ev = plan.provenance["compile_evidence"]
            cal = plan.provenance.get("calibration", {})
            print(f"[planner] {t.name}: wrote {path}")
            print(f"[planner]   mesh="
                  f"{ {a: s for a, s in plan.mesh.items() if s > 1} } "
                  f"remat={plan.remat} batch/shard="
                  f"{plan.batch_per_shard} fingerprint="
                  f"{plan.fingerprint()}")
            print(f"[planner]   reshard_warnings="
                  f"{ev['spmd_reshard_warnings']} collective_bytes="
                  f"{ev['collective_bytes_per_step']}")
            print(f"[planner]   cost model: "
                  f"{cal.get('source', 'nominal')} "
                  f"({cal.get('note', '')})")
            if args.json:
                with open(args.json, "w", encoding="utf-8") as f:
                    json.dump(plan.to_doc(), f, indent=1,
                              sort_keys=True)
                    f.write("\n")
        elif args.check:
            problems = check_plan(t, compile_winner=args.compile)
            for p in problems:
                print(f"[planner] {p}")
            if problems:
                rc = 1
            else:
                plan = load_plan(t.name)
                cal = plan.provenance.get("calibration", {})
                print(f"[planner] {t.name}: OK "
                      f"(fingerprint {plan.fingerprint()}, "
                      f"reshard-clean, winner unchanged, "
                      f"cost model {cal.get('source', 'nominal')}"
                      + (f"@{cal['fingerprint']}"
                         if cal.get("fingerprint") else "") + ")")
        else:
            ranked = rank_candidates(t)
            print(f"[planner] {t.name}: "
                  f"{len(enumerate_candidates(t))} candidates, "
                  f"{len(ranked)} feasible; top 5:")
            for c, s in ranked[:5]:
                print(f"[planner]   {c.key:40s} score={s['score']:.3e}"
                      f" hbm={s['hbm_gib']:.3f}GiB")
    return rc


if __name__ == "__main__":
    import sys
    sys.exit(main())
