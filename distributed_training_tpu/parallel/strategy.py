"""Sharding strategies: param/batch PartitionSpec producers.

Strategy interface parity with the reference ABC
(``prepare_model`` / ``save_checkpoint`` / ``load_checkpoint``,
src/dist_strategy/dist_strategy.py:8-26) re-expressed for SPMD:

- ``prepare_model`` → ``param_shardings(mesh, shapes, logical)``: where the
  torch wrapper decides replicate-vs-shard at wrap time, here the decision
  is a ``NamedSharding`` pytree consumed by ``jit(in_shardings=...)``; XLA
  compiles the matching collectives (broadcast/all-reduce for DDP,
  all-gather + reduce-scatter for FSDP) into the step function.
- checkpoint policy → strategies declare whether checkpoints are written
  sharded (each host its shards — the scalable default) or gathered
  (the FULL_STATE_DICT analogue, fsdp_strategy.py:31-36).

Two spec sources compose:
1. *logical axis names* attached to params by the model (e.g.
   ``("embed", "mlp")``), mapped through per-strategy rules — how TP/SP
   express themselves;
2. a shape heuristic for unannotated pytrees — FSDP shards the largest
   axis-size-divisible dimension (the standard JAX FSDP recipe; cf.
   SNIPPETS.md [1]/[2] patterns).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import warnings
from abc import ABC, abstractmethod
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_tpu.runtime import (
    AXIS_FSDP, AXIS_TP, BATCH_AXES,
)

logger = logging.getLogger(__name__)

Rules = dict[str, str | tuple[str, ...] | None]


def logical_to_spec(logical: tuple[str | None, ...], rules: Rules) -> P:
    """Map per-dimension logical axis names → mesh axes via ``rules``.

    Unknown / None logical names replicate. A mesh axis may appear at most
    once in the result (XLA requirement)."""
    assigned: list[str | tuple[str, ...] | None] = []
    used: set[str] = set()
    for name in logical:
        axis = rules.get(name) if name is not None else None
        if axis is None:
            assigned.append(None)
            continue
        flat = (axis,) if isinstance(axis, str) else tuple(axis)
        if any(a in used for a in flat):
            # Same mesh axis twice in one param: keep the first use.
            assigned.append(None)
            continue
        used.update(flat)
        assigned.append(axis)
    while assigned and assigned[-1] is None:
        assigned.pop()
    return P(*assigned)


def prune_spec(shape: tuple[int, ...], spec: P, axis_sizes: dict[str, int],
               min_elems: int = 0) -> P:
    """Drop sharding assignments a given array can't honor: dims not
    divisible by the assigned mesh-axis size, and fsdp assignments on
    arrays too small to be worth a collective. Keeps the layout valid for
    any model/mesh combination (tiny parity MLPs on big meshes included)."""
    if len(spec) > len(shape):
        raise ValueError(
            f"logical axis annotation {tuple(spec)} has more dims than "
            f"the array of shape {shape} — fix the model's logical_axes")
    padded = list(spec) + [None] * (len(shape) - len(spec))
    small = math.prod(shape) < min_elems if shape else True
    out: list = []
    for d, a in enumerate(padded):
        if a is None:
            out.append(None)
            continue
        flat = (a,) if isinstance(a, str) else tuple(a)
        if any(x not in axis_sizes for x in flat):
            # Axis whose size we don't know (user-extended rules): keep
            # the assignment so XLA validates it loudly rather than
            # silently replicating.
            out.append(a)
            continue
        prod = math.prod(axis_sizes[x] for x in flat)
        if shape[d] % prod != 0:
            if prod > 1:
                logger.warning(
                    "dropping sharding %s on dim %d of %s: %d not "
                    "divisible by mesh axes product %d — param will be "
                    "replicated on %s", a, d, shape, shape[d], prod, flat)
            out.append(None)
        elif small and all(x == AXIS_FSDP for x in flat):
            out.append(None)
        else:
            out.append(a)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def _largest_divisible_dim(shape: tuple[int, ...], size: int,
                           min_elems: int) -> int | None:
    """Pick the dimension FSDP shards: the largest one divisible by the
    axis size, for arrays big enough to be worth sharding."""
    if size <= 1 or math.prod(shape) < min_elems or len(shape) == 0:
        return None
    candidates = [(d, shape[d]) for d in range(len(shape))
                  if shape[d] % size == 0 and shape[d] >= size]
    if not candidates:
        return None
    return max(candidates, key=lambda t: (t[1], -t[0]))[0]


def _heuristic_spec(shape: tuple[int, ...], size: int, axis,
                    min_elems: int) -> P:
    """Shape-heuristic spec: ``axis`` on the largest divisible dim,
    replicated otherwise. The shared tail of every strategy's
    fallback path."""
    dim = _largest_divisible_dim(shape, size, min_elems)
    if dim is None:
        return P()
    spec: list = [None] * len(shape)
    spec[dim] = axis
    return P(*spec)


@dataclasses.dataclass
class ShardingStrategy(ABC):
    """Produces sharding layouts; consumed by the Trainer's jitted step."""

    # Arrays smaller than this stay replicated under shape-heuristic FSDP
    # (tiny biases/norms aren't worth a collective; mirrors torch FSDP's
    # min_num_params wrapping policy in spirit).
    min_shard_elems: int = 2 ** 12

    name: str = dataclasses.field(default="base", init=False)
    # True → each save point ALSO exports a gathered single-file
    # artifact (checkpoint/consolidate.py) next to the sharded Orbax
    # checkpoint — the working analogue of the reference FSDP
    # FULL_STATE_DICT gather (fsdp_strategy.py:31-36), minus its
    # rank0-only-collective deadlock (SURVEY.md §8 B6). The sharded
    # path stays primary (the gather is O(model) HBM + host RAM).
    gather_on_save: bool = False

    @property
    def wants_gather_for_compute(self) -> bool:
        """Whether the trainer should bind the model's gather-for-
        compute constraint (weights all-gather per layer, activations
        never pay collective traffic) for this layout. True for the
        FSDP family; ``PlannedStrategy`` delegates to its plan's base
        strategy."""
        return self.name == "fsdp"

    @abstractmethod
    def param_spec(self, shape: tuple[int, ...],
                   logical: tuple[str | None, ...] | None) -> P:
        """PartitionSpec for one param/optimizer leaf."""

    def opt_spec(self, shape: tuple[int, ...],
                 logical: tuple[str | None, ...] | None) -> P:
        """PartitionSpec for a param-shaped OPTIMIZER leaf (Adam
        moments, momentum). Defaults to the param's own layout; ZeRO-1
        overrides it to shard moments while params stay replicated."""
        return self.param_spec(shape, logical)

    def batch_spec(self) -> P:
        """Batch dim over all data-like mesh axes (dp, fsdp)."""
        return P(BATCH_AXES)

    # -- pytree-level helpers ----------------------------------------------

    def specs_for_tree(self, tree: Any, logical_tree: Any = None,
                       spec_fn: Any = None) -> Any:
        """Map ``param_spec`` (or ``spec_fn``) over a pytree of
        arrays/ShapeDtypeStructs."""
        fn = spec_fn or self.param_spec
        if logical_tree is None:
            return jax.tree.map(
                lambda leaf: fn(tuple(leaf.shape), None), tree)
        return jax.tree.map(
            lambda leaf, lg: fn(tuple(leaf.shape), lg),
            tree, logical_tree,
            is_leaf=lambda x: x is None)

    def opt_specs_for_tree(self, tree: Any,
                           logical_tree: Any = None) -> Any:
        """Like ``specs_for_tree`` but for param-shaped optimizer
        leaves (routes through ``opt_spec``)."""
        return self.specs_for_tree(tree, logical_tree,
                                   spec_fn=self.opt_spec)

    def shardings_for_tree(self, mesh: Mesh, tree: Any,
                           logical_tree: Any = None) -> Any:
        return jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            self.specs_for_tree(tree, logical_tree),
            is_leaf=lambda x: isinstance(x, P))

    def describe(self) -> str:
        return f"{self.name}(batch={self.batch_spec()})"


@dataclasses.dataclass
class DataParallel(ShardingStrategy):
    """DDP: params replicated on every device; batch split on (dp, fsdp).

    The compiled-collective counterpart of torch DDP's bucketed NCCL
    allreduce (reference: src/dist_strategy/ddp_strategy.py:15-21): with
    replicated params and sharded batch, XLA emits a single fused
    gradient all-reduce over ICI in the backward pass.
    """

    def __post_init__(self) -> None:
        self.name = "ddp"

    def param_spec(self, shape: tuple[int, ...],
                   logical: tuple[str | None, ...] | None) -> P:
        del shape, logical
        return P()  # fully replicated


@dataclasses.dataclass
class ZeRO1(DataParallel):
    """ZeRO stage 1: params replicated (DDP compute/communication),
    optimizer moments sharded over the data axes.

    The torch analogue is ZeroRedundancyOptimizer — absent from the
    reference (its FSDP jump skips stage 1; SURVEY.md §2.3) but the
    natural midpoint this mesh design gets nearly for free: the jitted
    step computes each moment update on its home shard and XLA
    all-gathers only the param *delta*, cutting optimizer HBM by the
    data-axis product (Adam fp32 moments = 8 bytes/param, the largest
    single state after the params themselves).
    """

    data_size: int = 1

    def __post_init__(self) -> None:
        self.name = "zero1"

    def opt_spec(self, shape: tuple[int, ...],
                 logical: tuple[str | None, ...] | None) -> P:
        del logical
        # BATCH_AXES: shard over dp AND fsdp jointly.
        return _heuristic_spec(shape, self.data_size, BATCH_AXES,
                               self.min_shard_elems)


@dataclasses.dataclass
class FullyShardedDataParallel(ShardingStrategy):
    """ZeRO-3: every large param sharded over the ``fsdp`` axis.

    The compiled counterpart of torch FSDP's flat-param sharding
    (reference: src/dist_strategy/fsdp_strategy.py:17-26). The
    gather-weights-for-compute half of the contract is NOT left to the
    partitioner's cost model: measured via
    benchmarks/audit_collectives.py, XLA preferred partial matmuls on
    weight shards plus ACTIVATION-shaped all-reduces. The Trainer
    therefore binds the model's gather-for-compute constraint
    (``TrainConfig.fsdp_gather_for_compute``) so weights all-gather
    per layer and activations never pay collective traffic. With
    logical axes present, the storage shard dim follows ``rules``;
    otherwise the largest divisible dim.
    """

    fsdp_size: int = 1
    # Logical-axis routing for annotated models: shard the embedding/
    # feature dim, leave tp-owned dims alone.
    rules: Rules = dataclasses.field(default_factory=lambda: {
        "embed": AXIS_FSDP,
        "vocab": AXIS_FSDP,
        "mlp": None,
        "heads": None,
        "kv": None,
        "expert": AXIS_FSDP,
    })

    def __post_init__(self) -> None:
        self.name = "fsdp"

    def param_spec(self, shape: tuple[int, ...],
                   logical: tuple[str | None, ...] | None) -> P:
        sizes = {AXIS_FSDP: self.fsdp_size}
        if logical is not None:
            spec = prune_spec(shape, logical_to_spec(logical, self.rules),
                              sizes, self.min_shard_elems)
            if spec != P():
                return spec
        return _heuristic_spec(shape, self.fsdp_size, AXIS_FSDP,
                               self.min_shard_elems)


@dataclasses.dataclass
class TensorParallel(ShardingStrategy):
    """Megatron-style tensor parallelism composed with FSDP.

    Requires logical axis annotations from the model: column-parallel
    weights shard their output dim on ``tp``, row-parallel their input
    dim; attention shards heads. Unannotated leaves fall back to the FSDP
    heuristic over remaining dims. The reference has no TP
    (SURVEY.md §2.3) — this is a framework extension the mesh design
    leaves open.
    """

    fsdp_size: int = 1
    tp_size: int = 1
    rules: Rules = dataclasses.field(default_factory=lambda: {
        "embed": AXIS_FSDP,
        "vocab": AXIS_TP,
        "mlp": AXIS_TP,
        "heads": AXIS_TP,
        "kv": AXIS_TP,
        "expert": AXIS_FSDP,
    })

    def __post_init__(self) -> None:
        self.name = "tp"

    def param_spec(self, shape: tuple[int, ...],
                   logical: tuple[str | None, ...] | None) -> P:
        sizes = {AXIS_FSDP: self.fsdp_size, AXIS_TP: self.tp_size}
        if logical is not None:
            return prune_spec(shape, logical_to_spec(logical, self.rules),
                              sizes, self.min_shard_elems)
        return _heuristic_spec(shape, self.fsdp_size, AXIS_FSDP,
                               self.min_shard_elems)


def get_strategy(name: str, mesh_spec=None, **kwargs) -> ShardingStrategy:
    """Strategy registry (parity: the trainer's strategy selection switch,
    src/distributed_trainer.py:143-151). ``hybrid`` is FSDP specs over a
    mesh with dp > 1 — sharding within ICI, replicating across DCN."""
    sizes = {}
    if mesh_spec is not None:
        sizes = dict(fsdp_size=mesh_spec.fsdp, tp_size=mesh_spec.tp,
                     data_size=mesh_spec.dp * mesh_spec.fsdp)
    name = name.lower()
    if name == "ddp":
        return DataParallel(**kwargs)
    if name == "zero1":
        data_size = sizes.get("data_size", 1)
        if data_size <= 1:
            # ZeRO1 with one data shard degenerates to plain DDP
            # (moments fully replicated) — a silent no-op that hides
            # misconfiguration (ADVICE r3). Loud, not fatal: single
            # -chip smoke runs of multi-chip configs are legitimate.
            warnings.warn(
                "parallel_strategy='zero1' with data_size<=1: optimizer"
                " moments will be fully replicated (plain DDP). Pass a"
                " mesh with dp*fsdp > 1 for ZeRO-1 to shard anything.",
                stacklevel=2)
        return ZeRO1(data_size=data_size, **kwargs)
    if name in ("fsdp", "hybrid"):
        return FullyShardedDataParallel(
            fsdp_size=sizes.get("fsdp_size", 1), **kwargs)
    if name in ("tp", "tp_fsdp"):
        return TensorParallel(
            fsdp_size=sizes.get("fsdp_size", 1),
            tp_size=sizes.get("tp_size", 1), **kwargs)
    raise ValueError(
        f"unknown parallel_strategy '{name}'; known: ddp, zero1, "
        "fsdp, hybrid, tp")
