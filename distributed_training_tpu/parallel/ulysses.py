"""Ulysses-style sequence parallelism: all-to-all over the ``sp`` axis.

The second of the two standard long-context layouts (DeepSpeed-Ulysses;
the other is ring attention, parallel/ring_attention.py). Where the
ring keeps queries home and rotates KV blocks through sp neighbor
exchanges, Ulysses re-shards twice per attention call:

    (B, S/sp, H, D)  --all_to_all-->  (B, S, H/sp, D)
         sequence-sharded                  head-sharded
    → plain LOCAL attention over the full sequence per head group
      (the Pallas flash kernel — full S means its causal masking and
      tiling apply unchanged) →
    (B, S, H/sp, D)  --all_to_all-->  (B, S/sp, H, D)

Tradeoffs vs the ring, both O(S·H·D/sp) activation memory per device:

- communication: Ulysses moves q/k/v/out once each (4 a2a's of the
  local shard) regardless of sp; the ring moves K/V sp−1 times. For
  sp > ~4 Ulysses sends less total traffic, but as monolithic
  all-to-alls with no compute to hide behind, vs the ring's
  per-step ppermutes that overlap block compute.
- constraints: Ulysses needs ``H % sp == 0`` AND ``Hkv % sp == 0``
  (heads are the new shard dim); the ring has no head constraint —
  which is why the ring stays the default for GQA models with few KV
  heads.
- backward: plain autodiff — ``all_to_all`` transposes to the inverse
  all-to-all, and the local attention is the flash custom-VJP. No
  hand-written reverse schedule needed.

The reference repo has nothing like either (SURVEY.md §5.7); this
exists because the brief makes long-context a first-class axis and
names both layouts.
"""

from __future__ import annotations

import functools

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_training_tpu.ops.attention import dot_product_attention
from distributed_training_tpu.parallel.compat import axis_size
from distributed_training_tpu.runtime import AXIS_SP, BATCH_AXES


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = AXIS_SP, causal: bool = True,
                      local_impl: str = "auto", block_q: int = 0,
                      block_k: int = 0, window: int = 0) -> jax.Array:
    """Sequence-parallel attention; call INSIDE shard_map.

    Per-device shards: q (B, S_local, H, D); k/v (B, S_local, Hkv, D),
    the global sequence being the concatenation of shards in
    ``axis_name`` order. Output matches q's shape/dtype.
    ``local_impl`` feeds ops.dot_product_attention for the full-sequence
    local attention ("auto" → Pallas flash on TPU); ``block_q``/
    ``block_k`` are the flash tile overrides (0 → kernel defaults),
    threaded so the bench sweep tunes every attention layout
    (single-device, Ulysses, and the ring) with one knob.
    """
    sp = axis_size(axis_name)
    if sp == 1:
        return dot_product_attention(q, k, v, causal=causal,
                                     impl=local_impl, block_q=block_q,
                                     block_k=block_k, window=window)
    # Shapes here are per-shard: when a head axis (tp) also shards the
    # head dim, these are the per-tp-shard counts — which is exactly
    # what must divide by sp (the a2a swaps seq for heads within the
    # local head group, so tp composition falls out for free).
    H, Hkv = q.shape[2], k.shape[2]
    if H % sp or Hkv % sp:
        raise ValueError(
            f"ulysses needs the per-shard head counts (q: {H}, "
            f"kv: {Hkv}) divisible by sp ({sp}); use ring attention "
            "otherwise")

    def seq_to_heads(x):
        # (B, S/sp, h, D) -> (B, S, h/sp, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def heads_to_seq(x):
        # (B, S, h/sp, D) -> (B, S/sp, h, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    # Window semantics survive the a2a: the local attention sees the
    # FULL sequence (only heads are sharded), so the band is global.
    out = dot_product_attention(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
        causal=causal, impl=local_impl, block_q=block_q,
        block_k=block_k, window=window)
    return heads_to_seq(out)


def make_ulysses_attention(mesh: Mesh, causal: bool = True,
                           batch_axes=BATCH_AXES,
                           local_impl: str = "auto", block_q: int = 0,
                           block_k: int = 0, head_axis=None,
                           window: int = 0):
    """Build the shard_map'd Ulysses fn over global (B, S, H, D)
    arrays: batch over ``batch_axes``, sequence over ``sp``, heads
    over ``head_axis`` (tp) when given — the a2a then trades sequence
    for heads within each tp shard's head group, so tp and sp compose
    (requires H and Hkv divisible by tp·sp). Mirrors
    make_ring_attention's contract (the model picks by
    ``attention_impl``)."""
    spec = P(tuple(batch_axes) or None, AXIS_SP, head_axis, None)
    return shard_map(
        functools.partial(ulysses_attention, axis_name=AXIS_SP,
                          causal=causal, local_impl=local_impl,
                          block_q=block_q, block_k=block_k,
                          window=window),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )


def ulysses_attention_global(q: jax.Array, k: jax.Array, v: jax.Array,
                             mesh: Mesh, causal: bool = True,
                             batch_axes=BATCH_AXES,
                             head_axis=None) -> jax.Array:
    """Convenience entry for tests/eager use (mirrors
    ring_attention_global)."""
    from distributed_training_tpu.parallel.ring_attention import (
        usable_batch_axes,
    )
    fn = make_ulysses_attention(
        mesh, causal=causal,
        batch_axes=usable_batch_axes(mesh, q.shape[0], batch_axes),
        head_axis=head_axis)
    return jax.jit(fn)(q, k, v)
