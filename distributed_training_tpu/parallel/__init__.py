"""Parallelism strategies, expressed the TPU way.

The reference's ``src/dist_strategy`` package wraps the model object in
torch DDP/FSDP classes (dist_strategy.py:8-26; ddp_strategy.py:10-32;
fsdp_strategy.py:13-46). In JAX, "DDP vs FSDP" is not two model-wrapping
codepaths but two *sharding layouts over one mesh* applied to the same
jitted train step (SURVEY.md §7): params replicated → XLA emits a gradient
all-reduce (DDP); params sharded on ``fsdp`` → XLA emits all-gather on use
and reduce-scatter on gradients (ZeRO-3). The strategy object's semantic
content — "how are params laid out, how is the batch laid out, how are
checkpoints materialized" — survives as PartitionSpec producers.
"""

from distributed_training_tpu.parallel.strategy import (  # noqa: F401
    DataParallel,
    FullyShardedDataParallel,
    ShardingStrategy,
    TensorParallel,
    ZeRO1,
    get_strategy,
    logical_to_spec,
)
