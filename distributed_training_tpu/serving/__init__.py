"""Serving subsystem: continuous batching over a paged, sharded KV cache.

The inference half of the production story (ROADMAP item 1). Pieces:

- ``kv_cache.py``  — the paged KV pool: fixed-size pages in one
  preallocated reservation, per-sequence page tables, host-side
  allocator with telemetry-accounted occupancy;
- ``engine.py``    — the continuous-batching engine: admission queue
  feeding two jitted programs (chunked prefill, whole-batch decode),
  per-step join/evict with zero recompiles after warmup;
- ``disagg.py``    — prefill/decode disaggregation: two planner-derived
  layouts resolved against ONE weight store, KV handed off between
  mesh slices;
- ``server.py``    — stdlib HTTP generate endpoint + live serving
  gauges on the telemetry metrics endpoint.

Benchmark: ``benchmarks/bench_serving.py`` (Poisson load, TTFT/latency
percentiles, goodput under a mid-storm preemption) → SERVING ledger.
Docs: docs/serving.md.
"""

from distributed_training_tpu.serving.engine import (  # noqa: F401
    Engine,
    EngineConfig,
    Request,
)
from distributed_training_tpu.serving.kv_cache import (  # noqa: F401
    PagedCacheConfig,
    PagedKVCache,
)
