"""Continuous-batching engine: admission queue → two jitted programs.

The serving hot loop. Requests join and leave the running batch at
every step (continuous batching — no head-of-line blocking behind the
longest sequence in a static batch), against exactly THREE compiled
programs whose shapes never change:

- **prefill, first chunk** — the prompt's first ``prefill_chunk``
  tokens as ordinary causal self-attention (flash-eligible on TPU via
  ops.attention), KV written into the sequence's pages;
- **prefill, continuation chunk** — later chunks attend the pages
  written so far plus themselves (ops/paged_attention.py chunk form);
- **decode** — ONE token for the whole slot table (max_batch wide)
  against the paged pool, inactive slots masked and their writes
  pointed at the scratch page.

Join/evict therefore never change a traced shape: admission fills a
slot and allocates pages; completion frees them; the programs compile
once at warmup and never again (``compile_counts`` exposes the jit
cache sizes so the bench can ASSERT zero recompiles mid-storm).

Scheduling policy (``EngineConfig.policy``):

- ``"prefill"`` (default): pending prompt work runs before decode —
  lowest TTFT, decode tokens stall behind prompt storms;
- ``"decode"``: the active batch decodes first; prompts admit only
  when no sequence can decode — best per-token latency, TTFT suffers.

``prefill_chunk`` is the per-step prefill token budget (one chunk per
step); decode emits up to ``max_batch`` tokens per step.

Sampling is greedy at ``temperature == 0`` (the parity-tested path —
token-for-token equal to full-context argmax); ``temperature > 0``
samples per-slot from a per-step folded key. Batch-composition
independence (a sequence's tokens don't depend on who shares the
batch) is exact for greedy decoding and pinned by test.

MoE models are rejected at construction: expert dispatch has no
serving decode path yet.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field

import numpy as np

from distributed_training_tpu.serving.kv_cache import (
    PagedCacheConfig,
    PagedKVCache,
)
from distributed_training_tpu.telemetry import event

_STACKED = ("ln1", "ln2", "attn", "mlp")


@dataclass(frozen=True)
class EngineConfig:
    """Engine knobs (mirrored by ``conf/serving/default.yaml``)."""

    max_batch: int = 8            # decode slot count
    page_size: int = 16
    num_pages: int = 128
    max_seq_len: int = 256        # per-sequence cap (prompt + new)
    prefill_chunk: int = 32       # tokens per prefill step
    policy: str = "prefill"       # "prefill" | "decode" priority
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    kv_axis: str = "tp"           # pool kv-head shard axis
    paged_impl: str = "auto"      # ops/paged_attention dispatch

    def __post_init__(self):
        if self.policy not in ("prefill", "decode"):
            raise ValueError(
                f"unknown scheduling policy '{self.policy}' "
                "(expected 'prefill' or 'decode')")
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")


@dataclass
class Request:
    """One generation request. ``arrival`` defaults to submit time."""

    id: str
    prompt: np.ndarray
    max_new_tokens: int
    arrival: float | None = None


@dataclass
class _Seq:
    req: Request
    slot: int
    prefilled: int = 0            # prompt tokens consumed so far
    generated: list = field(default_factory=list)
    first_token_t: float | None = None
    token_times: list = field(default_factory=list)

    @property
    def prompt_len(self) -> int:
        return int(self.req.prompt.shape[0])

    @property
    def prefill_done(self) -> bool:
        return self.prefilled >= self.prompt_len

    @property
    def done(self) -> bool:
        return len(self.generated) >= self.req.max_new_tokens


def _rope_bhd(x, positions):
    """RoPE on (B, H, hd) with per-row absolute positions (B,) —
    the same freqs/rotation as models.transformer._rope (parity with
    the training stack is load-bearing: drift here is silent output
    corruption, caught by the paged⇄dense test)."""
    import jax.numpy as jnp

    D = x.shape[-1]
    half = D // 2
    freqs = 1.0 / (10000 ** (jnp.arange(half, dtype=jnp.float32)
                             / half))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]
    cos = jnp.cos(angles)[:, None, :]
    sin = jnp.sin(angles)[:, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos],
                           axis=-1).astype(x.dtype)


def _layer_norm(x, scale, bias):
    import jax
    import jax.numpy as jnp

    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + 1e-5)
    return (y * scale + bias).astype(dtype)


class Engine:
    """The continuous-batching engine over one model + weight set.

    ``params`` should already be placed (serving/disagg.py
    ``place_params`` for a planned layout); ``mesh`` shards the KV
    pool's kv-head axis over ``cfg.kv_axis`` when that axis has
    extent > 1. ``telemetry`` rides the ambient sink
    (telemetry/events.py) — every step emits a ``serving`` record the
    metrics endpoint folds into the ``dtt_serving_*`` gauges.
    """

    def __init__(self, model, params, cfg: EngineConfig,
                 mesh=None):
        import jax

        if getattr(model.cfg, "moe_num_experts", 0) > 0:
            raise ValueError(
                "serving engine has no MoE decode path (expert "
                "dispatch per single token is unimplemented)")
        if cfg.max_seq_len > model.cfg.max_seq_len:
            raise ValueError(
                f"engine max_seq_len ({cfg.max_seq_len}) exceeds the "
                f"model's ({model.cfg.max_seq_len})")
        self.model = model
        self.cfg = cfg
        self.mesh = mesh
        self.params = params
        self.cache = PagedKVCache(
            PagedCacheConfig(
                n_layers=model.cfg.n_layers,
                n_kv_heads=model.cfg.n_kv_heads,
                head_dim=model.cfg.head_dim,
                page_size=cfg.page_size,
                num_pages=cfg.num_pages,
                max_seq_len=cfg.max_seq_len,
                dtype=model.cfg.dtype),
            mesh=mesh, kv_axis=cfg.kv_axis)
        self.queue: collections.deque[Request] = collections.deque()
        self.slots: list[_Seq | None] = [None] * cfg.max_batch
        self.completed: list[dict] = []
        self._step_counter = 0
        self._base_rng = jax.random.PRNGKey(cfg.seed)
        self._build_programs()

    # -- jitted programs ---------------------------------------------------

    def _build_programs(self) -> None:
        import functools

        import jax

        c = self.model.cfg
        # Donate the pools: the decode/prefill programs functionally
        # update arrays that dominate serving HBM — without donation
        # every step would hold two live copies of the whole pool.
        self._decode_fn = jax.jit(
            functools.partial(_decode_program, cfg=c,
                              temperature=self.cfg.temperature,
                              top_k=self.cfg.top_k,
                              paged_impl=self.cfg.paged_impl),
            donate_argnums=(1, 2))
        self._prefill_first_fn = jax.jit(
            functools.partial(_prefill_program, cfg=c, first=True,
                              paged_impl=self.cfg.paged_impl),
            donate_argnums=(1, 2))
        self._prefill_cont_fn = jax.jit(
            functools.partial(_prefill_program, cfg=c, first=False,
                              paged_impl=self.cfg.paged_impl),
            donate_argnums=(1, 2))

    def compile_counts(self) -> dict:
        """Jit-cache sizes per program — the bench's zero-recompile
        assertion compares this dict before/after the storm."""
        return {
            "decode": self._decode_fn._cache_size(),
            "prefill_first": self._prefill_first_fn._cache_size(),
            "prefill_cont": self._prefill_cont_fn._cache_size(),
        }

    def warmup(self) -> dict:
        """Compile all three programs against scratch-only page rows
        (zero allocator side effects: every write lands in the
        scratch page). Returns compile_counts()."""
        import jax.numpy as jnp

        B, P = self.cfg.max_batch, self.cache.cfg.pages_per_seq
        C = self.cfg.prefill_chunk
        zrows = jnp.zeros((B, P), jnp.int32)
        toks = jnp.zeros((B,), jnp.int32)
        pos = jnp.zeros((B,), jnp.int32)
        act = jnp.zeros((B,), jnp.bool_)
        rng = jnp.zeros((2,), jnp.uint32)
        _t, k, v = self._decode_fn(self.params, self.cache.k_pages,
                                   self.cache.v_pages, toks, pos,
                                   zrows, act, rng)
        self.cache.update_pools(k, v)
        ctoks = jnp.zeros((1, C), jnp.int32)
        row = jnp.zeros((P,), jnp.int32)
        for fn in (self._prefill_first_fn, self._prefill_cont_fn):
            _lg, k, v = fn(self.params, self.cache.k_pages,
                           self.cache.v_pages, ctoks,
                           jnp.int32(0), jnp.int32(1), row)
            self.cache.update_pools(k, v)
        return self.compile_counts()

    # -- admission ---------------------------------------------------------

    def _validate(self, req: Request) -> None:
        if req.prompt.shape[0] == 0:
            raise ValueError(f"request {req.id}: empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.id}: max_new_tokens must be >= 1")
        total = req.prompt.shape[0] + req.max_new_tokens
        if total > self.cfg.max_seq_len:
            raise ValueError(
                f"request {req.id}: prompt ({req.prompt.shape[0]}) + "
                f"max_new_tokens ({req.max_new_tokens}) exceeds "
                f"max_seq_len ({self.cfg.max_seq_len})")

    def submit(self, req: Request) -> None:
        if req.arrival is None:
            req.arrival = time.monotonic()
        self._validate(req)
        self.queue.append(req)

    @property
    def in_flight(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def idle(self) -> bool:
        return not self.queue and self.in_flight == 0

    def _free_slot(self) -> int | None:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _admit(self) -> _Seq | None:
        """Move the head-of-queue request into a slot, pages for its
        FIRST chunk allocated. None when no slot/pages are free
        (backpressure — the request stays queued)."""
        if not self.queue:
            return None
        slot = self._free_slot()
        if slot is None:
            return None
        req = self.queue[0]
        first = min(req.prompt.shape[0], self.cfg.prefill_chunk)
        if not self.cache.can_admit(first):
            return None
        self.queue.popleft()
        self.cache.join(req.id)
        self.cache.ensure(req.id, first)
        seq = _Seq(req=req, slot=slot)
        self.slots[slot] = seq
        return seq

    # -- step --------------------------------------------------------------

    def _prefill_candidates(self) -> list[_Seq]:
        return [s for s in self.slots
                if s is not None and not s.prefill_done]

    def _decode_candidates(self) -> list[_Seq]:
        return [s for s in self.slots
                if s is not None and s.prefill_done and not s.done]

    def step(self) -> dict:
        """One scheduling decision + one compiled program launch.
        Returns a record of what ran (``kind``: prefill/decode/idle).
        """
        t0 = time.monotonic()
        pending = self._prefill_candidates()
        can_admit = (self.queue and self._free_slot() is not None)
        want_prefill = bool(pending or can_admit)
        decodable = self._decode_candidates()
        if self.cfg.policy == "prefill":
            kind = "prefill" if want_prefill else (
                "decode" if decodable else "idle")
        else:
            kind = "decode" if decodable else (
                "prefill" if want_prefill else "idle")
        tokens_out = 0
        if kind == "prefill":
            seq = pending[0] if pending else self._admit()
            # Backpressure fallback: when admission OR a mid-prompt
            # page allocation fails (pool exhausted), decode instead
            # — decoding sequences finish and free the pages the
            # prefill is waiting for. Without the second fallback a
            # prefill-priority engine livelocks: step() would pick
            # the stalled prefill forever and decode would never run
            # (regression-pinned in tests/test_serving.py).
            if seq is None or not self._run_prefill_chunk(seq):
                kind = "decode" if decodable else "idle"
        if kind == "decode":
            tokens_out = self._run_decode(decodable)
        dur = time.monotonic() - t0
        # "op", not "kind": telemetry's record envelope owns "kind"
        # (the event name), and a colliding field would silently
        # relabel the whole record past the metrics observer.
        rec = {"op": kind, "dur_s": dur, "tokens": tokens_out,
               "in_flight": self.in_flight,
               "queue_depth": len(self.queue),
               **self.cache.occupancy()}
        event("serving", **rec)
        self._step_counter += 1
        return rec

    def _run_prefill_chunk(self, seq: _Seq) -> bool:
        """One chunk of ``seq``'s prompt. False = no progress (the
        pool could not cover the chunk's pages — backpressure; the
        caller must let decode run so pages free up)."""
        import jax.numpy as jnp

        c = self.cfg
        start = seq.prefilled
        n_valid = min(c.prefill_chunk, seq.prompt_len - start)
        if not self.cache.ensure(seq.req.id, start + n_valid):
            return False
        chunk = np.zeros((1, c.prefill_chunk), np.int32)
        chunk[0, :n_valid] = seq.req.prompt[start:start + n_valid]
        row = jnp.asarray(self.cache.page_row(seq.req.id))
        fn = (self._prefill_first_fn if start == 0
              else self._prefill_cont_fn)
        logits, k, v = fn(self.params, self.cache.k_pages,
                          self.cache.v_pages, jnp.asarray(chunk),
                          jnp.int32(start), jnp.int32(n_valid), row)
        self.cache.update_pools(k, v)
        self.cache.advance(seq.req.id, n_valid)
        seq.prefilled = start + n_valid
        if seq.prefill_done:
            tok = self._sample_host(logits)
            now = time.monotonic()
            seq.first_token_t = now
            seq.token_times.append(now)
            seq.generated.append(tok)
            self._maybe_finish(seq)
        return True

    def _sample_host(self, logits) -> int:
        """Sample the prefill's first token on host — one token per
        request lifetime; the decode program samples the rest
        in-compiled."""
        import jax
        import jax.numpy as jnp

        if self.cfg.temperature <= 0:
            return int(jnp.argmax(logits))
        rng = jax.random.fold_in(self._base_rng,
                                 1_000_000 + self._step_counter)
        lg = logits / self.cfg.temperature
        if self.cfg.top_k:
            kth = jax.lax.top_k(lg, self.cfg.top_k)[0][-1]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        return int(jax.random.categorical(rng, lg))

    def _run_decode(self, decodable: list[_Seq]) -> int:
        import jax
        import jax.numpy as jnp

        B = self.cfg.max_batch
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        seq_ids: list = [None] * B
        stepped: list[_Seq] = []
        for s in decodable:
            # The new token's KV lands at position length(seq); make
            # sure a page covers it. Failure = pool exhausted: the
            # slot stalls this step and resumes when pages free.
            if not self.cache.ensure(s.req.id,
                                     self.cache.length(s.req.id) + 1):
                continue
            b = s.slot
            tokens[b] = s.generated[-1]
            positions[b] = self.cache.length(s.req.id)
            active[b] = True
            seq_ids[b] = s.req.id
            stepped.append(s)
        if not stepped:
            return 0
        rows = self.cache.page_rows(seq_ids)
        rng = jax.random.fold_in(self._base_rng, self._step_counter)
        nxt, k, v = self._decode_fn(
            self.params, self.cache.k_pages, self.cache.v_pages,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(rows), jnp.asarray(active),
            jax.random.key_data(rng))
        self.cache.update_pools(k, v)
        nxt = np.asarray(nxt)
        now = time.monotonic()
        for s in stepped:
            self.cache.advance(s.req.id, 1)
            s.generated.append(int(nxt[s.slot]))
            if s.first_token_t is None:
                s.first_token_t = now
            s.token_times.append(now)
            self._maybe_finish(s)
        return len(stepped)

    def _maybe_finish(self, seq: _Seq) -> None:
        if not seq.done:
            return
        self.cache.free(seq.req.id)
        self.slots[seq.slot] = None
        now = time.monotonic()
        arrival = seq.req.arrival if seq.req.arrival is not None \
            else now
        gaps = [b - a for a, b in zip(seq.token_times,
                                      seq.token_times[1:])]
        rec = {
            "id": seq.req.id,
            "prompt_tokens": seq.prompt_len,
            "new_tokens": len(seq.generated),
            "tokens": list(seq.generated),
            "ttft_s": (seq.first_token_t - arrival
                       if seq.first_token_t is not None else None),
            "latency_s": now - arrival,
            "token_gaps_s": gaps,
        }
        self.completed.append(rec)
        event("serving_request",
              **{k: rec[k] for k in ("id", "prompt_tokens",
                                     "new_tokens", "ttft_s",
                                     "latency_s")})

    # -- convenience -------------------------------------------------------

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        """Step until queue + slots are empty. Returns steps taken."""
        n = 0
        while not self.idle and n < max_steps:
            self.step()
            n += 1
        if not self.idle:
            raise RuntimeError(
                f"engine not drained after {max_steps} steps "
                f"(queue={len(self.queue)}, in_flight="
                f"{self.in_flight})")
        return n

    def generate(self, prompt: np.ndarray, max_new_tokens: int
                 ) -> list[int]:
        """One prompt through the full continuous-batching path
        (the generate-CLI route). Returns the generated token ids."""
        rid = f"gen-{self._step_counter}-{len(self.completed)}"
        self.submit(Request(id=rid,
                            prompt=np.asarray(prompt, np.int32),
                            max_new_tokens=max_new_tokens))
        self.run_until_drained()
        rec = next(r for r in reversed(self.completed)
                   if r["id"] == rid)
        return rec["tokens"]

    def adopt(self, req: Request, first_token: int,
              k_dense: np.ndarray, v_dense: np.ndarray) -> None:
        """Adopt an EXTERNALLY-PREFILLED sequence (the disaggregation
        handoff, serving/disagg.py): its prompt KV arrives as dense
        (L, Hkv, prompt_len, hd) arrays and is written into this
        engine's pages; decode continues here as if the prefill had
        run locally. ``first_token`` is the token the prefill slice
        sampled from its final logits."""
        from distributed_training_tpu.serving.disagg import import_kv

        if req.arrival is None:
            req.arrival = time.monotonic()
        self._validate(req)
        slot = self._free_slot()
        if slot is None:
            raise RuntimeError("no free slot to adopt into")
        self.cache.join(req.id)
        try:
            import_kv(self.cache, req.id, k_dense, v_dense)
        except Exception:
            # A failed import must not leak the joined table entry
            # (a retry of the same request id would hit "already
            # joined" forever).
            self.cache.free(req.id)
            raise
        seq = _Seq(req=req, slot=slot, prefilled=req.prompt.shape[0])
        now = time.monotonic()
        seq.first_token_t = now
        seq.token_times.append(now)
        seq.generated.append(int(first_token))
        self.slots[slot] = seq
        self._maybe_finish(seq)

    def preempt(self) -> list[Request]:
        """Simulated engine preemption: drop all device-side progress,
        free every page, and hand back the unfinished work (queued +
        in-flight requests, fresh — generation restarts from the
        prompt, the standard continuous-batching recovery). The
        engine is reusable afterwards (a restarted incarnation calls
        ``submit`` with these)."""
        lost: list[Request] = []
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            self.cache.free(s.req.id)
            self.slots[i] = None
            lost.append(Request(id=s.req.id, prompt=s.req.prompt,
                                max_new_tokens=s.req.max_new_tokens,
                                arrival=s.req.arrival))
        lost.extend(self.queue)
        self.queue.clear()
        event("serving_preempt", lost=len(lost))
        return lost


# ---------------------------------------------------------------------------
# The compiled programs (pure functions of arrays + static model cfg)
# ---------------------------------------------------------------------------


def _write_kv(k_pages, v_pages, k_new, v_new, page_ids, offsets):
    """Scatter per-row new KV into the layer's pool.

    k_pages/v_pages (Hkv, N, ps, hd); k_new/v_new (B, Hkv, hd);
    page_ids/offsets (B,) int32 — rows whose write must be dead point
    at the scratch page (id 0). Live rows never share a (page, slot)
    pair (pages are owned by exactly one sequence), so scatter order
    is immaterial; scratch-page collisions write garbage over
    garbage."""
    kT = k_new.transpose(1, 0, 2)          # (Hkv, B, hd)
    vT = v_new.transpose(1, 0, 2)
    k_pages = k_pages.at[:, page_ids, offsets].set(kT)
    v_pages = v_pages.at[:, page_ids, offsets].set(vT)
    return k_pages, v_pages


def _decode_program(params, k_pages, v_pages, tokens, positions,
                    page_tables, active, rng_data, *, cfg,
                    temperature, top_k, paged_impl):
    """One token for the whole slot table.

    tokens (B,) int32 — last sampled token per slot; positions (B,)
    — the ABSOLUTE position that token occupies (== kv entries
    already written); page_tables (B, P); active (B,) bool. Returns
    (next_tokens (B,), k_pages, v_pages). Inactive slots compute
    garbage into the scratch page and their sampled token is 0.
    """
    import jax
    import jax.numpy as jnp

    from distributed_training_tpu.ops.paged_attention import (
        paged_attention)

    dt = jnp.dtype(cfg.dtype)
    B = tokens.shape[0]
    ps = k_pages.shape[3]
    x = params["tok_embed"][tokens].astype(dt)            # (B, D)
    if cfg.pos_encoding == "learned":
        x = x + params["pos_embed"][positions].astype(dt)
    # Dead writes → scratch page 0, offset 0.
    page_ids = jnp.where(
        active,
        jnp.take_along_axis(page_tables,
                            (positions // ps)[:, None],
                            axis=1)[:, 0],
        0).astype(jnp.int32)
    offsets = jnp.where(active, positions % ps, 0).astype(jnp.int32)
    lengths = jnp.where(active, positions + 1, 0).astype(jnp.int32)
    stacked = {k: params[k] for k in _STACKED}

    def layer_body(x, inp):
        layer, kp, vp = inp
        h = _layer_norm(x, layer["ln1"]["scale"],
                        layer["ln1"]["bias"])
        q = jnp.einsum("bd,dhk->bhk", h,
                       layer["attn"]["wq"].astype(dt))
        k = jnp.einsum("bd,dhk->bhk", h,
                       layer["attn"]["wk"].astype(dt))
        v = jnp.einsum("bd,dhk->bhk", h,
                       layer["attn"]["wv"].astype(dt))
        if cfg.pos_encoding == "rope":
            q = _rope_bhd(q, positions)
            k = _rope_bhd(k, positions)
        kp, vp = _write_kv(kp, vp, k.astype(kp.dtype),
                           v.astype(vp.dtype), page_ids, offsets)
        attn = paged_attention(q, kp, vp, lengths, page_tables,
                               impl=paged_impl)
        x = x + jnp.einsum("bhk,hkd->bd", attn,
                           layer["attn"]["wo"].astype(dt))
        h = _layer_norm(x, layer["ln2"]["scale"],
                        layer["ln2"]["bias"])
        m = layer["mlp"]
        u = jax.nn.gelu(jnp.einsum("bd,df->bf", h,
                                   m["wi"].astype(dt))
                        + m["bi"].astype(dt))
        x = x + (jnp.einsum("bf,fd->bd", u, m["wo"].astype(dt))
                 + m["bo"].astype(dt))
        return x, (kp, vp)

    x, (k_pages, v_pages) = jax.lax.scan(
        layer_body, x, (stacked, k_pages, v_pages))
    x = _layer_norm(x, params["final_norm"]["scale"],
                    params["final_norm"]["bias"])
    head = (params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"])
    logits = jnp.einsum("bd,dv->bv", x,
                        head.astype(dt)).astype(jnp.float32)
    if temperature <= 0:
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        lg = logits / temperature
        if top_k:
            kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        keys = jax.random.split(
            jax.random.wrap_key_data(rng_data), B)
        nxt = jax.vmap(jax.random.categorical)(keys, lg).astype(
            jnp.int32)
    return jnp.where(active, nxt, 0), k_pages, v_pages


def _prefill_program(params, k_pages, v_pages, chunk_tokens,
                     start_pos, n_valid, page_row, *, cfg, first,
                     paged_impl):
    """One prompt chunk for one sequence.

    chunk_tokens (1, C) int32 (positions >= n_valid are padding);
    start_pos — the chunk's first absolute position; page_row (P,) —
    the sequence's table. Writes the chunk's KV into its pages and
    returns (next-token logits (V,) fp32 — from the LAST VALID
    position, meaningful when this is the prompt's final chunk —
    k_pages, v_pages).

    ``first=True`` (start_pos == 0, traced as a separate program):
    attention is ordinary causal self-attention over the chunk
    (ops.attention — the flash path on TPU). Continuation chunks
    attend the pages written so far plus themselves via the paged
    chunk form. Both write-then-read the pool identically, so the
    two programs' caches are interchangeable token-for-token.
    """
    import jax
    import jax.numpy as jnp

    from distributed_training_tpu.ops.attention import (
        dot_product_attention)
    from distributed_training_tpu.ops.paged_attention import (
        paged_attention_chunk)

    del paged_impl  # chunk form has no kernel path yet
    dt = jnp.dtype(cfg.dtype)
    C = chunk_tokens.shape[1]
    ps = k_pages.shape[3]
    idx = jnp.arange(C, dtype=jnp.int32)
    abs_pos = start_pos + idx                             # (C,)
    valid = idx < n_valid
    x = params["tok_embed"][chunk_tokens[0]].astype(dt)   # (C, D)
    if cfg.pos_encoding == "learned":
        # Clamp padding positions into range; their rows are dead.
        safe = jnp.minimum(abs_pos, cfg.max_seq_len - 1)
        x = x + params["pos_embed"][safe].astype(dt)
    page_ids = jnp.where(valid, page_row[abs_pos // ps], 0)
    offsets = jnp.where(valid, abs_pos % ps, 0)
    # Padding queries mask out of the paged form via negative
    # positions; the causal first-chunk form never lets a valid query
    # see a padding key (pads sit at higher positions).
    q_pos = jnp.where(valid, abs_pos, -1)[None, :]        # (1, C)
    stacked = {k: params[k] for k in _STACKED}

    def layer_body(x, inp):
        layer, kp, vp = inp
        h = _layer_norm(x, layer["ln1"]["scale"],
                        layer["ln1"]["bias"])
        q = jnp.einsum("cd,dhk->chk", h,
                       layer["attn"]["wq"].astype(dt))
        k = jnp.einsum("cd,dhk->chk", h,
                       layer["attn"]["wk"].astype(dt))
        v = jnp.einsum("cd,dhk->chk", h,
                       layer["attn"]["wv"].astype(dt))
        if cfg.pos_encoding == "rope":
            q = _rope_bhd(q, abs_pos)
            k = _rope_bhd(k, abs_pos)
        kp, vp = _write_kv(kp, vp, k.astype(kp.dtype),
                           v.astype(vp.dtype), page_ids, offsets)
        if first:
            attn = dot_product_attention(
                q[None], k[None], v[None], causal=True,
                impl=cfg.attention_impl
                if cfg.attention_impl in ("auto", "flash", "naive")
                else "auto",
                window=0)[0]
        else:
            attn = paged_attention_chunk(
                q[None], kp, vp, page_row[None], q_pos)[0]
        x = x + jnp.einsum("chk,hkd->cd", attn,
                           layer["attn"]["wo"].astype(dt))
        h = _layer_norm(x, layer["ln2"]["scale"],
                        layer["ln2"]["bias"])
        m = layer["mlp"]
        u = jax.nn.gelu(jnp.einsum("cd,df->cf", h,
                                   m["wi"].astype(dt))
                        + m["bi"].astype(dt))
        x = x + (jnp.einsum("cf,fd->cd", u, m["wo"].astype(dt))
                 + m["bo"].astype(dt))
        return x, (kp, vp)

    x, (k_pages, v_pages) = jax.lax.scan(
        layer_body, x, (stacked, k_pages, v_pages))
    x_last = jax.lax.dynamic_index_in_dim(
        x, jnp.maximum(n_valid - 1, 0), axis=0, keepdims=False)
    x_last = _layer_norm(x_last, params["final_norm"]["scale"],
                         params["final_norm"]["bias"])
    head = (params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"])
    logits = jnp.einsum("d,dv->v", x_last,
                        head.astype(dt)).astype(jnp.float32)
    return logits, k_pages, v_pages
