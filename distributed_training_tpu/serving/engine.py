"""Continuous-batching engine: admission queue → two jitted programs.

The serving hot loop. Requests join and leave the running batch at
every step (continuous batching — no head-of-line blocking behind the
longest sequence in a static batch), against TWO compiled programs
whose shapes never change, both BATCH-SHARDED over the mesh's ``dp``
axis (a ``shard_map`` manual over ``dp``; every other mesh axis —
``tp``'s head shard in particular — stays under the SPMD partitioner
via the ``auto`` axes):

- **batched prefill** — up to ``prefill_slots`` sequences' CURRENT
  prompt chunks in ONE launch: each dp group packs its own admitted
  prompts into ``prefill_slots/dp`` lanes of ``prefill_chunk`` tokens
  (per-lane page rows, start positions, valid counts, live masks —
  the SERVING_r02 per-group ``q_pos=-1`` masking generalized to a
  whole lane table) and writes their KV through one batched page-row
  scatter; the next token of every prompt-completing lane is sampled
  IN-PROGRAM, so completion reads a ``(G, slots)`` int32 block
  instead of a vocab-sized logits block per prompt. This replaces the
  one-sequence-per-launch prefill (which replicated a single chunk
  across dp groups with the dead groups masked — the launch-bound
  cost SERVING_r02's ledger recorded); that path survives as
  ``prefill_mode="sequential"`` for same-run comparison benches and
  the parity tests.
- **decode** — the ``max_batch`` slot table dealt into ``dp`` groups
  of ``max_batch/dp``, each group decoding only its own slots against
  its own KV pool shard; dp adds ZERO new collectives (rows are
  independent). With ``spec_k > 1`` the decode step is
  MULTI-TOKEN SELF-SPECULATIVE: each slot drafts ``spec_k - 1``
  tokens by prompt-lookup (the most recent earlier occurrence of the
  sequence's own trailing n-gram — no second model), verifies the
  whole chain in one batched forward (the same chunk program as
  batched prefill, emitting the argmax at EVERY position), and emits
  the accepted prefix. Greedy output is token-identical BY
  CONSTRUCTION: every emitted token is the verified argmax given the
  true prefix (a draft is accepted only when it equals the previous
  position's argmax), so speculation changes launch count, never
  tokens. Launch overhead amortizes by the acceptance length
  (telemetry: ``spec_accepted_mean`` on step records,
  ``Engine.spec_stats`` totals).

Join/evict never change a traced shape: admission fills a slot in ONE
group and allocates pages from that group's shard; completion frees
them; the programs compile once at warmup and never again
(``compile_counts`` exposes the jit cache sizes so the bench can
ASSERT zero recompiles mid-storm).

Admission is dp-aware: the queue load-balances across groups —
fewest-active-slots-first, pages permitting — so a burst cannot pile
onto one shard while the others idle (pinned by test under a skewed
arrival burst). Under batched prefill a prefill step admits as many
queued requests as slots+pages allow before launching (one admission
per step would starve the lane table it just paid for).

Scheduling policy (``EngineConfig.policy``):

- ``"prefill"`` (default): pending prompt work runs before decode —
  lowest TTFT, decode tokens stall behind prompt storms;
- ``"decode"``: the active batch decodes first; prompts admit only
  when no sequence can decode — best per-token latency, TTFT suffers.

``prefill_chunk`` is the per-step prefill token budget (one chunk per
step); decode emits up to ``max_batch`` tokens per step (all groups
fire in one program launch).

Sampling is greedy at ``temperature == 0`` (the parity-tested path —
token-for-token equal to full-context argmax); ``temperature > 0``
samples per-slot from a per-(step, group) folded key. Batch-
composition independence (a sequence's tokens don't depend on who
shares the batch OR which group it was dealt into) is exact for
greedy decoding and pinned by test.

Token streaming: ``add_token_listener(req_id, fn)`` registers a
callback fired as ``fn(token, done)`` the moment each token is
sampled — the HTTP server's chunked ``"stream": true`` path rides
this (serving/server.py); listener failures are isolated from the
step loop.

MoE models are rejected at construction: expert dispatch has no
serving decode path yet.
"""

from __future__ import annotations

import collections
import logging
import time
from dataclasses import dataclass, field

import numpy as np

from distributed_training_tpu.serving.kv_cache import (
    PagedCacheConfig,
    PagedKVCache,
)
from distributed_training_tpu.telemetry import event

logger = logging.getLogger(__name__)

_STACKED = ("ln1", "ln2", "attn", "mlp")


@dataclass(frozen=True)
class EngineConfig:
    """Engine knobs (mirrored by ``conf/serving/default.yaml``).

    ``max_batch`` is the AGGREGATE decode slot count across all dp
    groups; on a mesh whose ``dp_axis`` has extent G it must divide
    into G equal group-local tables. ``num_pages`` is the per-group
    pool shard size (serving/kv_cache.py). ``prefill_slots`` is the
    AGGREGATE lane count of the batched prefill program (0 = same as
    ``max_batch``), dealt over dp exactly like the decode table.
    ``spec_k`` is the tokens-per-decode-launch of the speculative
    program (1 = the plain one-token decode; > 1 requires greedy
    ``temperature == 0`` — acceptance verification is exact only for
    the argmax chain)."""

    max_batch: int = 8            # decode slots, aggregate over dp
    page_size: int = 16
    num_pages: int = 128          # per dp group
    max_seq_len: int = 256        # per-sequence cap (prompt + new)
    prefill_chunk: int = 32       # tokens per prefill lane per step
    prefill_slots: int = 0        # batched-prefill lanes (0 = max_batch)
    prefill_mode: str = "batched"  # "batched" | "sequential" (r02 path)
    spec_k: int = 1               # decode tokens per launch (1 = off)
    spec_ngram: int = 3           # longest prompt-lookup n-gram tried
    resident_k: int = 1           # device-resident decode steps (1 = off)
    prefix_sharing: bool = True   # refcounted prefix reuse + sessions
    eos_id: int = -1              # stop token (< 0 = disabled)
    policy: str = "prefill"       # "prefill" | "decode" priority
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    kv_axis: str = "tp"           # pool kv-head shard axis
    dp_axis: str = "dp"           # slot-table / pool batch shard axis
    paged_impl: str = "auto"      # ops/paged_attention dispatch
    swap_staleness_tokens: int = -1  # hot-swap bound (-1 = unbounded)

    def __post_init__(self):
        if self.swap_staleness_tokens < -1:
            raise ValueError(
                "swap_staleness_tokens must be >= -1 (-1 disables "
                "the bound; 0 resubmits every in-flight request with "
                "emitted tokens at swap time)")
        if self.policy not in ("prefill", "decode"):
            raise ValueError(
                f"unknown scheduling policy '{self.policy}' "
                "(expected 'prefill' or 'decode')")
        if self.prefill_mode not in ("batched", "sequential"):
            raise ValueError(
                f"unknown prefill_mode '{self.prefill_mode}' "
                "(expected 'batched' or 'sequential')")
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.prefill_slots < 0:
            raise ValueError("prefill_slots must be >= 0")
        if self.spec_k < 1:
            raise ValueError("spec_k must be >= 1")
        if self.spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1")
        if self.spec_k > 1 and self.temperature > 0:
            raise ValueError(
                "speculative decode (spec_k > 1) requires greedy "
                "temperature == 0 — the verification accepts exactly "
                "the argmax chain, which has no sampled analogue "
                "without rejection sampling")
        if self.resident_k < 1:
            raise ValueError("resident_k must be >= 1")
        if self.resident_k > 1 and self.temperature > 0:
            raise ValueError(
                "device-resident decode (resident_k > 1) requires "
                "greedy temperature == 0 — the in-program accept/"
                "stop logic is exact only for the argmax chain")
        if self.resident_k > 1 and self.prefill_mode != "batched":
            raise ValueError(
                "device-resident decode (resident_k > 1) requires "
                "prefill_mode='batched' — the sequential r02 prefill "
                "pulls a logits block per chunk, defeating the "
                "burst's one-sync contract")


@dataclass
class Request:
    """One generation request. ``arrival`` defaults to submit time.
    ``session``: chat-session key — on completion the sequence's KV
    pages are RETAINED under this key instead of freed, and a later
    request with the same key whose prompt extends the retained
    history re-attaches them (zero prefill for the shared part;
    an exact-history prompt needs zero prefill launches at all).
    ``tenant``: multi-tenant accounting label — threaded from the HTTP
    JSON body into the per-request ``serving_trace`` record and the
    tenant-labeled latency histograms; never affects scheduling."""

    id: str
    prompt: np.ndarray
    max_new_tokens: int
    arrival: float | None = None
    session: str | None = None
    tenant: str = "default"


@dataclass
class _Seq:
    req: Request
    slot: int                     # global slot id (group * B_local + i)
    prefilled: int = 0            # prompt tokens consumed so far
    generated: list = field(default_factory=list)
    first_token_t: float | None = None
    token_times: list = field(default_factory=list)
    eos: bool = False             # emitted the configured stop token
    ngram: "NgramIndex | None" = None  # lazy prompt-lookup index
    trace: list = field(default_factory=list)  # lifecycle spans
    queue_wait_s: float | None = None  # arrival -> admission
    prefix_hit: int = 0           # prompt tokens served from cache
    # Per-token weight-version tags, run-length encoded as
    # ``[version, count]`` pairs in emission order — a sequence that
    # straddles a hot-swap shows both versions; most show one.
    versions: list = field(default_factory=list)

    def span(self, ev: str, t: float, **fields) -> None:
        """Append a lifecycle span. ``t`` is an absolute monotonic
        host timestamp taken at a point the host already occupies
        (admission bookkeeping, the post-``_fetch_host`` reads every
        launch path takes) — stored RELATIVE to arrival so the trace
        is meaningful offline. Pure host-side list append: no device
        touch, no sync, no recompile."""
        rel = t - self.req.arrival if self.req.arrival is not None \
            else t
        self.trace.append({"ev": ev, "t": round(rel, 6), **fields})

    @property
    def prompt_len(self) -> int:
        return int(self.req.prompt.shape[0])

    @property
    def last_token(self) -> int:
        """The token the next decode launch feeds. A zero-prefill
        admission (full prefix hit / exact session resume) starts
        decoding with NOTHING generated yet — it replays the last
        PROMPT token at its already-resident position (the COW'd
        boundary page takes the rewrite), which samples exactly the
        first token a prefill launch would have."""
        return int(self.generated[-1]) if self.generated \
            else int(self.req.prompt[-1])

    @property
    def prefill_done(self) -> bool:
        return self.prefilled >= self.prompt_len

    @property
    def done(self) -> bool:
        return self.eos or \
            len(self.generated) >= self.req.max_new_tokens


def _rope_bhd(x, positions):
    """RoPE on (..., H, hd) with per-row absolute positions (...) —
    the same freqs/rotation as models.transformer._rope (parity with
    the training stack is load-bearing: drift here is silent output
    corruption, caught by the paged⇄dense test). The leading shape is
    free: (B,) rows for the one-token decode, (S, C) lanes×positions
    for the batched chunk program."""
    import jax.numpy as jnp

    D = x.shape[-1]
    half = D // 2
    freqs = 1.0 / (10000 ** (jnp.arange(half, dtype=jnp.float32)
                             / half))
    angles = positions[..., None].astype(jnp.float32) * freqs
    cos = jnp.cos(angles)[..., None, :]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos],
                           axis=-1).astype(x.dtype)


def _layer_norm(x, scale, bias):
    import jax
    import jax.numpy as jnp

    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + 1e-5)
    return (y * scale + bias).astype(dtype)


def _w(leaf, dt):
    """A weight leaf in compute dtype. An int8 weight-only leaf is a
    dict ``{"qw": int8, "scale": fp32}`` with per-output-channel
    scales (serving/disagg.py ``quantize_params_int8``) and is
    DEQUANTIZED AT COMPUTE — the stored layout (and its tp/fsdp
    partition specs) stays int8; plain arrays cast exactly as
    before. Every weight einsum in the serving programs reads its
    operand through this one helper so the fp32 and int8 paths
    cannot drift."""
    if isinstance(leaf, dict):
        return leaf["qw"].astype(dt) * leaf["scale"].astype(dt)
    return leaf.astype(dt)


def draft_tokens(history: np.ndarray, m: int,
                 ngram_max: int = 3) -> np.ndarray:
    """Prompt-lookup drafting: ``m`` speculative tokens from the
    sequence's OWN history (prompt + generated) — no second model.

    Finds the most recent EARLIER occurrence of the history's
    trailing n-gram (longest n <= ngram_max first) and drafts the
    tokens that followed it; short continuations pad with the last
    token, and a history with no repeated n-gram drafts the last
    token repeated. Draft quality only moves the ACCEPTANCE LENGTH —
    never the output: verification emits exactly the argmax chain
    regardless (serving/engine.py spec decode)."""
    hist = np.array(history, np.int32)
    L = hist.shape[0]
    if m <= 0 or L == 0:
        return np.zeros((max(0, m),), np.int32)
    fill = int(hist[-1])
    for n in range(min(ngram_max, L - 1), 0, -1):
        pat = hist[L - n:]
        # All windows starting strictly before the trailing n-gram
        # itself (an occurrence needs at least one continuation
        # token).
        win = np.lib.stride_tricks.sliding_window_view(
            hist, n)[:L - n]
        matches = np.nonzero((win == pat).all(axis=1))[0]
        if matches.size:
            p = int(matches[-1])
            cont = hist[p + n:p + n + m]
            if cont.shape[0] < m:
                cont = np.concatenate([
                    cont, np.full((m - cont.shape[0],), fill,
                                  np.int32)])
            return cont.astype(np.int32)
    return np.full((m,), fill, np.int32)


class NgramIndex:
    """Incremental trailing-n-gram index behind ``Engine._draft``.

    ``draft_tokens`` re-scans the sequence's FULL history with a
    sliding-window numpy pass per launch — O(L · ngram) per slot per
    launch, the dominant host cost of a long sequence's speculative
    step. This keeps, per n <= ngram_max, a dict from n-gram tuple to
    its MOST RECENT start plus a per-start link to the previous start
    of the same gram, updated in O(ngram) per appended token — so a
    draft is a dict probe, not a rescan. Drafts are pinned IDENTICAL
    to ``draft_tokens`` by a randomized test (draft quality only
    moves acceptance length, but the pin keeps the ledgers
    comparable across revisions)."""

    def __init__(self, ngram_max: int = 3):
        self.ngram_max = ngram_max
        self.hist: list[int] = []
        # maps[n-1]: gram tuple -> most recent start index;
        # prev[n-1]: start index -> previous start of the same gram.
        self._maps: list[dict] = [{} for _ in range(ngram_max)]
        self._prev: list[dict] = [{} for _ in range(ngram_max)]

    def __len__(self) -> int:
        return len(self.hist)

    def extend(self, tokens) -> None:
        for t in tokens:
            self.append(int(t))

    def append(self, t: int) -> None:
        self.hist.append(int(t))
        L = len(self.hist)
        for n in range(1, self.ngram_max + 1):
            if L < n:
                break
            start = L - n
            gram = tuple(self.hist[start:])
            m = self._maps[n - 1]
            if gram in m:
                self._prev[n - 1][start] = m[gram]
            m[gram] = start

    def draft(self, m: int) -> np.ndarray:
        """``m`` drafted tokens — same contract (and pinned same
        output) as ``draft_tokens(hist, m, ngram_max)``."""
        L = len(self.hist)
        if m <= 0 or L == 0:
            return np.zeros((max(0, m),), np.int32)
        fill = self.hist[-1]
        for n in range(min(self.ngram_max, L - 1), 0, -1):
            pat = tuple(self.hist[L - n:])
            p = self._maps[n - 1].get(pat)
            if p == L - n:
                # The trailing gram itself — an occurrence needs a
                # continuation token, so step to the previous start
                # (draft_tokens' windows stop at L - n).
                p = self._prev[n - 1].get(p)
            if p is None:
                continue
            cont = self.hist[p + n:p + n + m]
            return np.array(cont + [fill] * (m - len(cont)),
                            np.int32)
        return np.full((m,), fill, np.int32)


# ---------------------------------------------------------------------------
# Program builders (shared by the engine and the planner's stage-2
# serving verifier, serving/disagg.py — the verified program and the
# served program are constructed HERE, once, so they cannot drift)
# ---------------------------------------------------------------------------


def _dp_extent(mesh, dp_axis: str) -> int:
    if mesh is None:
        return 1
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return sizes.get(dp_axis, 1)


def _sharded(body, mesh, dp_axis: str, n_grouped: int,
             n_replicated: int, n_outs: int):
    """Wrap a group-local program body in a shard_map manual over the
    dp axis. Argument order contract: ``params`` first, then
    ``n_grouped`` group-batched arrays (leading dp-group dim, spec
    P(dp)), then ``n_replicated`` replicated args; all ``n_outs``
    outputs are group-batched. Every OTHER mesh axis is an ``auto``
    axis — tp's head shard (params + pool kv-head dim) stays under
    the SPMD partitioner exactly as in the unsharded engine."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    grouped = P(dp_axis)
    in_specs = ((P(),) + (grouped,) * n_grouped
                + (P(),) * n_replicated)
    out_specs = (grouped,) * n_outs
    return shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
        auto=frozenset(mesh.axis_names) - {dp_axis})


def _out_shardings(model_cfg, ecfg: EngineConfig, mesh):
    """(per-group result sharding, pool sharding) for the jitted
    programs' ``out_shardings``. Pinning these is load-bearing:
    shard_map's out_specs only fix the MANUAL dp axis, so without an
    explicit jit-level constraint the pool's tp (auto-axis) layout
    could drift between warmup and the storm and force a mid-storm
    recompile. One resolution shared with the cache's device_put
    (kv_cache.pool_sharding)."""
    from distributed_training_tpu.serving.kv_cache import (
        pool_sharding)
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        return None, None
    G = _dp_extent(mesh, ecfg.dp_axis)
    pool = pool_sharding(mesh, model_cfg.n_kv_heads, G,
                         ecfg.kv_axis, ecfg.dp_axis)
    grp = NamedSharding(mesh, P(ecfg.dp_axis if G > 1 else None))
    return grp, pool


def build_decode_fn(model_cfg, ecfg: EngineConfig, mesh=None):
    """The jitted dp-sharded decode program for (model, engine cfg,
    mesh). Signature (all group-batched, G = dp extent, B = group-
    local slots): ``fn(params, k_pages, v_pages, tokens (G, B),
    positions (G, B), page_tables (G, B, P), active (G, B), rng_data
    (G, 2)) -> (next_tokens (G, B), k_pages, v_pages)``. Pools are
    donated (serving HBM's dominant term must not hold two copies)."""
    import functools

    import jax

    body = functools.partial(
        _decode_program, cfg=model_cfg,
        temperature=ecfg.temperature, top_k=ecfg.top_k,
        paged_impl=ecfg.paged_impl)
    kw = {}
    if mesh is not None:
        grp, pool = _out_shardings(model_cfg, ecfg, mesh)
        kw["out_shardings"] = (grp, pool, pool)
    if _dp_extent(mesh, ecfg.dp_axis) > 1:
        body = _sharded(body, mesh, ecfg.dp_axis,
                        n_grouped=7, n_replicated=0, n_outs=3)
    return jax.jit(body, donate_argnums=(1, 2), **kw)


def build_prefill_fn(model_cfg, ecfg: EngineConfig, first: bool,
                     mesh=None):
    """The jitted prefill program (first or continuation chunk).
    Signature: ``fn(params, k_pages, v_pages, page_row (G, P),
    live (G,), chunk (1, C), start_pos, n_valid) -> (logits (G, V),
    k_pages, v_pages)``. The chunk is replicated across groups; only
    the ``live`` group's pool shard takes real writes (the rest land
    in scratch) and only its logits row is meaningful for
    continuation chunks."""
    import functools

    import jax

    body = functools.partial(
        _prefill_program, cfg=model_cfg, first=first,
        paged_impl=ecfg.paged_impl)
    kw = {}
    if mesh is not None:
        grp, pool = _out_shardings(model_cfg, ecfg, mesh)
        kw["out_shardings"] = (grp, pool, pool)
    if _dp_extent(mesh, ecfg.dp_axis) > 1:
        body = _sharded(body, mesh, ecfg.dp_axis,
                        n_grouped=4, n_replicated=3, n_outs=3)
    return jax.jit(body, donate_argnums=(1, 2), **kw)


def _chunk_fn(model_cfg, ecfg: EngineConfig, emit: str, mesh=None):
    """Jit the multi-lane chunk program (``_chunk_program``) for
    (model, engine cfg, mesh). Signature (all group-batched, G = dp
    extent, S = lanes per group, C = tokens per lane):
    ``fn(params, k_pages, v_pages, page_rows (G, S, P),
    tokens (G, S, C), start_pos (G, S), n_valid (G, S),
    active (G, S), rng_data (G, 2)) -> (next_tokens, k_pages,
    v_pages)`` where next_tokens is (G, S) for ``emit="last"`` (the
    batched-prefill first-token sample) and (G, S, C) for
    ``emit="all"`` (the speculative verification chain). Pools are
    donated."""
    import functools

    import jax

    body = functools.partial(
        _chunk_program, cfg=model_cfg,
        temperature=ecfg.temperature, top_k=ecfg.top_k,
        paged_impl=ecfg.paged_impl, emit=emit)
    kw = {}
    if mesh is not None:
        grp, pool = _out_shardings(model_cfg, ecfg, mesh)
        kw["out_shardings"] = (grp, pool, pool)
    if _dp_extent(mesh, ecfg.dp_axis) > 1:
        body = _sharded(body, mesh, ecfg.dp_axis,
                        n_grouped=8, n_replicated=0, n_outs=3)
    return jax.jit(body, donate_argnums=(1, 2), **kw)


def build_prefill_batch_fn(model_cfg, ecfg: EngineConfig, mesh=None):
    """The jitted BATCHED multi-sequence prefill program: up to
    ``prefill_slots/dp`` prompt chunks per group in one launch, each
    lane writing its chunk's KV through the batched page-row scatter
    and sampling its next token in-program (the first token of every
    prompt-completing lane — read as one (G, S) int32 block, never a
    vocab-sized logits transfer)."""
    return _chunk_fn(model_cfg, ecfg, emit="last", mesh=mesh)


def build_spec_decode_fn(model_cfg, ecfg: EngineConfig, mesh=None):
    """The jitted MULTI-TOKEN speculative decode program: ``spec_k``
    tokens per slot across the whole dealt slot table in one launch —
    lane c's argmax is the verified next token GIVEN the drafted
    prefix, so the host accepts exactly the prefix whose drafts match
    the chain (greedy-token-identical by construction)."""
    return _chunk_fn(model_cfg, ecfg, emit="all", mesh=mesh)


def build_resident_decode_fn(model_cfg, ecfg: EngineConfig,
                             mesh=None):
    """The jitted DEVICE-RESIDENT decode program: a
    ``lax.while_loop`` of up to ``resident_k`` chunk iterations
    (each one a ``spec_k``-wide speculative step — the same
    ``_chunk_hidden`` math as the host-driven paths), drafting,
    verifying, stop-detecting (EOS / budget) and advancing each
    slot's page cursor IN-PROGRAM. The host syncs once per burst.

    Signature (all group-batched, G = dp extent, B = group-local
    slots, Lmax = max_seq_len, T = resident_k * spec_k):
    ``fn(params, k_pages, v_pages, page_rows (G, B, P), history
    (G, B, Lmax), kv_len (G, B), budget (G, B), active (G, B)) ->
    (out (G, B, T), n_emitted (G, B), steps (G,), k_pages,
    v_pages)``. Pools are donated. An all-slots-complete burst
    returns early via the loop predicate."""
    import functools

    import jax

    body = functools.partial(
        _resident_program, cfg=model_cfg, K=ecfg.resident_k,
        C=ecfg.spec_k, ngram=ecfg.spec_ngram, eos_id=ecfg.eos_id,
        paged_impl=ecfg.paged_impl)
    kw = {}
    if mesh is not None:
        grp, pool = _out_shardings(model_cfg, ecfg, mesh)
        kw["out_shardings"] = (grp, grp, grp, pool, pool)
    if _dp_extent(mesh, ecfg.dp_axis) > 1:
        body = _sharded(body, mesh, ecfg.dp_axis,
                        n_grouped=7, n_replicated=0, n_outs=5)
    return jax.jit(body, donate_argnums=(1, 2), **kw)


def _cow_program(k_pages, v_pages, src, dst):
    """Copy-on-write page copy for one dp group's pool shard:
    ``k/v_pages`` (1, L, Hkv, N, ps, hd), ``src``/``dst`` (1, W)
    int32 page ids. One batched gather + scatter per pool — W page
    copies in ONE launch, no per-token host sync, zero collectives
    (pages never cross a group shard). Unused lanes ride as
    (0 -> 0): a scratch-to-scratch identity copy, the same dead-write
    trick as the decode program's inactive slots."""
    s, d = src[0], dst[0]

    def copy(pages):
        g = pages[0]                       # (L, Hkv, N, ps, hd)
        return g.at[:, :, d].set(g[:, :, s])[None]

    return copy(k_pages), copy(v_pages)


def build_cow_fn(model_cfg, ecfg: EngineConfig, mesh=None):
    """The jitted COW page-copy program. Signature:
    ``fn(k_pages, v_pages, src (G, W), dst (G, W)) -> (k_pages,
    v_pages)`` — pools donated (the copy must not double the serving
    HBM's dominant term), fixed W so a storm's forks never change a
    traced shape."""
    import jax

    body = _cow_program
    kw = {}
    if mesh is not None:
        _grp, pool = _out_shardings(model_cfg, ecfg, mesh)
        kw["out_shardings"] = (pool, pool)
    if _dp_extent(mesh, ecfg.dp_axis) > 1:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        grouped = P(ecfg.dp_axis)
        body = shard_map(
            body, mesh=mesh, in_specs=(grouped,) * 4,
            out_specs=(grouped,) * 2, check_rep=False,
            auto=frozenset(mesh.axis_names) - {ecfg.dp_axis})
    return jax.jit(body, donate_argnums=(0, 1), **kw)


class Engine:
    """The continuous-batching engine over one model + weight set.

    ``params`` should already be placed (serving/disagg.py
    ``place_params`` for a planned layout); ``mesh`` shards the KV
    pool's kv-head axis over ``cfg.kv_axis`` and the slot table +
    pool's group axis over ``cfg.dp_axis`` (each axis when its extent
    is > 1). ``telemetry`` rides the ambient sink
    (telemetry/events.py) — every step emits a ``serving`` record the
    metrics endpoint folds into the ``dtt_serving_*`` gauges,
    per-group stats included.
    """

    def __init__(self, model, params, cfg: EngineConfig,
                 mesh=None, weights_version: str = "v0",
                 weights_provenance: dict | None = None):
        import jax

        if getattr(model.cfg, "moe_num_experts", 0) > 0:
            raise ValueError(
                "serving engine has no MoE decode path (expert "
                "dispatch per single token is unimplemented)")
        if cfg.max_seq_len > model.cfg.max_seq_len:
            raise ValueError(
                f"engine max_seq_len ({cfg.max_seq_len}) exceeds the "
                f"model's ({model.cfg.max_seq_len})")
        self.model = model
        self.cfg = cfg
        self.mesh = mesh
        self.params = params
        # Live-swap state (``swap_weights`` is the ONLY other place
        # allowed to rebind ``self.params`` — pitfalls rule DTT011).
        self.weights_version = weights_version
        self.weights_provenance = (dict(weights_provenance)
                                   if weights_provenance else None)
        self.swap_stats = {"installed": 0, "refused": 0,
                           "stale_preempted": 0}
        self.dp_groups = _dp_extent(mesh, cfg.dp_axis)
        if cfg.max_batch % self.dp_groups:
            raise ValueError(
                f"max_batch ({cfg.max_batch}) must divide over the "
                f"{self.dp_groups} dp group(s) — the slot table is "
                "dealt into equal group-local tables")
        self.batch_local = cfg.max_batch // self.dp_groups
        prefill_slots = cfg.prefill_slots or cfg.max_batch
        if prefill_slots % self.dp_groups:
            raise ValueError(
                f"prefill_slots ({prefill_slots}) must divide over "
                f"the {self.dp_groups} dp group(s) — the prefill "
                "lane table deals exactly like the decode table")
        self.prefill_local = prefill_slots // self.dp_groups
        # Speculative-decode accounting (the acceptance-length
        # telemetry the bench ledgers): per-slot-launch totals, plus
        # the last step's numbers for the step record.
        self.spec_stats = {"launches": 0, "emitted": 0}
        self._step_spec: tuple[int, int] | None = None
        self._last_prefill_lanes: list[int] | None = None
        # Device-resident decode accounting: program launches and
        # total in-program loop iterations (the burst depth the
        # ``dtt_serving_resident_steps_per_launch`` gauge tracks).
        self.resident_stats = {"launches": 0, "steps": 0,
                               "emitted": 0}
        self._step_resident: tuple[float, int] | None = None
        # Prefix sharing + chat sessions (SERVING_r05). ``sessions``
        # maps session key -> retained state (cache id holding the
        # parked pages, the full token history they cover, the owning
        # dp group, last-use time for LRU eviction under pool
        # pressure). The stats totals feed the bench ledger; the
        # per-step pair feeds the step record the metrics endpoint
        # folds into the dtt_serving_prefix_* counters.
        self._sharing = cfg.prefix_sharing
        self.sessions: dict[str, dict] = {}
        self.prefix_stats = {"hit_tokens": 0, "saved_tokens": 0,
                             "cow_pages": 0, "session_resumes": 0}
        self._step_prefix = [0, 0]
        # Prefill-compute accounting for the sharing win: prompt
        # tokens actually pushed through a prefill program, and
        # prefill program launches (a zero-prefill session re-attach
        # must not move either).
        self.prefill_tokens_computed = 0
        self.prefill_launches = 0
        self._cow_width = max(self.batch_local, self.prefill_local)
        # EVERY device->host sync in the serving hot path goes
        # through ``_fetch_host`` (pitfalls rule DTT010), so this
        # counter is exact — the bench asserts syncs <= tokens /
        # resident_k + completions.
        self.host_syncs = 0
        self.weight_bytes = int(sum(
            getattr(x, "nbytes", 0)
            for x in jax.tree.leaves(params)))
        self.cache = PagedKVCache(
            PagedCacheConfig(
                n_layers=model.cfg.n_layers,
                n_kv_heads=model.cfg.n_kv_heads,
                head_dim=model.cfg.head_dim,
                page_size=cfg.page_size,
                num_pages=cfg.num_pages,
                max_seq_len=cfg.max_seq_len,
                dtype=model.cfg.dtype,
                dp_groups=self.dp_groups),
            mesh=mesh, kv_axis=cfg.kv_axis, dp_axis=cfg.dp_axis)
        self.queue: collections.deque[Request] = collections.deque()
        self.slots: list[_Seq | None] = [None] * cfg.max_batch
        self.completed: list[dict] = []
        self._step_counter = 0
        self._base_rng = jax.random.PRNGKey(cfg.seed)
        self._token_listeners: dict[str, object] = {}
        # Exactly-once stream state: per-request emitted-token
        # high-water mark. SURVIVES preemption (unlike the listener
        # registry) so a resubmitted request's regenerated prefix —
        # greedy decode makes it token-identical — is never delivered
        # twice; popped only at completion. ``finished_total`` is the
        # monotone progress counter the serving supervisor's restart
        # budget refunds against.
        self._emit_hwm: dict[str, int] = {}
        self.finished_total = 0
        # Drain / fault-injection state: ``draining`` gates admission
        # only (in-flight work keeps stepping); ``launch_count`` is
        # the serving analogue of the global step — one per non-idle
        # step — that ``resilience/faults.py`` serving kinds key on
        # via the ``faults`` injector slot (None = no injection).
        self.draining = False
        self.launch_count = 0
        self.faults = None
        self._build_programs()
        # Greedy decode never reads the rng operand — fold_in/
        # key_data are ~5 device dispatches PER STEP, and on the CPU
        # mesh that was ~40% of the decode step's wall clock
        # (SERVING_r02's dispatch-bound profile). One cached zero key
        # per group replaces them when temperature == 0.
        import jax.numpy as jnp
        self._zero_rng = jnp.zeros((self.dp_groups, 2), jnp.uint32)

    # -- jitted programs ---------------------------------------------------

    def _build_programs(self) -> None:
        c = self.model.cfg
        if self.cfg.resident_k > 1:
            # The device-resident K-step loop IS the decode program:
            # each loop iteration is one spec_k-wide chunk (spec_k=1
            # degenerates to plain one-token steps), so speculation
            # composes inside the burst. One jit entry, one sync per
            # burst.
            self._decode_fn = build_resident_decode_fn(
                c, self.cfg, self.mesh)
        elif self.cfg.spec_k > 1:
            # Multi-token decode IS the chunk program at C = spec_k
            # (even an effective one-token launch — pages tight, or
            # one token remaining — rides it with n_valid = 1: one
            # program, one jit entry, zero recompiles).
            self._decode_fn = build_spec_decode_fn(c, self.cfg,
                                                   self.mesh)
        else:
            self._decode_fn = build_decode_fn(c, self.cfg, self.mesh)
        if self.cfg.prefill_mode == "batched":
            self._prefill_batch_fn = build_prefill_batch_fn(
                c, self.cfg, mesh=self.mesh)
        else:
            self._prefill_first_fn = build_prefill_fn(
                c, self.cfg, first=True, mesh=self.mesh)
            self._prefill_cont_fn = build_prefill_fn(
                c, self.cfg, first=False, mesh=self.mesh)
        if self._sharing:
            self._cow_fn = build_cow_fn(c, self.cfg, mesh=self.mesh)

    def compile_counts(self) -> dict:
        """Jit-cache sizes per program — the bench's zero-recompile
        assertion compares this dict before/after the storm."""
        counts = {"decode": self._decode_fn._cache_size()}
        if self.cfg.prefill_mode == "batched":
            counts["prefill_batch"] = \
                self._prefill_batch_fn._cache_size()
        else:
            counts["prefill_first"] = \
                self._prefill_first_fn._cache_size()
            counts["prefill_cont"] = \
                self._prefill_cont_fn._cache_size()
        if self._sharing:
            counts["cow"] = self._cow_fn._cache_size()
        return counts

    def warmup(self) -> dict:
        """Compile every program against scratch-only page rows and
        all-dead lanes (zero allocator side effects: every write
        lands in each group's scratch page). Returns
        compile_counts()."""
        import jax.numpy as jnp

        G, B = self.dp_groups, self.batch_local
        P = self.cache.cfg.pages_per_seq
        C = self.cfg.prefill_chunk
        rng = jnp.zeros((G, 2), jnp.uint32)
        if self.cfg.resident_k > 1:
            # All-dead burst: zero budgets fail the loop predicate at
            # iteration 0 (the all-slots-complete early exit), but
            # tracing still compiles the full resident body.
            _o, _n, _s, k, v = self._decode_fn(
                self.params, self.cache.k_pages, self.cache.v_pages,
                jnp.zeros((G, B, P), jnp.int32),
                jnp.zeros((G, B, self.cfg.max_seq_len), jnp.int32),
                jnp.zeros((G, B), jnp.int32),
                jnp.zeros((G, B), jnp.int32),
                jnp.zeros((G, B), jnp.bool_))
        elif self.cfg.spec_k > 1:
            _t, k, v = self._decode_fn(
                self.params, self.cache.k_pages, self.cache.v_pages,
                jnp.zeros((G, B, P), jnp.int32),
                jnp.zeros((G, B, self.cfg.spec_k), jnp.int32),
                jnp.zeros((G, B), jnp.int32),
                jnp.zeros((G, B), jnp.int32),
                jnp.zeros((G, B), jnp.bool_), rng)
        else:
            _t, k, v = self._decode_fn(
                self.params, self.cache.k_pages, self.cache.v_pages,
                jnp.zeros((G, B), jnp.int32),
                jnp.zeros((G, B), jnp.int32),
                jnp.zeros((G, B, P), jnp.int32),
                jnp.zeros((G, B), jnp.bool_), rng)
        self.cache.update_pools(k, v)
        if self.cfg.prefill_mode == "batched":
            Sp = self.prefill_local
            _t, k, v = self._prefill_batch_fn(
                self.params, self.cache.k_pages, self.cache.v_pages,
                jnp.zeros((G, Sp, P), jnp.int32),
                jnp.zeros((G, Sp, C), jnp.int32),
                jnp.zeros((G, Sp), jnp.int32),
                jnp.zeros((G, Sp), jnp.int32),
                jnp.zeros((G, Sp), jnp.bool_), rng)
            self.cache.update_pools(k, v)
        else:
            ctoks = jnp.zeros((1, C), jnp.int32)
            row = jnp.zeros((G, P), jnp.int32)
            live = jnp.zeros((G,), jnp.bool_)
            for fn in (self._prefill_first_fn,
                       self._prefill_cont_fn):
                # Plain-int scalars, matching the step loop's calls —
                # a jnp.int32() here would warm a DIFFERENT
                # (non-weak) jit entry than the one the storm hits.
                _lg, k, v = fn(self.params, self.cache.k_pages,
                               self.cache.v_pages, row, live, ctoks,
                               0, 1)
                self.cache.update_pools(k, v)
        if self._sharing:
            # Scratch-to-scratch identity copies: compiles the COW
            # program with zero allocator side effects.
            W = self._cow_width
            k, v = self._cow_fn(
                self.cache.k_pages, self.cache.v_pages,
                jnp.zeros((G, W), jnp.int32),
                jnp.zeros((G, W), jnp.int32))
            self.cache.update_pools(k, v)
        return self.compile_counts()

    # -- admission ---------------------------------------------------------

    def _validate(self, req: Request) -> None:
        if req.prompt.shape[0] == 0:
            raise ValueError(f"request {req.id}: empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.id}: max_new_tokens must be >= 1")
        total = req.prompt.shape[0] + req.max_new_tokens
        if total > self.cfg.max_seq_len:
            raise ValueError(
                f"request {req.id}: prompt ({req.prompt.shape[0]}) + "
                f"max_new_tokens ({req.max_new_tokens}) exceeds "
                f"max_seq_len ({self.cfg.max_seq_len})")

    def submit(self, req: Request) -> None:
        if req.arrival is None:
            req.arrival = time.monotonic()
        self._validate(req)
        self.queue.append(req)

    # -- request-lifecycle tracing ------------------------------------------
    #
    # Spans are host-side list appends at points the admission /
    # launch bookkeeping already occupies; timestamps reuse the
    # monotonic reads the engine already takes after ``_fetch_host``
    # where one exists. Zero device syncs (DTT010), zero new jit
    # entries, and the only write path is telemetry.event() — see
    # telemetry/serving_trace.py for the schema the analyzer pins.

    def _mark_admitted(self, seq: _Seq, ev: str, **fields) -> None:
        """Open a sequence's trace: queued at t=0 (arrival), then the
        admission span (``admitted`` / ``resumed`` / ``adopted``).
        ``queue_wait_s`` is fixed here — a resubmitted-after-preempt
        request keeps its ORIGINAL arrival, so its second trace shows
        the full wait including the lost first pass."""
        now = time.monotonic()
        seq.trace.append({"ev": "queued", "t": 0.0})
        seq.span(ev, now, slot=seq.slot, **fields)
        if seq.req.arrival is not None:
            seq.queue_wait_s = now - seq.req.arrival
        seq.prefix_hit = int(fields.get("prefix_hit_tokens")
                             or fields.get("hit_tokens") or 0)

    def _emit_trace(self, seq: _Seq, outcome: str, now: float,
                    tokens_discarded: int = 0) -> None:
        """Close a sequence's trace and emit the ``serving_trace``
        record through the ambient sink. ``now`` is a timestamp the
        caller already took (post-fetch or preempt bookkeeping)."""
        seq.span(outcome, now,
                 **({"tokens_discarded": tokens_discarded}
                    if outcome == "preempted" else {}))
        arrival = seq.req.arrival
        ttft = None
        if seq.first_token_t is not None and arrival is not None:
            ttft = seq.first_token_t - arrival
        event("serving_trace",
              id=seq.req.id,
              tenant=seq.req.tenant,
              outcome=outcome,
              prompt_tokens=seq.prompt_len,
              new_tokens=len(seq.generated),
              queue_wait_s=seq.queue_wait_s,
              ttft_s=ttft,
              e2e_s=(now - arrival) if arrival is not None else None,
              prefix_hit_tokens=seq.prefix_hit,
              tokens_discarded=tokens_discarded,
              weights_versions=[list(p) for p in seq.versions],
              spans=list(seq.trace))

    def add_token_listener(self, req_id: str, fn) -> None:
        """Register ``fn(token: int, done: bool)`` to fire as each of
        ``req_id``'s tokens is sampled (the HTTP streaming path).
        Dropped automatically when the request completes; listener
        exceptions are logged, never raised into the step loop."""
        self._token_listeners[req_id] = fn

    def remove_token_listener(self, req_id: str) -> None:
        self._token_listeners.pop(req_id, None)

    def _emit_token(self, seq: _Seq, token: int) -> None:
        # Tag EVERY emitted token with the live weight version
        # (run-length on the sequence — the trace/debug surfaces
        # decode it), listener or not.
        if not seq.versions or \
                seq.versions[-1][0] != self.weights_version:
            seq.versions.append([self.weights_version, 0])
        seq.versions[-1][1] += 1
        # Exactly-once gate: this token's index vs the request's
        # high-water mark. A replayed prefix (preempt-resubmit or
        # crash re-adoption regenerates tokens already emitted —
        # greedy-identical values) advances the slot state but is NOT
        # re-delivered.
        idx = len(seq.generated) - 1
        hwm = self._emit_hwm.get(seq.req.id, 0)
        fresh = idx >= hwm
        if fresh:
            self._emit_hwm[seq.req.id] = idx + 1
        fn = self._token_listeners.get(seq.req.id)
        if fn is not None and fresh:
            try:
                fn(int(token), seq.done)
            except Exception:
                logger.exception("token listener for %r failed; "
                                 "dropping it", seq.req.id)
                self._token_listeners.pop(seq.req.id, None)
        if seq.done:
            self._token_listeners.pop(seq.req.id, None)

    @property
    def in_flight(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def idle(self) -> bool:
        return not self.queue and self.in_flight == 0

    def group_of_slot(self, slot: int) -> int:
        return slot // self.batch_local

    def slots_active_by_group(self) -> list[int]:
        B = self.batch_local
        return [sum(1 for s in self.slots[g * B:(g + 1) * B]
                    if s is not None)
                for g in range(self.dp_groups)]

    def _free_slot(self, group: int | None = None) -> int | None:
        B = self.batch_local
        if group is None:
            for i, s in enumerate(self.slots):
                if s is None:
                    return i
            return None
        for i in range(group * B, (group + 1) * B):
            if self.slots[i] is None:
                return i
        return None

    def _pick_group(self, first_tokens: int) -> tuple[int, int] | None:
        """Admission load balancing: the fewest-active-slots group
        (ties to the lowest index) that has BOTH a free slot and pages
        for the first chunk. None = every group is full/backpressured
        (the request stays queued)."""
        active = self.slots_active_by_group()
        order = sorted(range(self.dp_groups),
                       key=lambda g: (active[g], g))
        for g in order:
            slot = self._free_slot(g)
            if slot is None:
                continue
            if not self.cache.can_admit(first_tokens, group=g):
                continue
            return g, slot
        return None

    def _admit(self) -> _Seq | None:
        """Move the head-of-queue request into a free slot. With
        prefix sharing the placement prefers the group holding the
        LONGEST resident page-aligned prefix of the prompt (the new
        sequence attaches those pages read-only and prefills only the
        unmatched tail — a full cover prefills nothing); with no hit
        anywhere it falls back to fewest-active-slots-first, exactly
        the pre-sharing balancing. A session request whose retained
        turn is resident resumes in ITS group (pages cannot cross a
        pool shard) or waits for a slot there. None = backpressure —
        the request stays queued."""
        if self.draining or not self.queue:
            return None
        req = self.queue[0]
        plen = int(req.prompt.shape[0])
        first = min(plen, self.cfg.prefill_chunk)
        if not self._sharing:
            picked = self._pick_group(first)
            if picked is None:
                return None
            group, slot = picked
            self.queue.popleft()
            self.cache.join(req.id, group=group)
            self.cache.ensure(req.id, first)
            seq = _Seq(req=req, slot=slot)
            self._mark_admitted(seq, "admitted", group=group,
                                prefix_hit_tokens=0)
            self.slots[slot] = seq
            return seq
        if req.session is not None and req.session in self.sessions:
            res = self._try_resume(req)
            if res is not None:
                return None if res == "wait" else res
            # retained turn diverged from this prompt — it was
            # dropped; fall through to the normal path (the prefix
            # index may still cover part of the prompt).
        ps = self.cfg.page_size
        active = self.slots_active_by_group()
        order = sorted(range(self.dp_groups),
                       key=lambda g: (active[g], g))
        best = None      # (m, pages, group, slot), longest match wins
        starved = None   # best candidate short on pages (sessions
        for g in order:  # may be evictable — deferred to the pick)
            slot = self._free_slot(g)
            if slot is None:
                continue
            pages, m = self.cache.match_prefix(g, req.prompt)
            if m * ps >= plen:
                need = 1  # COW headroom for the boundary replay
            elif m:
                tgt = min(plen, m * ps + self.cfg.prefill_chunk)
                need = -(-tgt // ps) - m
            else:
                need = -(-first // ps)
            if need > self.cache.free_pages_in(g):
                if starved is None or m > starved[0]:
                    starved = (m, pages, g, slot, need)
                continue
            if best is None or m > best[0]:
                best = (m, pages, g, slot)
            if best[0] == 0:
                break  # no hit and the balanced pick already found
        if best is None and starved is not None:
            # Every slot-holding group is short on pages; evict idle
            # sessions (LRU) in the best starved group's shard before
            # giving up — retained pages must never wedge admission.
            # Re-match afterwards: the eviction may have freed the
            # very pages the match pointed at.
            m, pages, g, slot, need = starved
            if self._evict_sessions(g, need):
                pages, m = self.cache.match_prefix(g, req.prompt)
                if m * ps >= plen or m or \
                        self.cache.can_admit(first, group=g):
                    best = (m, pages, g, slot)
        if best is None:
            return None
        m, pages, group, slot = best
        self.queue.popleft()
        self.cache.join(req.id, group=group)
        seq = _Seq(req=req, slot=slot)
        if m * ps >= plen:
            # Full page-aligned cover: ZERO prefill — attach all the
            # pages at length plen - 1 and let the first decode
            # replay the last prompt token (COW forks the boundary
            # page; the sampled token is the prefill's first token).
            self.cache.attach(req.id, pages, plen - 1)
            seq.prefilled = plen
            hit = plen
        elif m:
            self.cache.attach(req.id, pages, m * ps)
            self.cache.ensure(
                req.id, min(plen, m * ps + self.cfg.prefill_chunk))
            seq.prefilled = m * ps
            hit = m * ps
        else:
            self.cache.ensure(req.id, first)
            hit = 0
        if hit:
            self.prefix_stats["hit_tokens"] += hit
            self.prefix_stats["saved_tokens"] += hit
            self._step_prefix[0] += hit
            self._step_prefix[1] += hit
        self._mark_admitted(seq, "admitted", group=group,
                            prefix_hit_tokens=hit)
        self.slots[slot] = seq
        return seq

    # -- prefix sharing / sessions -----------------------------------------

    def _try_resume(self, req: Request):
        """Re-attach a retained session turn. Returns the installed
        ``_Seq``, ``"wait"`` (the session's group has no free slot —
        stay queued; its pages live in ONE pool shard), or None (the
        prompt diverged from the retained history, which was just
        dropped)."""
        key = req.session
        sess = self.sessions[key]
        hist = sess["history"]
        hl = int(hist.shape[0])
        prompt = np.array(req.prompt, np.int32)
        plen = int(prompt.shape[0])
        if hl > plen or not np.array_equal(prompt[:hl], hist):
            self._drop_session(key)
            return None
        slot = self._free_slot(sess["group"])
        if slot is None:
            return "wait"
        self.queue.popleft()
        del self.sessions[key]
        self.cache.rename(sess["cache_id"], req.id)
        # Retained length is hl - 1 (the last generated token was
        # sampled but its KV never written — decode's standard
        # frontier). Exact match: prefilled = plen, zero prefill
        # launches, decode replays prompt[-1]. Extended: the tail
        # from position hl - 1 prefills as a continuation chunk.
        exact = plen == hl
        seq = _Seq(req=req, slot=slot,
                   prefilled=plen if exact else hl - 1)
        self.slots[slot] = seq
        saved = plen if exact else hl - 1
        self._mark_admitted(seq, "resumed", group=sess["group"],
                            session=key, hit_tokens=saved)
        self.prefix_stats["session_resumes"] += 1
        self.prefix_stats["hit_tokens"] += saved
        self.prefix_stats["saved_tokens"] += saved
        self._step_prefix[0] += saved
        self._step_prefix[1] += saved
        return seq

    def _drop_session(self, key: str) -> None:
        sess = self.sessions.pop(key)
        self.cache.free(sess["cache_id"])

    def _evict_sessions(self, group: int, need: int) -> bool:
        """Free retained sessions in ``group`` (LRU first) until
        ``need`` pages are free. Returns True when satisfied.
        Sessions sharing pages with live sequences release only
        their unshared pages (refcounts protect the rest) — the loop
        keeps evicting until the target is met or no session in the
        group remains."""
        while self.cache.free_pages_in(group) < need:
            cands = sorted(
                (s["t"], k) for k, s in self.sessions.items()
                if s["group"] == group)
            if not cands:
                return False
            self._drop_session(cands[0][1])
        return True

    def _cow_guard(self, seq_id) -> list | None:
        """Privatize any shared page the next write into ``seq_id``
        would touch. Returns the (src, dst) page pairs for
        ``_apply_cow`` ([] = nothing shared), or None when the fork
        stalled on free pages even after evicting an idle session
        (the sequence skips this launch)."""
        pairs = self.cache.privatize(seq_id)
        if pairs is None:
            self._evict_sessions(self.cache.group_of(seq_id), 1)
            pairs = self.cache.privatize(seq_id)
        return pairs

    def _apply_cow(self, pairs: list) -> None:
        """ONE fixed-shape launch copying every forked page:
        ``pairs`` is [(group, src_page, dst_page)]. Unused lanes stay
        (0 -> 0) scratch identities, so fork count never changes a
        traced shape."""
        import jax.numpy as jnp

        G, W = self.dp_groups, self._cow_width
        src = np.zeros((G, W), np.int32)
        dst = np.zeros((G, W), np.int32)
        fill = [0] * G
        for g, a, b in pairs:
            src[g, fill[g]] = a
            dst[g, fill[g]] = b
            fill[g] += 1
        k, v = self._cow_fn(self.cache.k_pages, self.cache.v_pages,
                            jnp.asarray(src), jnp.asarray(dst))
        self.cache.update_pools(k, v)
        self.prefix_stats["cow_pages"] += len(pairs)

    def _register(self, seq: _Seq) -> None:
        """Index the sequence's newly committed page-aligned
        prefixes so later prompts can attach them. Skipped when the
        pages are about to be freed anyway (finished, no session)."""
        if not self._sharing:
            return
        if seq.done and seq.req.session is None:
            return
        if not self.cache.needs_register(seq.req.id):
            return
        self.cache.register_prefix(
            seq.req.id,
            np.concatenate([np.array(seq.req.prompt, np.int32),
                            np.array(seq.generated, np.int32)]))

    # -- step --------------------------------------------------------------

    def _prefill_candidates(self) -> list[_Seq]:
        return [s for s in self.slots
                if s is not None and not s.prefill_done]

    def _decode_candidates(self) -> list[_Seq]:
        return [s for s in self.slots
                if s is not None and s.prefill_done and not s.done]

    def step(self) -> dict:
        """One scheduling decision + one compiled program launch.
        Returns a record of what ran (``kind``: prefill/decode/idle).
        """
        t0 = time.monotonic()
        pending = self._prefill_candidates()
        can_admit = (not self.draining and self.queue
                     and self._free_slot() is not None)
        want_prefill = bool(pending or can_admit)
        decodable = self._decode_candidates()
        if self.cfg.policy == "prefill":
            kind = "prefill" if want_prefill else (
                "decode" if decodable else "idle")
        else:
            kind = "decode" if decodable else (
                "prefill" if want_prefill else "idle")
        tokens_out = 0
        self._step_spec = None
        self._step_resident = None
        self._last_prefill_lanes = None
        self._step_prefix = [0, 0]
        syncs0 = self.host_syncs
        if kind == "prefill":
            if self.cfg.prefill_mode == "batched":
                # Admit everything slots+pages allow BEFORE the
                # launch — one admission per step would starve the
                # lane table the batched program pays for.
                while not self.draining and self.queue \
                        and self._admit() is not None:
                    pass
                tokens_out = self._run_prefill_batch(
                    self._prefill_candidates())
                if tokens_out == 0:
                    # Backpressure (every pending chunk stalled on
                    # pages — the r02 livelock fallback) OR every
                    # admission was a zero-prefill attach: decode.
                    # Recompute decodable — a full prefix hit or an
                    # exact session resume admits straight into the
                    # decodable set.
                    decodable = self._decode_candidates()
                    kind = "decode" if decodable else "idle"
            else:
                seq = pending[0] if pending else self._admit()
                if seq is not None and seq.prefill_done:
                    # Zero-prefill admission (full prefix hit /
                    # exact session resume): nothing to prefill —
                    # the fresh slot decodes this very step.
                    decodable = self._decode_candidates()
                    kind = "decode" if decodable else "idle"
                # Backpressure fallback: when admission OR a
                # mid-prompt page allocation fails (pool exhausted),
                # decode instead — decoding sequences finish and
                # free the pages the prefill is waiting for. Without
                # the second fallback a prefill-priority engine
                # livelocks (regression-pinned in
                # tests/test_serving.py).
                elif seq is None or not self._run_prefill_chunk(seq):
                    kind = "decode" if decodable else "idle"
        if kind == "decode":
            tokens_out = self._run_decode(decodable)
        dur = time.monotonic() - t0
        # "op", not "kind": telemetry's record envelope owns "kind"
        # (the event name), and a colliding field would silently
        # relabel the whole record past the metrics observer.
        # "tokens" counts NEW tokens for decode steps and PROMPT
        # tokens processed for (batched) prefill steps — the metrics
        # observer splits them into the decode/prefill tok/s gauges
        # by "op".
        rec = {"op": kind, "dur_s": dur, "tokens": tokens_out,
               "in_flight": self.in_flight,
               "queue_depth": len(self.queue),
               **self.cache.occupancy()}
        if self._step_spec is not None:
            launches, emitted = self._step_spec
            rec["spec_k"] = self.cfg.spec_k
            rec["spec_accepted_mean"] = round(emitted / launches, 4)
        if self._step_resident is not None:
            mean_steps, _slots = self._step_resident
            rec["resident_k"] = self.cfg.resident_k
            rec["resident_steps_per_launch"] = mean_steps
        if self._sharing:
            # Additive sharing fields (schema pinned by test): the
            # metrics observer accumulates the per-step deltas into
            # the dtt_serving_prefix_* counters and folds the
            # per-group shared-page list into a labeled family.
            rec["prefix_hit_tokens"] = self._step_prefix[0]
            rec["prefill_tokens_saved"] = self._step_prefix[1]
            rec["sessions_resident"] = len(self.sessions)
            rec["kv_pages_shared"] = [
                self.cache.shared_pages_in(g)
                for g in range(self.dp_groups)]
        syncs = self.host_syncs - syncs0
        rec["host_syncs"] = syncs
        if tokens_out:
            rec["host_syncs_per_token"] = round(
                syncs / tokens_out, 6)
        rec["weight_bytes"] = self.weight_bytes
        if self.dp_groups > 1:
            rec["group_slots_active"] = self.slots_active_by_group()
            if self._last_prefill_lanes is not None:
                rec["group_prefill_slots_active"] = \
                    self._last_prefill_lanes
        event("serving", **rec)
        self._step_counter += 1
        if kind != "idle":
            self.launch_count += 1
            if self.faults is not None:
                self._run_faults()
        return rec

    def _run_faults(self) -> None:
        """Serving fault hook, fired AFTER the step record is emitted
        (the fault ledger write happens inside the injector BEFORE
        any action — crash/restart cannot re-fire a fault). The
        injector sleeps ``slow_decode`` itself; the engine performs
        the actions that need its state: ``client_disconnect`` drops
        one live stream listener (the high-water mark keeps
        advancing, so the severed stream never resumes mid-request
        with duplicates), and ``engine_crash`` raises out of
        ``step()`` exactly like a real engine-thread fault."""
        from distributed_training_tpu.resilience.faults import (
            InjectedCrash)

        fired = self.faults.on_launch(self.launch_count)
        if "client_disconnect" in fired and self._token_listeners:
            rid = next(iter(self._token_listeners))
            self._token_listeners.pop(rid, None)
            logger.warning("injected client_disconnect: dropped "
                           "stream listener %r", rid)
        if "engine_crash" in fired:
            raise InjectedCrash(
                f"injected engine_crash at launch "
                f"{self.launch_count}")

    def _fetch_host(self, *arrays) -> tuple:
        """THE designated device->host sync point of the serving hot
        path: every blocking fetch in the step loop funnels through
        here so the sync cadence is countable (``host_syncs``, the
        ``dtt_serving_host_syncs_per_token`` gauge) and so pitfalls
        rule DTT010 can flag any round-trip that creeps in anywhere
        else. One call = one sync, however many arrays ride it."""
        self.host_syncs += 1
        return tuple(np.asarray(a) for a in arrays)

    def _group_row(self, seq_id) -> tuple[np.ndarray, np.ndarray, int]:
        """(G, P) page rows + (G,) live mask for a single sequence:
        the owner group's real row, all-scratch rows elsewhere."""
        G = self.dp_groups
        g = self.cache.group_of(seq_id)
        rows = np.zeros((G, self.cache.cfg.pages_per_seq), np.int32)
        rows[g] = self.cache.page_row(seq_id)
        live = np.zeros((G,), bool)
        live[g] = True
        return rows, live, g

    def _run_prefill_chunk(self, seq: _Seq) -> bool:
        """One chunk of ``seq``'s prompt. False = no progress (the
        owning group's pool could not cover the chunk's pages —
        backpressure; the caller must let decode run so pages free
        up)."""
        import jax.numpy as jnp

        c = self.cfg
        start = seq.prefilled
        n_valid = min(c.prefill_chunk, seq.prompt_len - start)
        if not self.cache.ensure(seq.req.id, start + n_valid):
            return False
        if self._sharing:
            pairs = self._cow_guard(seq.req.id)
            if pairs is None:
                return False  # fork stalled on pages — backpressure
            if pairs:
                g = self.cache.group_of(seq.req.id)
                self._apply_cow([(g, a, b) for a, b in pairs])
        chunk = np.zeros((1, c.prefill_chunk), np.int32)
        chunk[0, :n_valid] = seq.req.prompt[start:start + n_valid]
        rows, live, g = self._group_row(seq.req.id)
        fn = (self._prefill_first_fn if start == 0
              else self._prefill_cont_fn)
        # start/n_valid ride as weak-typed scalars: same jit cache
        # entry for every value, no explicit device_put dispatches.
        logits, k, v = fn(self.params, self.cache.k_pages,
                          self.cache.v_pages, jnp.asarray(rows),
                          jnp.asarray(live), jnp.asarray(chunk),
                          start, n_valid)
        self.cache.update_pools(k, v)
        self.cache.advance(seq.req.id, n_valid)
        seq.prefilled = start + n_valid
        self.prefill_tokens_computed += n_valid
        self.prefill_launches += 1
        if seq.prefill_done:
            # Slice ON DEVICE before the pull: one (V,) transfer per
            # completed prompt instead of the whole (G, V) block —
            # the r02 dispatch-diet leftover (completion cost must
            # not scale with vocab x dp). The batched prefill path
            # goes further and never moves logits at all (in-program
            # sampling).
            (lg,) = self._fetch_host(logits[g])
            tok = self._sample_host(lg)
            now = time.monotonic()
            seq.span("prefill", now, tokens=n_valid)
            seq.first_token_t = now
            seq.token_times.append(now)
            seq.generated.append(tok)
            if self.cfg.eos_id >= 0 and tok == self.cfg.eos_id:
                seq.eos = True
            self._emit_token(seq, tok)
            self._register(seq)
            self._maybe_finish(seq)
            return True
        # Mid-prompt chunk: no fetch happens, so the span timestamp
        # is the post-dispatch host clock (launch enqueue time under
        # async dispatch — the token counts are the load-bearing
        # fields; the sync-accurate timestamps are the fetched ones).
        seq.span("prefill", time.monotonic(), tokens=n_valid)
        self._register(seq)
        return True

    def _sample_host(self, logits) -> int:
        """Sample the prefill's first token on host — one token per
        request lifetime; the decode program samples the rest
        in-compiled. ``logits`` is a HOST array (the caller already
        pulled it through ``_fetch_host``)."""
        import jax
        import jax.numpy as jnp

        if self.cfg.temperature <= 0:
            # Host argmax: one V-sized transfer instead of a device
            # argmax dispatch + sync — on the dispatch-bound CPU
            # mesh the extra launch was ~30% of a prefill step.
            return int(logits.argmax())
        rng = jax.random.fold_in(self._base_rng,
                                 1_000_000 + self._step_counter)
        lg = logits / self.cfg.temperature
        if self.cfg.top_k:
            kth = jax.lax.top_k(lg, self.cfg.top_k)[0][-1]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        return int(jax.random.categorical(rng, lg))

    def _rng_grouped(self, salt: int):
        """(G, 2) uint32 per-group key data for the compiled
        programs' sampling tail. Greedy returns the cached zero key
        (the operand is dead — the r02 dispatch diet)."""
        import jax
        import jax.numpy as jnp

        if self.cfg.temperature <= 0:
            return self._zero_rng
        base = jax.random.fold_in(self._base_rng, salt)
        return jnp.asarray(np.stack([
            np.asarray(jax.random.key_data(  # noqa: DTT010 — sampled
                jax.random.fold_in(base, g)))  # path only; greedy
            for g in range(self.dp_groups)]))  # rides _zero_rng

    def _run_prefill_batch(self, pending: list[_Seq]) -> int:
        """One launch of the batched prefill program: pack up to
        ``prefill_local`` pending sequences PER GROUP (each lane is
        one sequence's current chunk, pages ensured first), write all
        their KV through one batched scatter, and read the in-program
        sample for every lane whose chunk completed its prompt.
        Returns the prompt tokens processed (0 = every pending chunk
        stalled on pages — backpressure; the caller lets decode run
        so pages free up)."""
        import jax.numpy as jnp

        c = self.cfg
        G, Sp, C = self.dp_groups, self.prefill_local, c.prefill_chunk
        chosen: list[list[_Seq]] = [[] for _ in range(G)]
        cow: list = []
        for s in pending:
            g = self.cache.group_of(s.req.id)
            if len(chosen[g]) >= Sp:
                continue
            n = min(C, s.prompt_len - s.prefilled)
            if not self.cache.ensure(s.req.id, s.prefilled + n):
                continue  # this lane stalls; others still launch
            if self._sharing:
                pairs = self._cow_guard(s.req.id)
                if pairs is None:
                    continue  # lane stalls on fork pages
                cow += [(g, a, b) for a, b in pairs]
            chosen[g].append(s)
        if not any(chosen):
            return 0
        if cow:
            self._apply_cow(cow)
        tokens = np.zeros((G, Sp, C), np.int32)
        start_pos = np.zeros((G, Sp), np.int32)
        n_valid = np.zeros((G, Sp), np.int32)
        active = np.zeros((G, Sp), bool)
        for g, seqs in enumerate(chosen):
            for i, s in enumerate(seqs):
                start = s.prefilled
                n = min(C, s.prompt_len - start)
                tokens[g, i, :n] = s.req.prompt[start:start + n]
                start_pos[g, i] = start
                n_valid[g, i] = n
                active[g, i] = True
        rows = self.cache.page_rows_grouped(
            [[s.req.id for s in seqs] for seqs in chosen], width=Sp)
        nxt, k, v = self._prefill_batch_fn(
            self.params, self.cache.k_pages, self.cache.v_pages,
            jnp.asarray(rows), jnp.asarray(tokens),
            jnp.asarray(start_pos), jnp.asarray(n_valid),
            jnp.asarray(active),
            self._rng_grouped(1_000_000 + self._step_counter))
        self.cache.update_pools(k, v)
        self._last_prefill_lanes = [len(seqs) for seqs in chosen]
        self.prefill_launches += 1
        total = 0
        fetched = None
        now = None
        t_launch = time.monotonic()  # dispatch-time stamp for lanes
        for g, seqs in enumerate(chosen):  # that trigger no fetch
            for i, s in enumerate(seqs):
                n = int(n_valid[g, i])
                self.cache.advance(s.req.id, n)
                s.prefilled += n
                total += n
                if not s.prefill_done:
                    s.span("prefill", t_launch, tokens=n)
                if s.prefill_done:
                    if fetched is None:
                        # ONE (G, Sp) int32 pull for the whole
                        # launch, and only when some prompt
                        # completed — never a logits block. The
                        # timestamp is taken AFTER this blocking
                        # fetch: under async dispatch an earlier
                        # clock read would exclude the launch's own
                        # compute from TTFT.
                        (fetched,) = self._fetch_host(nxt)
                        now = time.monotonic()
                    tok = int(fetched[g, i])
                    s.span("prefill", now, tokens=n)
                    s.first_token_t = now
                    s.token_times.append(now)
                    s.generated.append(tok)
                    if self.cfg.eos_id >= 0 and \
                            tok == self.cfg.eos_id:
                        s.eos = True
                    self._emit_token(s, tok)
                self._register(s)
                if s.prefill_done:
                    self._maybe_finish(s)
        self.prefill_tokens_computed += total
        return total

    def _draft(self, seq: _Seq, m: int) -> np.ndarray:
        """``m`` drafted tokens for ``seq`` by prompt lookup over its
        own history (prompt + generated) — ``draft_tokens``
        semantics served from the sequence's INCREMENTAL
        ``NgramIndex`` (built lazily on first draft, extended by the
        tokens emitted since the last one — O(new tokens), not a
        full-history rescan per launch)."""
        if m <= 0:
            return np.zeros((0,), np.int32)
        idx = seq.ngram
        if idx is None:
            idx = seq.ngram = NgramIndex(self.cfg.spec_ngram)
            idx.extend(seq.req.prompt.tolist())
            idx.extend(seq.generated)
        else:
            idx.extend(
                seq.generated[len(idx) - seq.prompt_len:])
        return idx.draft(m)

    def _run_decode_spec(self, decodable: list[_Seq]) -> int:
        """One launch of the speculative multi-token decode program:
        every decodable slot carries [last sampled token, spec_k - 1
        drafted tokens], the program argmax-verifies all positions in
        one forward, and the host emits the accepted prefix — each
        emitted token IS the argmax given the true prefix, so greedy
        output is token-identical to one-token decode. The cache
        advances only by the accepted length; rejected positions'
        stale KV sits beyond ``length`` (masked out of attention) and
        is overwritten by the next launch's writes."""
        import jax.numpy as jnp

        G, B = self.dp_groups, self.batch_local
        K = self.cfg.spec_k
        tokens = np.zeros((G, B, K), np.int32)
        start_pos = np.zeros((G, B), np.int32)
        n_valid = np.zeros((G, B), np.int32)
        active = np.zeros((G, B), bool)
        seq_ids: list[list] = [[None] * B for _ in range(G)]
        stepped: list[tuple[_Seq, int, np.ndarray]] = []
        cow: list = []
        for s in decodable:
            length = self.cache.length(s.req.id)
            remaining = s.req.max_new_tokens - len(s.generated)
            # Clamp the chain to what the sequence can still hold —
            # positions past max_seq_len or past the request's budget
            # ride as masked padding (n_valid), never as writes.
            n = min(K, remaining, self.cfg.max_seq_len - length)
            if not self.cache.ensure(s.req.id, length + n):
                # Pages for the full chain are short: fall back to a
                # one-token launch in the SAME program before
                # stalling outright.
                if n == 1 or not self.cache.ensure(s.req.id,
                                                   length + 1):
                    continue
                n = 1
            g, i = divmod(s.slot, B)
            if self._sharing:
                pairs = self._cow_guard(s.req.id)
                if pairs is None:
                    continue  # fork stalled on pages; retry next step
                cow += [(g, a, b) for a, b in pairs]
            draft = self._draft(s, n - 1)
            tokens[g, i, 0] = s.last_token
            if n > 1:
                tokens[g, i, 1:n] = draft
            start_pos[g, i] = length
            n_valid[g, i] = n
            active[g, i] = True
            seq_ids[g][i] = s.req.id
            stepped.append((s, n, draft))
        if not stepped:
            return 0
        if cow:
            self._apply_cow(cow)
        rows = self.cache.page_rows_grouped(seq_ids)
        out, k, v = self._decode_fn(
            self.params, self.cache.k_pages, self.cache.v_pages,
            jnp.asarray(rows), jnp.asarray(tokens),
            jnp.asarray(start_pos), jnp.asarray(n_valid),
            jnp.asarray(active), self._zero_rng)
        self.cache.update_pools(k, v)
        (out,) = self._fetch_host(out)
        now = time.monotonic()
        total = 0
        for s, n, draft in stepped:
            g, i = divmod(s.slot, B)
            # out[g, i, j] is the verified argmax AFTER position j.
            # Accept draft j while it equals the chain's previous
            # token; every accepted position's argmax is then
            # conditioned on true tokens only.
            emit = [int(out[g, i, 0])]
            j = 1
            while j < n and int(draft[j - 1]) == emit[-1]:
                emit.append(int(out[g, i, j]))
                j += 1
            if self.cfg.eos_id >= 0 and self.cfg.eos_id in emit:
                # Stop at the stop token: later accepted positions
                # are conditioned on a sequence that already ended.
                emit = emit[:emit.index(self.cfg.eos_id) + 1]
            self.cache.advance(s.req.id, len(emit))
            self.spec_stats["launches"] += 1
            self.spec_stats["emitted"] += len(emit)
            s.span("decode", now, emitted=len(emit), budget=n)
            for tok in emit:
                s.generated.append(tok)
                if self.cfg.eos_id >= 0 and \
                        tok == self.cfg.eos_id:
                    s.eos = True
                if s.first_token_t is None:
                    s.first_token_t = now
                s.token_times.append(now)
                self._emit_token(s, tok)
            total += len(emit)
            self._register(s)
            self._maybe_finish(s)
        self._step_spec = (len(stepped), total)
        return total

    def _run_decode_resident(self, decodable: list[_Seq]) -> int:
        """One BURST of the device-resident decode loop: every
        decodable slot ships its full history row + a token budget,
        the program runs up to ``resident_k`` chunk iterations
        (drafting, verifying, stop-detecting and advancing its own
        page cursor per slot ON DEVICE), and the host syncs ONCE for
        the whole burst — ``(out, n_emitted, steps)``, one
        ``_fetch_host`` call. Greedy token identity is preserved by
        construction: each iteration emits exactly the argmax chain
        the host spec path would (the same ``_chunk_hidden`` math),
        so K only moves the sync cadence, never tokens. A burst is
        atomic host-side — the cache advances only after the fetch —
        so a preemption between bursts resubmits cleanly."""
        import jax.numpy as jnp

        G, B = self.dp_groups, self.batch_local
        T = self.cfg.resident_k * self.cfg.spec_k
        L = self.cfg.max_seq_len
        history = np.zeros((G, B, L), np.int32)
        kv_len = np.zeros((G, B), np.int32)
        budget = np.zeros((G, B), np.int32)
        active = np.zeros((G, B), bool)
        seq_ids: list[list] = [[None] * B for _ in range(G)]
        stepped: list[_Seq] = []
        cow: list = []
        for s in decodable:
            length = self.cache.length(s.req.id)
            remaining = s.req.max_new_tokens - len(s.generated)
            # The burst budget is clamped to the pages the slot
            # could actually claim RIGHT NOW (its allocated pages +
            # its group's free list): a tight pool degrades the
            # burst toward one token — the all-slots-stall
            # fallback — instead of stalling the slot outright.
            cap = self.cache.token_capacity(s.req.id)
            want = min(remaining, T, cap - length)
            if want < 1:
                continue  # zero headroom: wait for frees
            if not self.cache.ensure(s.req.id, length + want):
                continue
            g, i = divmod(s.slot, B)
            if self._sharing:
                pairs = self._cow_guard(s.req.id)
                if pairs is None:
                    continue  # fork stalled on pages; retry next step
                cow += [(g, a, b) for a, b in pairs]
            hist = np.concatenate([
                np.array(s.req.prompt, np.int32),
                np.array(s.generated, np.int32)])
            history[g, i, :hist.shape[0]] = hist
            kv_len[g, i] = length
            budget[g, i] = want
            active[g, i] = True
            seq_ids[g][i] = s.req.id
            stepped.append(s)
        if not stepped:
            return 0
        if cow:
            self._apply_cow(cow)
        rows = self.cache.page_rows_grouped(seq_ids)
        out, n_emitted, steps, k, v = self._decode_fn(
            self.params, self.cache.k_pages, self.cache.v_pages,
            jnp.asarray(rows), jnp.asarray(history),
            jnp.asarray(kv_len), jnp.asarray(budget),
            jnp.asarray(active))
        self.cache.update_pools(k, v)
        out, n_emitted, steps = self._fetch_host(
            out, n_emitted, steps)
        now = time.monotonic()
        total = 0
        for s in stepped:
            g, i = divmod(s.slot, B)
            e = int(n_emitted[g, i])
            self.cache.advance(s.req.id, e)
            s.span("decode", now, emitted=e,
                   budget=int(budget[g, i]))
            for t in range(e):
                tok = int(out[g, i, t])
                s.generated.append(tok)
                if self.cfg.eos_id >= 0 and \
                        tok == self.cfg.eos_id:
                    s.eos = True
                if s.first_token_t is None:
                    s.first_token_t = now
                s.token_times.append(now)
                self._emit_token(s, tok)
            total += e
            self._register(s)
            self._maybe_finish(s)
        g_steps = [int(steps[g]) for g in range(G)
                   if active[g].any()]
        mean_steps = sum(g_steps) / max(1, len(g_steps))
        self.resident_stats["launches"] += 1
        self.resident_stats["steps"] += max(g_steps, default=0)
        self.resident_stats["emitted"] += total
        self._step_resident = (round(mean_steps, 4), len(stepped))
        return total

    def _run_decode(self, decodable: list[_Seq]) -> int:
        import jax.numpy as jnp

        if self.cfg.resident_k > 1:
            return self._run_decode_resident(decodable)
        if self.cfg.spec_k > 1:
            return self._run_decode_spec(decodable)
        G, B = self.dp_groups, self.batch_local
        tokens = np.zeros((G, B), np.int32)
        positions = np.zeros((G, B), np.int32)
        active = np.zeros((G, B), bool)
        seq_ids: list[list] = [[None] * B for _ in range(G)]
        stepped: list[_Seq] = []
        cow: list = []
        for s in decodable:
            # The new token's KV lands at position length(seq); make
            # sure a page covers it. Failure = that group's pool
            # shard is exhausted: the slot stalls this step and
            # resumes when pages free.
            if not self.cache.ensure(s.req.id,
                                     self.cache.length(s.req.id) + 1):
                continue
            g, i = divmod(s.slot, B)
            if self._sharing:
                pairs = self._cow_guard(s.req.id)
                if pairs is None:
                    continue  # fork stalled on pages; retry next step
                cow += [(g, a, b) for a, b in pairs]
            tokens[g, i] = s.last_token
            positions[g, i] = self.cache.length(s.req.id)
            active[g, i] = True
            seq_ids[g][i] = s.req.id
            stepped.append(s)
        if not stepped:
            return 0
        if cow:
            self._apply_cow(cow)
        rows = self.cache.page_rows_grouped(seq_ids)
        rng = self._rng_grouped(self._step_counter)
        nxt, k, v = self._decode_fn(
            self.params, self.cache.k_pages, self.cache.v_pages,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(rows), jnp.asarray(active), rng)
        self.cache.update_pools(k, v)
        (nxt,) = self._fetch_host(nxt)
        now = time.monotonic()
        for s in stepped:
            g, i = divmod(s.slot, B)
            self.cache.advance(s.req.id, 1)
            s.span("decode", now, emitted=1)
            tok = int(nxt[g, i])
            s.generated.append(tok)
            if self.cfg.eos_id >= 0 and tok == self.cfg.eos_id:
                s.eos = True
            if s.first_token_t is None:
                s.first_token_t = now
            s.token_times.append(now)
            self._emit_token(s, tok)
            self._register(s)
            self._maybe_finish(s)
        return len(stepped)

    def _maybe_finish(self, seq: _Seq) -> None:
        if not seq.done:
            return
        if self._sharing and seq.req.session is not None:
            # Retain the turn's pages under the session key instead
            # of freeing them: a follow-up request with this key
            # re-attaches with zero prefill for the whole retained
            # history. A stale earlier turn of the same key is
            # superseded (its pages go back through the refcounted
            # free).
            key = seq.req.session
            if key in self.sessions:
                self._drop_session(key)
            cid = f"~session:{key}"
            self.cache.rename(seq.req.id, cid)
            retain_t = time.monotonic()
            self.sessions[key] = {
                "cache_id": cid,
                "history": np.concatenate([
                    np.array(seq.req.prompt, np.int32),
                    np.array(seq.generated, np.int32)]),
                "group": self.cache.group_of(cid),
                "t": retain_t}
            seq.span("session_retain", retain_t, session=key)
        else:
            self.cache.free(seq.req.id)
        self.slots[seq.slot] = None
        now = time.monotonic()
        arrival = seq.req.arrival if seq.req.arrival is not None \
            else now
        gaps = [b - a for a, b in zip(seq.token_times,
                                      seq.token_times[1:])]
        rec = {
            "id": seq.req.id,
            "tenant": seq.req.tenant,
            "prompt_tokens": seq.prompt_len,
            "new_tokens": len(seq.generated),
            "tokens": list(seq.generated),
            "ttft_s": (seq.first_token_t - arrival
                       if seq.first_token_t is not None else None),
            "queue_wait_s": seq.queue_wait_s,
            "latency_s": now - arrival,
            "token_gaps_s": gaps,
            "group": self.group_of_slot(seq.slot),
            "weights_versions": [list(p) for p in seq.versions],
        }
        self.completed.append(rec)
        self.finished_total += 1
        self._emit_hwm.pop(seq.req.id, None)
        event("serving_request",
              **{k: rec[k] for k in ("id", "tenant",
                                     "prompt_tokens", "new_tokens",
                                     "ttft_s", "queue_wait_s",
                                     "latency_s", "group")})
        self._emit_trace(seq, "finished", now)

    # -- convenience -------------------------------------------------------

    def run_until_drained(self, max_steps: int = 100_000) -> int:
        """Step until queue + slots are empty. Returns steps taken."""
        n = 0
        while not self.idle and n < max_steps:
            self.step()
            n += 1
        if not self.idle:
            raise RuntimeError(
                f"engine not drained after {max_steps} steps "
                f"(queue={len(self.queue)}, in_flight="
                f"{self.in_flight})")
        return n

    def generate(self, prompt: np.ndarray, max_new_tokens: int
                 ) -> list[int]:
        """One prompt through the full continuous-batching path
        (the generate-CLI route). Returns the generated token ids."""
        rid = f"gen-{self._step_counter}-{len(self.completed)}"
        self.submit(Request(id=rid,
                            prompt=np.array(prompt, np.int32),
                            max_new_tokens=max_new_tokens))
        self.run_until_drained()
        rec = next(r for r in reversed(self.completed)
                   if r["id"] == rid)
        return rec["tokens"]

    def adopt(self, req: Request, first_token: int,
              k_dense: np.ndarray, v_dense: np.ndarray) -> None:
        """Adopt an EXTERNALLY-PREFILLED sequence (the disaggregation
        handoff, serving/disagg.py): its prompt KV arrives as dense
        (L, Hkv, prompt_len, hd) arrays and is written into this
        engine's pages — into the least-loaded dp group's shard, the
        same balancing as queue admission; decode continues here as
        if the prefill had run locally. ``first_token`` is the token
        the prefill slice sampled from its final logits."""
        self.adopt_batch([(req, first_token, k_dense, v_dense)])

    def adopt_batch(self, items) -> None:
        """Adopt MANY externally-prefilled sequences in one batched
        page import (serving/disagg.py ``import_kv_batch`` — a single
        scatter per pool instead of one device round-trip per
        request; the continuous-handoff rate path). ``items`` is a
        list of ``(req, tokens, k_dense, v_dense)`` where ``tokens``
        is either the single first sampled token (the disaggregation
        handoff) or the FULL generated history so far (the crash-
        recovery re-adoption, ``export_in_flight``) — the dense KV
        must cover ``prompt_len + len(tokens) - 1`` positions, the
        decode invariant (the newest token's KV is written by its own
        decode launch). Raises before touching the pool when any
        request cannot get a slot+pages — the caller holds the batch
        and retries once decode frees capacity."""
        from distributed_training_tpu.serving.disagg import (
            import_kv_batch)

        now = time.monotonic()
        staged = []
        try:
            for req, toks, k_dense, v_dense in items:
                tokens = ([int(toks)]
                          if isinstance(toks, (int, np.integer))
                          else [int(t) for t in toks])
                if not tokens:
                    raise ValueError(
                        f"adopt of {req.id!r} carries no tokens — a "
                        "never-decoded sequence resubmits as a fresh "
                        "request instead")
                if req.arrival is None:
                    req.arrival = now
                self._validate(req)
                need = req.prompt.shape[0] + len(tokens) - 1
                picked = self._pick_group(need)
                if picked is None:
                    raise RuntimeError(
                        f"no free slot/pages to adopt {req.id!r} "
                        "into")
                group, slot = picked
                self.cache.join(req.id, group=group)
                seq = _Seq(req=req, slot=slot,
                           prefilled=req.prompt.shape[0])
                self._mark_admitted(seq, "adopted", group=group)
                self.slots[slot] = seq
                staged.append((seq, tokens, k_dense, v_dense))
            import_kv_batch(self.cache,
                            [(s.req.id, k, v)
                             for s, _t, k, v in staged])
        except Exception:
            # A failed batch must not leak joined table entries or
            # slots (a retry of the same request id would hit
            # "already joined" forever). ensure() inside the batch
            # import is atomic per sequence, so freeing returns
            # exactly the pages taken.
            for s, _t, _k, _v in staged:
                self.cache.free(s.req.id)
                self.slots[s.slot] = None
            raise
        now = time.monotonic()
        for seq, tokens, _k, _v in staged:
            seq.first_token_t = now
            for tok in tokens:
                seq.token_times.append(now)
                seq.generated.append(tok)
                if self.cfg.eos_id >= 0 and tok == self.cfg.eos_id:
                    seq.eos = True
                self._emit_token(seq, tok)
                self._register(seq)
            self._maybe_finish(seq)

    def preempt(self) -> list[Request]:
        """Simulated engine preemption: drop all device-side progress,
        free every page, and hand back the unfinished work (queued +
        in-flight requests, fresh — generation restarts from the
        prompt, the standard continuous-batching recovery). The
        engine is reusable afterwards (a restarted incarnation calls
        ``submit`` with these). Token listeners for the lost work are
        dropped too — a resubmitted request restarts from the prompt,
        and a stale listener would stream its early tokens twice.
        RETAINED SESSIONS SURVIVE: their pages are refcount-held, so
        freeing the in-flight sequences (some sharing those pages)
        returns exactly the unshared pages — no leak, no double-free
        — and a post-preemption resume still re-attaches with zero
        prefill. Page content is untouched by the frees (a page is
        never reused while held), so the retained KV stays valid."""
        lost: list[Request] = []
        now = time.monotonic()
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            # Close the trace honestly BEFORE dropping the state:
            # the tokens this incarnation computed and is about to
            # throw away are recorded, so the offline retry-cost
            # number is derived from the stream, never inferred.
            self._emit_trace(s, "preempted", now,
                             tokens_discarded=len(s.generated))
            self.cache.free(s.req.id)
            self.slots[i] = None
            lost.append(Request(id=s.req.id, prompt=s.req.prompt,
                                max_new_tokens=s.req.max_new_tokens,
                                arrival=s.req.arrival,
                                tenant=s.req.tenant))
        lost.extend(self.queue)
        self.queue.clear()
        for req in lost:
            self._token_listeners.pop(req.id, None)
        event("serving_preempt", lost=len(lost))
        return lost

    # -- resilience: live weight swap, drain, crash recovery ----------------

    def _replay_request(self, seq: _Seq) -> Request:
        """A fresh Request preserving the ORIGINAL identity (id,
        arrival, session, tenant) — resubmission/re-adoption keeps
        the queue-wait accounting and the exactly-once stream keyed
        to the same request."""
        return Request(id=seq.req.id, prompt=seq.req.prompt,
                       max_new_tokens=seq.req.max_new_tokens,
                       arrival=seq.req.arrival,
                       session=seq.req.session,
                       tenant=seq.req.tenant)

    def _preempt_seq(self, seq: _Seq) -> None:
        """Preempt ONE in-flight sequence back to the head of the
        queue (the staleness-bound path): pages freed, slot vacated,
        trace closed honestly. Unlike ``preempt()`` the stream
        listener and the emitted-token high-water mark are KEPT —
        greedy decode regenerates a token-identical prefix, and the
        high-water mark suppresses its re-delivery, so the client
        stream continues exactly once."""
        now = time.monotonic()
        self._emit_trace(seq, "preempted", now,
                         tokens_discarded=len(seq.generated))
        self.cache.free(seq.req.id)
        self.slots[seq.slot] = None
        self.queue.appendleft(self._replay_request(seq))

    def swap_weights(self, params, version: str,
                     provenance: dict | None = None) -> int:
        """Install a new weight set into the RUNNING engine between
        launches — the live hot-swap (ROADMAP item 1's transfer
        primitive). All-or-nothing: every gate below runs BEFORE the
        first byte is installed, and a refusal leaves the engine
        serving the incumbent version untouched.

        Gates, in order: (1) injected ``swap_corrupt`` (a torn
        publish whose artifact no longer verifies), (2) plan
        provenance — the publish must carry the SAME plan name +
        fingerprint the engine's weights were laid out under (the
        WeightStore discipline: new weights under a silently-
        regenerated plan are refused), (3) pytree structure, (4)
        per-leaf shape/dtype, (5) placement — each leaf is
        ``device_put`` onto the incumbent leaf's sharding, so the
        installed tree is layout-identical and every existing jit
        entry is reused (ZERO recompiles; the programs take params as
        a call argument, never close over them).

        After install, in-flight sequences keep decoding — their
        remaining tokens come from the new version and every emitted
        token is version-tagged. With ``cfg.swap_staleness_tokens``
        >= 0, any sequence that has already emitted MORE than that
        many old-version tokens is preempted-and-resubmitted instead
        (regenerating token-identically under the new version, the
        high-water mark deduplicating its stream); the contract: a
        completed request carries at most ``swap_staleness_tokens``
        tokens from a superseded version. Returns the number of
        sequences preempted for staleness."""
        import jax

        from distributed_training_tpu.serving.disagg import (
            ProvenanceError)

        def _refuse(exc: Exception):
            self.swap_stats["refused"] += 1
            event("serving_swap", outcome="refused",
                  version=version, engine_version=self.weights_version,
                  reason=str(exc))
            logger.warning("weight swap to %r REFUSED: %s", version,
                           exc)
            raise exc

        if self.faults is not None and \
                self.faults.on_swap(self.launch_count):
            _refuse(ProvenanceError(
                f"swap to {version!r}: injected swap_corrupt — "
                "published artifact failed verification"))
        if self.weights_provenance is not None:
            if provenance is None:
                _refuse(ProvenanceError(
                    f"swap to {version!r}: engine weights carry plan "
                    f"provenance ({self.weights_provenance.get('name')})"
                    " but the publish carries none"))
            for key in ("name", "fingerprint"):
                if provenance.get(key) != \
                        self.weights_provenance.get(key):
                    _refuse(ProvenanceError(
                        f"swap to {version!r}: plan {key} mismatch — "
                        f"engine {self.weights_provenance.get(key)!r}"
                        f" vs publish {provenance.get(key)!r}"))
        elif provenance is None:
            logger.warning(
                "weight swap to %r: no provenance on either side "
                "(legacy artifact) — accepting on shape/dtype/"
                "placement gates only", version)
        old_leaves, old_def = jax.tree.flatten(self.params)
        new_leaves, new_def = jax.tree.flatten(params)
        if old_def != new_def:
            _refuse(ValueError(
                f"swap to {version!r}: params tree structure "
                f"differs from the serving tree"))
        bad = [i for i, (o, n) in
               enumerate(zip(old_leaves, new_leaves))
               if getattr(o, "shape", None) != getattr(n, "shape",
                                                       None)
               or getattr(o, "dtype", None) != getattr(n, "dtype",
                                                       None)]
        if bad:
            _refuse(ValueError(
                f"swap to {version!r}: {len(bad)} leaf(s) differ in "
                f"shape/dtype (first at flat index {bad[0]})"))
        # Placement: each leaf lands on the incumbent leaf's sharding
        # so every existing jit entry is reused. Leaves already laid
        # out identically (same sharding AND same device-commitment —
        # commitment is part of the jit cache key, so a gratuitous
        # device_put on an uncommitted tree would retrace) pass
        # through untouched.
        def _place(o, n):
            if not hasattr(o, "sharding"):
                return n
            if getattr(n, "sharding", None) == o.sharding and \
                    getattr(n, "committed", None) == \
                    getattr(o, "committed", None):
                return n
            return jax.device_put(n, o.sharding)

        placed = [_place(o, n)
                  for o, n in zip(old_leaves, new_leaves)]
        # Every gate passed: install. The ONE sanctioned rebinding of
        # ``self.params`` outside __init__ (pitfalls rule DTT011).
        self.params = jax.tree.unflatten(old_def, placed)
        self.weights_version = version
        if provenance is not None:
            self.weights_provenance = dict(provenance)
        self.swap_stats["installed"] += 1
        bound = self.cfg.swap_staleness_tokens
        stale = []
        if bound >= 0:
            stale = [s for s in self.slots
                     if s is not None and len(s.generated) > bound]
            for s in stale:
                self._preempt_seq(s)
            self.swap_stats["stale_preempted"] += len(stale)
        event("serving_swap", outcome="installed", version=version,
              stale_preempted=len(stale), in_flight=self.in_flight,
              swaps_installed=self.swap_stats["installed"])
        return len(stale)

    def drain(self, deadline_s: float | None = None) -> dict:
        """Graceful drain: stop admission, run in-flight work to
        completion (or to ``deadline_s``), and report per-request
        outcomes. Queued-but-never-admitted requests stay queued and
        are listed as ``requeued`` (a successor engine submits them
        verbatim); at the deadline, still-in-flight sequences are
        persisted host-side via ``export_in_flight`` and returned
        under ``persisted`` for re-adoption. Retained sessions
        survive by construction — they live in the cache's refcounted
        session table, not in slots. The engine stays ``draining``
        afterwards (flip the flag to reopen admission)."""
        self.draining = True
        t0 = time.monotonic()
        n0 = len(self.completed)
        steps = 0
        while self.in_flight and \
                (deadline_s is None
                 or time.monotonic() - t0 < deadline_s):
            self.step()
            steps += 1
            if steps > 200_000:
                raise RuntimeError(
                    "drain not converging after 200k steps "
                    f"(in_flight={self.in_flight})")
        persisted = self.export_in_flight() if self.in_flight \
            else {"adoptable": [], "requests": []}
        report = {
            "finished": [r["id"] for r in self.completed[n0:]],
            "persisted": ([it[0].id for it in persisted["adoptable"]]
                          + [r.id for r in persisted["requests"]]),
            "requeued": [r.id for r in self.queue],
            "steps": steps,
            "duration_s": time.monotonic() - t0,
            "export": persisted,
        }
        event("serving_drain", deadline_s=deadline_s,
              finished=len(report["finished"]),
              persisted=len(report["persisted"]),
              requeued=len(report["requeued"]),
              steps=steps, duration_s=report["duration_s"])
        return report

    def export_in_flight(self) -> dict:
        """Persist every in-flight sequence host-side and vacate its
        device state (the crash-salvage / drain-deadline path).
        Sequences that have decoded at least one token export their
        EXACT dense KV (one batched ``export_kv_batch`` fetch) plus
        generated history — ``adopt_batch`` items for a successor
        engine, nothing recomputed but the newest token's KV write
        (the decode invariant: ``prompt + generated - 1`` positions
        are resident). Never-decoded sequences (mid-prefill, or
        zero-prefill admissions awaiting their first launch) come
        back as fresh ``Request``s — nothing was emitted, so restart
        costs only their prefill. Traces close as ``preempted`` with
        ``tokens_discarded=0`` for the persisted group (their tokens
        survive). Listeners and high-water marks are NOT touched —
        ``export_emission_state`` carries those."""
        from distributed_training_tpu.serving.disagg import (
            export_kv_batch)

        now = time.monotonic()
        seqs = [s for s in self.slots if s is not None]
        adoptable = [s for s in seqs
                     if s.prefill_done and s.generated]
        adopt_ids = {id(s) for s in adoptable}
        fresh = [s for s in seqs if id(s) not in adopt_ids]
        ks, vs = (export_kv_batch(self.cache,
                                  [s.req.id for s in adoptable])
                  if adoptable else ([], []))
        items = [(self._replay_request(s), list(s.generated), k, v)
                 for s, k, v in zip(adoptable, ks, vs)]
        requests = [self._replay_request(s) for s in fresh]
        for s in seqs:
            discarded = 0 if id(s) in adopt_ids \
                else len(s.generated)
            self._emit_trace(s, "preempted", now,
                             tokens_discarded=discarded)
            self.cache.free(s.req.id)
            self.slots[s.slot] = None
        return {"adoptable": items, "requests": requests}

    def export_emission_state(self) -> dict:
        """Host-side exactly-once stream state for an IN-PROCESS
        successor engine: the per-request emitted-token high-water
        marks plus the live token listeners (callables — same-process
        transfer only, the serving supervisor's restart path)."""
        return {"hwm": dict(self._emit_hwm),
                "listeners": dict(self._token_listeners)}

    def import_emission_state(self, state: dict | None) -> None:
        if not state:
            return
        self._emit_hwm.update(state.get("hwm", {}))
        self._token_listeners.update(state.get("listeners", {}))


# ---------------------------------------------------------------------------
# The compiled programs (pure functions of arrays + static model cfg).
# Each body sees ONE dp group's block: pools (1, L, Hkv, N, ps, hd),
# batch arrays with a leading group dim of 1 — under shard_map that is
# the per-group shard; without a dp mesh it is the whole (only) group.
# ---------------------------------------------------------------------------


def _write_kv(k_pages, v_pages, k_new, v_new, page_ids, offsets):
    """Scatter per-row new KV into the layer's pool.

    k_pages/v_pages (Hkv, N, ps, hd); k_new/v_new (B, Hkv, hd);
    page_ids/offsets (B,) int32 — rows whose write must be dead point
    at the scratch page (id 0). Live rows never share a (page, slot)
    pair (pages are owned by exactly one sequence), so scatter order
    is immaterial; scratch-page collisions write garbage over
    garbage."""
    kT = k_new.transpose(1, 0, 2)          # (Hkv, B, hd)
    vT = v_new.transpose(1, 0, 2)
    k_pages = k_pages.at[:, page_ids, offsets].set(kT)
    v_pages = v_pages.at[:, page_ids, offsets].set(vT)
    return k_pages, v_pages


def _decode_program(params, k_pages, v_pages, tokens, positions,
                    page_tables, active, rng_data, *, cfg,
                    temperature, top_k, paged_impl):
    """One token for one dp group's slot table.

    k_pages/v_pages (1, L, Hkv, N, ps, hd) — the group's pool shard;
    tokens (1, B) int32 — last sampled token per local slot;
    positions (1, B) — the ABSOLUTE position that token occupies
    (== kv entries already written); page_tables (1, B, P); active
    (1, B) bool; rng_data (1, 2) uint32 — the group's folded key.
    Returns (next_tokens (1, B), k_pages, v_pages). Inactive slots
    compute garbage into the scratch page and their sampled token
    is 0.
    """
    import jax
    import jax.numpy as jnp

    from distributed_training_tpu.ops.paged_attention import (
        paged_attention)

    k_pages_g, v_pages_g = k_pages[0], v_pages[0]
    tokens, positions = tokens[0], positions[0]
    page_tables, active = page_tables[0], active[0]
    dt = jnp.dtype(cfg.dtype)
    B = tokens.shape[0]
    ps = k_pages_g.shape[3]
    x = params["tok_embed"][tokens].astype(dt)            # (B, D)
    if cfg.pos_encoding == "learned":
        x = x + params["pos_embed"][positions].astype(dt)
    # Dead writes → scratch page 0, offset 0.
    page_ids = jnp.where(
        active,
        jnp.take_along_axis(page_tables,
                            (positions // ps)[:, None],
                            axis=1)[:, 0],
        0).astype(jnp.int32)
    offsets = jnp.where(active, positions % ps, 0).astype(jnp.int32)
    lengths = jnp.where(active, positions + 1, 0).astype(jnp.int32)
    stacked = {k: params[k] for k in _STACKED}

    def layer_body(x, inp):
        layer, kp, vp = inp
        h = _layer_norm(x, layer["ln1"]["scale"],
                        layer["ln1"]["bias"])
        q = jnp.einsum("bd,dhk->bhk", h,
                       _w(layer["attn"]["wq"], dt))
        k = jnp.einsum("bd,dhk->bhk", h,
                       _w(layer["attn"]["wk"], dt))
        v = jnp.einsum("bd,dhk->bhk", h,
                       _w(layer["attn"]["wv"], dt))
        if cfg.pos_encoding == "rope":
            q = _rope_bhd(q, positions)
            k = _rope_bhd(k, positions)
        kp, vp = _write_kv(kp, vp, k.astype(kp.dtype),
                           v.astype(vp.dtype), page_ids, offsets)
        attn = paged_attention(q, kp, vp, lengths, page_tables,
                               impl=paged_impl)
        x = x + jnp.einsum("bhk,hkd->bd", attn,
                           _w(layer["attn"]["wo"], dt))
        h = _layer_norm(x, layer["ln2"]["scale"],
                        layer["ln2"]["bias"])
        m = layer["mlp"]
        u = jax.nn.gelu(jnp.einsum("bd,df->bf", h,
                                   _w(m["wi"], dt))
                        + m["bi"].astype(dt))
        x = x + (jnp.einsum("bf,fd->bd", u, _w(m["wo"], dt))
                 + m["bo"].astype(dt))
        return x, (kp, vp)

    x, (k_pages_g, v_pages_g) = jax.lax.scan(
        layer_body, x, (stacked, k_pages_g, v_pages_g))
    x = _layer_norm(x, params["final_norm"]["scale"],
                    params["final_norm"]["bias"])
    head = (params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"])
    logits = jnp.einsum("bd,dv->bv", x,
                        head.astype(dt)).astype(jnp.float32)
    if temperature <= 0:
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        lg = logits / temperature
        if top_k:
            kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        keys = jax.random.split(
            jax.random.wrap_key_data(rng_data[0]), B)
        nxt = jax.vmap(jax.random.categorical)(keys, lg).astype(
            jnp.int32)
    return (jnp.where(active, nxt, 0)[None],
            k_pages_g[None], v_pages_g[None])


def _prefill_program(params, k_pages, v_pages, page_row, live,
                     chunk_tokens, start_pos, n_valid, *, cfg, first,
                     paged_impl):
    """One prompt chunk for one sequence, on one dp group's shard.

    k_pages/v_pages (1, L, Hkv, N, ps, hd); page_row (1, P) — the
    sequence's table on its OWNER group, all-scratch elsewhere; live
    (1,) bool — True only on the owner (dead groups' writes land in
    their scratch page and their queries mask out); chunk_tokens
    (1, C) int32 (positions >= n_valid are padding); start_pos — the
    chunk's first absolute position. Writes the chunk's KV into its
    pages and returns (next-token logits (1, V) fp32 — from the LAST
    VALID position, meaningful on the OWNER group when this is the
    prompt's final chunk — k_pages, v_pages).

    ``first=True`` (start_pos == 0, traced as a separate program):
    attention is ordinary causal self-attention over the chunk
    (ops.attention — the flash path on TPU). Continuation chunks
    attend the pages written so far plus themselves via the paged
    chunk form. Both write-then-read the pool identically, so the
    two programs' caches are interchangeable token-for-token.
    """
    import jax
    import jax.numpy as jnp

    from distributed_training_tpu.ops.attention import (
        dot_product_attention)
    from distributed_training_tpu.ops.paged_attention import (
        paged_attention_chunk)

    del paged_impl  # chunk form has no kernel path yet
    k_pages_g, v_pages_g = k_pages[0], v_pages[0]
    page_row, live = page_row[0], live[0]
    dt = jnp.dtype(cfg.dtype)
    C = chunk_tokens.shape[1]
    ps = k_pages_g.shape[3]
    idx = jnp.arange(C, dtype=jnp.int32)
    abs_pos = start_pos + idx                             # (C,)
    valid = (idx < n_valid) & live
    x = params["tok_embed"][chunk_tokens[0]].astype(dt)   # (C, D)
    if cfg.pos_encoding == "learned":
        # Clamp padding positions into range; their rows are dead.
        safe = jnp.minimum(abs_pos, cfg.max_seq_len - 1)
        x = x + params["pos_embed"][safe].astype(dt)
    page_ids = jnp.where(valid, page_row[abs_pos // ps], 0)
    offsets = jnp.where(valid, abs_pos % ps, 0)
    # Padding queries — and every query on a non-live group — mask
    # out of the paged form via negative positions; the causal
    # first-chunk form never lets a valid query see a padding key
    # (pads sit at higher positions) and never reads the pool, so
    # its logits are identical on every group.
    q_pos = jnp.where(valid, abs_pos, -1)[None, :]        # (1, C)
    stacked = {k: params[k] for k in _STACKED}

    def layer_body(x, inp):
        layer, kp, vp = inp
        h = _layer_norm(x, layer["ln1"]["scale"],
                        layer["ln1"]["bias"])
        q = jnp.einsum("cd,dhk->chk", h,
                       _w(layer["attn"]["wq"], dt))
        k = jnp.einsum("cd,dhk->chk", h,
                       _w(layer["attn"]["wk"], dt))
        v = jnp.einsum("cd,dhk->chk", h,
                       _w(layer["attn"]["wv"], dt))
        if cfg.pos_encoding == "rope":
            q = _rope_bhd(q, abs_pos)
            k = _rope_bhd(k, abs_pos)
        kp, vp = _write_kv(kp, vp, k.astype(kp.dtype),
                           v.astype(vp.dtype), page_ids, offsets)
        if first:
            attn = dot_product_attention(
                q[None], k[None], v[None], causal=True,
                impl=cfg.attention_impl
                if cfg.attention_impl in ("auto", "flash", "naive")
                else "auto",
                window=0)[0]
        else:
            attn = paged_attention_chunk(
                q[None], kp, vp, page_row[None], q_pos)[0]
        x = x + jnp.einsum("chk,hkd->cd", attn,
                           _w(layer["attn"]["wo"], dt))
        h = _layer_norm(x, layer["ln2"]["scale"],
                        layer["ln2"]["bias"])
        m = layer["mlp"]
        u = jax.nn.gelu(jnp.einsum("cd,df->cf", h,
                                   _w(m["wi"], dt))
                        + m["bi"].astype(dt))
        x = x + (jnp.einsum("cf,fd->cd", u, _w(m["wo"], dt))
                 + m["bo"].astype(dt))
        return x, (kp, vp)

    x, (k_pages_g, v_pages_g) = jax.lax.scan(
        layer_body, x, (stacked, k_pages_g, v_pages_g))
    x_last = jax.lax.dynamic_index_in_dim(
        x, jnp.maximum(n_valid - 1, 0), axis=0, keepdims=False)
    x_last = _layer_norm(x_last, params["final_norm"]["scale"],
                         params["final_norm"]["bias"])
    head = (params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"])
    logits = jnp.einsum("d,dv->v", x_last,
                        head.astype(dt)).astype(jnp.float32)
    return logits[None], k_pages_g[None], v_pages_g[None]


def _chunk_hidden(params, k_pages_g, v_pages_g, page_rows, tokens,
                  start_pos, n_valid, active, *, cfg):
    """The multi-lane chunk forward SHARED by ``_chunk_program``
    (batched prefill + speculative verification) and
    ``_resident_program`` (every resident loop iteration) — ONE
    implementation, so the device-resident path cannot drift from
    the host-verified chunk math. Operates on one group's UNPACKED
    block (no leading group dim): k_pages_g/v_pages_g
    (L, Hkv, N, ps, hd); page_rows (S, P); tokens (S, C); start_pos,
    n_valid (S,); active (S,) bool. Writes every lane's valid
    tokens' KV through one batched page-row scatter and returns
    ``(x (S, C, D) final hidden states, valid (S, C), k_pages_g,
    v_pages_g)``."""
    import jax
    import jax.numpy as jnp

    from distributed_training_tpu.ops.paged_attention import (
        paged_attention_chunk)

    dt = jnp.dtype(cfg.dtype)
    S, C = tokens.shape
    P = page_rows.shape[1]
    ps = k_pages_g.shape[3]
    idx = jnp.arange(C, dtype=jnp.int32)
    abs_pos = start_pos[:, None] + idx[None, :]           # (S, C)
    valid = (idx[None, :] < n_valid[:, None]) & active[:, None]
    x = params["tok_embed"][tokens].astype(dt)            # (S, C, D)
    if cfg.pos_encoding == "learned":
        safe = jnp.minimum(abs_pos, cfg.max_seq_len - 1)
        x = x + params["pos_embed"][safe].astype(dt)
    # Page coordinates per (lane, position); dead writes → each
    # group's scratch page 0 (page index clamped first: padding
    # positions of a lane near max_seq_len could index past its row).
    logical = jnp.minimum(abs_pos // ps, P - 1)
    page_ids = jnp.where(
        valid, jnp.take_along_axis(page_rows, logical, axis=1), 0)
    offsets = jnp.where(valid, abs_pos % ps, 0)
    q_pos = jnp.where(valid, abs_pos, -1)                 # (S, C)
    stacked = {k: params[k] for k in _STACKED}

    def layer_body(x, inp):
        layer, kp, vp = inp
        h = _layer_norm(x, layer["ln1"]["scale"],
                        layer["ln1"]["bias"])
        q = jnp.einsum("scd,dhk->schk", h,
                       _w(layer["attn"]["wq"], dt))
        k = jnp.einsum("scd,dhk->schk", h,
                       _w(layer["attn"]["wk"], dt))
        v = jnp.einsum("scd,dhk->schk", h,
                       _w(layer["attn"]["wv"], dt))
        if cfg.pos_encoding == "rope":
            q = _rope_bhd(q, abs_pos)
            k = _rope_bhd(k, abs_pos)
        # One batched scatter for the whole lane table: flatten
        # (lane, position) — live coordinates never collide (a page
        # is owned by exactly one sequence and a lane's positions are
        # distinct); scratch collisions write garbage over garbage.
        Hkv, hd = k.shape[2], k.shape[3]
        kp, vp = _write_kv(kp, vp,
                           k.reshape(S * C, Hkv, hd).astype(kp.dtype),
                           v.reshape(S * C, Hkv, hd).astype(vp.dtype),
                           page_ids.reshape(-1), offsets.reshape(-1))
        attn = paged_attention_chunk(q, kp, vp, page_rows, q_pos)
        x = x + jnp.einsum("schk,hkd->scd", attn,
                           _w(layer["attn"]["wo"], dt))
        h = _layer_norm(x, layer["ln2"]["scale"],
                        layer["ln2"]["bias"])
        m = layer["mlp"]
        u = jax.nn.gelu(jnp.einsum("scd,df->scf", h,
                                   _w(m["wi"], dt))
                        + m["bi"].astype(dt))
        x = x + (jnp.einsum("scf,fd->scd", u, _w(m["wo"], dt))
                 + m["bo"].astype(dt))
        return x, (kp, vp)

    x, (k_pages_g, v_pages_g) = jax.lax.scan(
        layer_body, x, (stacked, k_pages_g, v_pages_g))
    return x, valid, k_pages_g, v_pages_g


def _argmax_chain(params, x, valid, cfg):
    """The verification chain over chunk hidden states: the ARGMAX
    after EVERY position (position c's argmax is the verified next
    token given tokens[:c+1]) — greedy only, by the spec/resident
    config contract. Invalid positions emit 0."""
    import jax.numpy as jnp

    dt = jnp.dtype(cfg.dtype)
    xs = _layer_norm(x, params["final_norm"]["scale"],
                     params["final_norm"]["bias"])
    head = (params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"])
    logits = jnp.einsum("scd,dv->scv", xs,
                        _w(head, dt)).astype(jnp.float32)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.where(valid, nxt, 0)


def _chunk_program(params, k_pages, v_pages, page_rows, tokens,
                   start_pos, n_valid, active, rng_data, *, cfg,
                   temperature, top_k, paged_impl, emit):
    """Multi-token chunks for a whole lane table, one dp group.

    The ONE program body behind both batched prefill (``emit="last"``,
    S = prefill lanes, C = prefill_chunk) and speculative multi-token
    decode (``emit="all"``, S = decode slots, C = spec_k) — the math
    is identical: write every lane's C tokens' KV into its pages
    through one batched scatter, then attend each query to its own
    pages at positions <= its own (the paged chunk form — for a
    first chunk that reduces to causal self-attention, for decode it
    verifies the drafted chain exactly as sequential steps would).

    k_pages/v_pages (1, L, Hkv, N, ps, hd) — the group's pool shard;
    page_rows (1, S, P); tokens (1, S, C) int32 (positions >=
    n_valid[s] are padding); start_pos (1, S) — each lane's first
    ABSOLUTE position; n_valid (1, S) — valid tokens per lane;
    active (1, S) bool — dead lanes write to the scratch page and
    their queries mask out via q_pos = -1; rng_data (1, 2).

    Returns ``(next_tokens, k_pages, v_pages)``:

    - ``emit="last"``: next_tokens (1, S) int32 — the SAMPLED token
      after each lane's last valid position (argmax at temperature 0,
      per-lane categorical otherwise) — meaningful when the lane's
      chunk completes its prompt;
    - ``emit="all"``: next_tokens (1, S, C) int32 — the ARGMAX after
      EVERY position (position c's argmax is the verified next token
      given tokens[:c+1]); the host accepts the longest prefix whose
      drafts match the chain. Always greedy (EngineConfig forbids
      spec_k > 1 with temperature > 0).

    Inactive lanes' outputs are 0.
    """
    import jax
    import jax.numpy as jnp

    del paged_impl  # chunk form has no kernel path yet
    k_pages_g, v_pages_g = k_pages[0], v_pages[0]
    page_rows, tokens = page_rows[0], tokens[0]
    start_pos, n_valid, active = start_pos[0], n_valid[0], active[0]
    dt = jnp.dtype(cfg.dtype)
    S = tokens.shape[0]
    x, valid, k_pages_g, v_pages_g = _chunk_hidden(
        params, k_pages_g, v_pages_g, page_rows, tokens,
        start_pos, n_valid, active, cfg=cfg)
    if emit == "all":
        # The verification chain: logits at EVERY position, argmax
        # only (spec decode is greedy by config contract).
        return (_argmax_chain(params, x, valid, cfg)[None],
                k_pages_g[None], v_pages_g[None])
    # emit == "last": each lane's LAST VALID position only — the
    # vocab-sized logits never leave the program.
    head = (params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"])
    last = jnp.maximum(n_valid - 1, 0)[:, None, None]     # (S, 1, 1)
    x_last = jnp.take_along_axis(
        x, jnp.broadcast_to(last, (S, 1, x.shape[-1])), axis=1)[:, 0]
    x_last = _layer_norm(x_last, params["final_norm"]["scale"],
                         params["final_norm"]["bias"])
    logits = jnp.einsum("sd,dv->sv", x_last,
                        _w(head, dt)).astype(jnp.float32)
    if temperature <= 0:
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        lg = logits / temperature
        if top_k:
            kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        keys = jax.random.split(
            jax.random.wrap_key_data(rng_data[0]), S)
        nxt = jax.vmap(jax.random.categorical)(keys, lg).astype(
            jnp.int32)
    return (jnp.where(active, nxt, 0)[None],
            k_pages_g[None], v_pages_g[None])


def _resident_program(params, k_pages, v_pages, page_rows, history,
                      kv_len, budget, active, *, cfg, K, C, ngram,
                      eos_id, paged_impl):
    """Device-resident K-step decode for one dp group's slot table.

    A ``lax.while_loop`` of up to ``K`` iterations; each iteration
    is one ``C``-wide speculative chunk through ``_chunk_hidden`` —
    the SAME forward as the host-driven spec path, so greedy token
    identity holds by construction (drafts only ever change the
    ACCEPTED PREFIX LENGTH, never a token value, so the in-program
    prompt-lookup draft need not match the host-side index). Per
    iteration each running slot drafts from its own history,
    verifies the argmax chain, truncates at EOS, appends accepted
    tokens to its history row and advances its KV cursor — all
    in-program. The loop predicate exits early once every slot has
    stopped (EOS or budget), so an all-slots-complete burst costs
    the iterations it used, not ``K``.

    k_pages/v_pages (1, L, Hkv, N, ps, hd); page_rows (1, B, P);
    history (1, B, Lmax) int32 — prompt + generated so far, with
    ``history[kv_len]`` the last generated token (its KV not yet
    written, exactly the host decode invariant); kv_len (1, B) —
    each slot's committed KV length; budget (1, B) — max tokens this
    burst may emit per slot (the host sized it against page
    capacity: positions written never exceed ``kv_len + budget - 1``
    because ``kv_len + remaining_budget`` is loop-invariant);
    active (1, B) bool.

    Returns ``(out (1, B, K*C) emitted tokens, n_emitted (1, B),
    steps (1,) loop iterations used, k_pages, v_pages)``.
    """
    import jax
    import jax.numpy as jnp

    del paged_impl  # chunk form has no kernel path yet
    kp, vp = k_pages[0], v_pages[0]
    page_rows_g = page_rows[0]
    history_g, kv_len_g = history[0], kv_len[0]
    budget_g, active_g = budget[0], active[0]
    B, Lmax = history_g.shape
    T = K * C
    pos = jnp.arange(Lmax, dtype=jnp.int32)

    def draft_cols(hist, hlen, last):
        """Prompt-lookup drafts (B, C-1): for each slot, the longest
        trailing n-gram (n <= ngram) with an EARLIER occurrence in
        ``hist[:hlen]`` proposes its continuation; slots with no
        match repeat ``last``. Vectorized over every window at once
        (ascending n — the longest match overwrites)."""
        draft = jnp.broadcast_to(last[:, None], (B, C - 1))
        for n in range(1, ngram + 1):
            off = jnp.arange(n, dtype=jnp.int32)
            pat_idx = jnp.clip(hlen[:, None] - n + off[None, :],
                               0, Lmax - 1)
            pat = jnp.take_along_axis(hist, pat_idx, axis=1)
            win_idx = jnp.clip(pos[:, None] + off[None, :],
                               0, Lmax - 1)             # (Lmax, n)
            win = hist[:, win_idx]                      # (B, Lmax, n)
            match = (win == pat[:, None, :]).all(-1)
            # earlier occurrences only: the window's continuation
            # position must land strictly inside history, and the
            # trailing gram itself (start hlen-n) is excluded.
            ok = match & ((pos[None, :] + n) < hlen[:, None])
            has = ok.any(axis=1) & (hlen > n)
            p = jnp.max(jnp.where(ok, pos[None, :], -1), axis=1)
            cont_idx = (p[:, None] + n
                        + jnp.arange(C - 1, dtype=jnp.int32)[None, :])
            cont = jnp.take_along_axis(
                hist, jnp.clip(cont_idx, 0, Lmax - 1), axis=1)
            cont = jnp.where(cont_idx < hlen[:, None], cont,
                             last[:, None])
            draft = jnp.where(has[:, None], cont, draft)
        return draft

    def cond(carry):
        j, _out, _n_em, _kvl, _bud, _hist, running, _kp, _vp = carry
        return (j < K) & running.any()

    def body(carry):
        j, out, n_em, kvl, bud, hist, running, kp, vp = carry
        n = jnp.where(running, jnp.minimum(C, bud), 0).astype(
            jnp.int32)
        last = jnp.take_along_axis(hist, kvl[:, None], axis=1)[:, 0]
        if C > 1:
            tokens = jnp.concatenate(
                [last[:, None], draft_cols(hist, kvl + 1, last)],
                axis=1)
        else:
            tokens = last[:, None]
        x, valid, kp, vp = _chunk_hidden(
            params, kp, vp, page_rows_g, tokens, kvl, n, running,
            cfg=cfg)
        nxt = _argmax_chain(params, x, valid, cfg)      # (B, C)
        if C > 1:
            sl = jnp.arange(C - 1, dtype=jnp.int32)
            match = ((tokens[:, 1:] == nxt[:, :-1])
                     & (sl[None, :] < (n - 1)[:, None]))
            e = 1 + jnp.sum(
                jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        else:
            e = jnp.ones((B,), jnp.int32)
        e = jnp.where(n > 0, e, 0).astype(jnp.int32)
        cl = jnp.arange(C, dtype=jnp.int32)
        if eos_id >= 0:
            is_eos = (nxt == eos_id) & (cl[None, :] < e[:, None])
            any_eos = is_eos.any(axis=1)
            e = jnp.where(
                any_eos,
                jnp.argmax(is_eos, axis=1).astype(jnp.int32) + 1, e)
        else:
            any_eos = jnp.zeros((B,), jnp.bool_)
        # scatter this iteration's accepted tokens into the output
        # block at each slot's emission cursor, and append them to
        # the history row right after its current last token.
        rel = (jnp.arange(T, dtype=jnp.int32)[None, :]
               - n_em[:, None])
        sel = (rel >= 0) & (rel < e[:, None])
        vals = jnp.take_along_axis(nxt, jnp.clip(rel, 0, C - 1),
                                   axis=1)
        out = jnp.where(sel, vals, out)
        hrel = pos[None, :] - (kvl + 1)[:, None]
        hsel = (hrel >= 0) & (hrel < e[:, None])
        hist = jnp.where(
            hsel,
            jnp.take_along_axis(nxt, jnp.clip(hrel, 0, C - 1),
                                axis=1),
            hist)
        n_em = n_em + e
        kvl = kvl + e
        bud = bud - e
        running = running & (bud > 0) & ~any_eos
        return (j + 1, out, n_em, kvl, bud, hist, running, kp, vp)

    init = (jnp.zeros((), jnp.int32),
            jnp.zeros((B, T), jnp.int32),
            jnp.zeros((B,), jnp.int32),
            kv_len_g, budget_g, history_g,
            active_g & (budget_g > 0), kp, vp)
    j, out, n_em, _kvl, _bud, _hist, _run, kp, vp = \
        jax.lax.while_loop(cond, body, init)
    return (out[None], n_em[None], jnp.reshape(j, (1,)),
            kp[None], vp[None])
